"""Engine configuration."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class EngineConfig:
    """Static configuration of one engine worker.

    Shape-affecting knobs (page_size, buckets, max_pages_per_seq) define the
    finite program family XLA compiles; everything dynamic is masked inside
    those shapes (no data-dependent shapes under jit).
    """

    model: str = "llama3-8b"
    #: KV pages on device (page 0 reserved as the null page)
    num_pages: int = 2048
    #: tokens per page == router token-block size (hashes align 1:1)
    page_size: int = 64
    #: max pages a single sequence may hold (=> max context length)
    max_pages_per_seq: int = 64
    #: decode batch buckets (padded up to the next bucket)
    decode_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    #: per-sequence prefill chunk length (a prompt is processed in chunks of
    #: at most this many tokens; also the max prefill T bucket)
    prefill_chunk: int = 512
    #: total prefill tokens per step across sequences (None => 4×chunk).
    #: Pieces of the same length bucket run as ONE batched [B, T] program —
    #: this is what lets many short/medium prompts prefill in one dispatch.
    prefill_token_budget: Optional[int] = None
    #: "fixed" spends at most `effective_prefill_budget` tokens per prefill
    #: step; "adaptive" grows the step budget toward the whole un-prefilled
    #: backlog (capped at `prefill_budget_max`) so an arrival burst drains
    #: in O(1) large dispatches instead of O(backlog) small ones — the
    #: saturation-TTFT cliff (docs/PERF.md: c=64 p50 2,232 ms was backlog
    #: drain at the default budget). An unloaded engine still takes the
    #: small fixed budget, keeping the per-step decode stall short.
    prefill_budget_policy: str = "fixed"
    #: adaptive-policy ceiling (None => 4× the effective budget). Bounds
    #: the worst-case single prefill dispatch, which is exactly the
    #: longest decode stall (ITL spike) a running sequence can observe.
    prefill_budget_max: Optional[int] = None
    #: max sequences resident (decode slots)
    max_seqs: int = 64
    #: decode steps fused per dispatch (lax.scan with on-device token
    #: feedback): one host⇄device sync per `decode_steps` tokens/seq. With
    #: a remote/tunneled TPU the sync round-trip dominates a decode step,
    #: so K steps per sync multiplies decode throughput by ~K. Finish
    #: conditions are applied on the host afterwards — up to K-1 speculative
    #: tokens past a stop are computed and dropped. 1 = classic stepping.
    decode_steps: int = 8
    #: on-device K-step decode windows (ROADMAP item 2a, the host-loop
    #: elimination lever): run K decode iterations inside ONE XLA program
    #: with per-iteration on-device sampling, on-device stop-condition
    #: masks (eos/stop-token/max_tokens freeze finished rows mid-window;
    #: frozen rows waste only masked lanes), and on-device paged KV
    #: writes + position advances — the host reads back [K, B] ids plus
    #: per-row emitted counts once per window instead of deciding every
    #: step. Differs from decode_steps (decode_multi) in that finish
    #: conditions are evaluated ON DEVICE, so no overshoot tokens are
    #: computed past a stop, and the scheduler reserves the whole
    #: window's page runway up front (or clamps the window). Composes
    #: with overlap_decode (the next window chains speculatively off
    #: device outputs) and mixed_steps (the window runs as the decode
    #: leg beside the prefill chunk). Auto-disabled, with a logged
    #: reason, for spec_ngram/spec_draft (they already batch steps),
    #: logprobs rows, and oversized stop sets. Runs on multi-process
    #: SPMD meshes too: window outcomes are replicated on-device, so
    #: every lockstep host reads back identical [K, B] ids and emit
    #: counts. 1 (default) = off: the classic path, bit-identical.
    #: Token streams at K>1 are bit-exact vs K=1 (greedy AND sampled —
    #: pinned by tests/test_engine_kstep.py). `--decode-kstep` on the
    #: CLI (vLLM `--num-scheduler-steps` analogue, docs/migrating.md).
    decode_kstep: int = 1
    #: overlapped decode loop: after dispatching decode step N, dispatch
    #: step N+1 speculatively (same batch, +1 round, sampled ids fed back
    #: on device) and read step N's ids back one step lagged via an async
    #: copy — host postprocessing and array staging hide under device
    #: compute. Rolled back (overshoot discarded, like decode_multi's
    #: post-stop tokens) when a finish/preemption/abort/admitted prefill
    #: changes the batch. Runs on multi-process SPMD meshes: decode ids
    #: are replicated on-device, the rollback decision is a pure
    #: function of the (broadcast) event log, so every lockstep host
    #: overlaps and rolls back identically — the lagged readback is the
    #: ONLY per-window host sync. Forced off when spec_ngram > 0
    #: (prompt-lookup drafts need host tokens). Token streams are
    #: bit-identical to the synchronous path (pinned by
    #: tests/test_engine_overlap.py and test_engine_multihost.py).
    overlap_decode: bool = True
    #: stall-free mixed prefill+decode steps (Sarathi-style piggybacking):
    #: when both a prefill backlog and running decodes exist, the
    #: scheduler emits ONE `mixed` step carrying a bounded prefill chunk
    #: plus the current decode batch, and the engine dispatches both as a
    #: single XLA program — decode rows emit a token every step even while
    #: a prompt burst drains, collapsing the burst-drain ITL tail the
    #: XOR (prefill-priority) policy pays (docs/PERF.md saturation
    #: section, lever 4). Greedy token streams are bit-exact vs the XOR
    #: scheduler (same kernels, same per-request order — pinned by
    #: tests/test_engine_mixed.py). Runs on multi-process SPMD meshes
    #: (the mixed/XOR choice is a deterministic function of the
    #: replicated scheduler state, so lockstep replicas agree). Forced
    #: off when spec_ngram > 0 (the verify program owns the decode
    #: batch).
    mixed_steps: bool = True
    #: speculative decoding by prompt lookup (draft-free n-gram
    #: speculation): propose this many draft tokens per decode step from
    #: the last occurrence of the sequence's trailing n-gram, verify all
    #: of them in ONE forward pass, accept the longest matching prefix
    #: plus the model's own token at the first mismatch. 0 = off. Greedy
    #: requests only; mixed batches with sampling/logprob/penalty
    #: requests fall back to the normal decode path for that step.
    spec_ngram: int = 0
    #: trailing n-gram length the lookup matches on
    spec_ngram_match: int = 2
    #: draft-model speculative decoding: a SECOND (small) model from the
    #: same registry family proposes spec_draft_tokens greedy drafts per
    #: decode step, and one fused program runs draft catch-up + proposal
    #: + target verify + ON-DEVICE acceptance (bit-exact greedy; exact
    #: rejection sampling for temperature>0 — accept draft x with prob
    #: min(1, p_target(x)/q(x)) where q is the deterministic draft's
    #: point mass, resample the residual otherwise, which preserves the
    #: target sampling distribution exactly). Unlike spec_ngram, the
    #: draft path COMPOSES with overlap_decode (the next spec dispatch
    #: chains off the previous one's on-device outputs) and mixed_steps
    #: (the verify program runs as the decode leg beside the prefill
    #: chunk). None = off. The draft must share the target's vocabulary
    #: (same tokenizer family); `--spec-draft` on the CLI.
    spec_draft_model: Optional[str] = None
    #: drafts proposed (and verified) per spec step; the fused program's
    #: verify window is spec_draft_tokens+1 wide
    spec_draft_tokens: int = 4
    #: checkpoint dir for the draft weights (None = the draft adapter's
    #: default checkpoint, else random init — random drafts accept at
    #: chance and immediately hit the acceptance cooldown)
    spec_draft_checkpoint: Optional[str] = None
    #: adaptive fallback: when a spec step's draft acceptance rate drops
    #: below this, decode reverts to the fused multi-step path for
    #: spec_cooldown_steps before probing speculation again (lookup-miss
    #: workloads must not pay s+1-wide verifies per single token)
    spec_min_accept_rate: float = 0.2
    spec_cooldown_steps: int = 16
    #: admission watermark: keep this fraction of pages free when admitting
    admission_watermark: float = 0.02
    #: bounded admission (docs/operations.md "Overload & draining"):
    #: cap on the scheduler's WAITING queue. None (default) keeps the
    #: historical unbounded queue; with a cap, add_request raises
    #: QueueFullError once `max_waiting` requests are already queued —
    #: the worker answers "overloaded" (HTTP 429 + Retry-After at the
    #: frontend) instead of queueing a request it cannot serve within
    #: any reasonable deadline. `--max-waiting` on the CLI.
    max_waiting: Optional[int] = None
    #: eos token ids (from the model card/tokenizer)
    eos_token_ids: tuple[int, ...] = ()
    #: dtype name for params/KV ("bfloat16" | "float32")
    dtype: str = "bfloat16"
    #: weight-only quantization: None | "int8" (per-output-channel scales;
    #: halves the HBM weight traffic decode is bound by)
    quantize: Optional[str] = None
    #: KV-cache page quantization: None | "int8" | "fp8". Pages store the
    #: narrow dtype with per-(page, slot, kv-head) f32 scale planes;
    #: dequant is folded into the Pallas page-walk kernels (and the XLA
    #: gather fallback), halving KV HBM traffic in the history-dominated
    #: decode regime and ~doubling effective cache capacity. "fp8" needs
    #: a jax with float8_e4m3fn. Not supported for MLA (shared-latent
    #: cache) models.
    kv_quantize: Optional[str] = None
    #: decode attention: "auto" (pallas on TPU single-chip, else xla),
    #: "xla", "pallas", or "hybrid" (pallas kernels with decode falling
    #: back to the XLA gather past LlamaConfig.pallas_decode_max_batch)
    attention_impl: str = "auto"
    #: mesh layout
    dp: int = 1
    tp: int = 1
    #: sequence/context parallel: long first-chunk prefills run ring
    #: attention over this many devices (parallel/context.py)
    sp: int = 1
    #: expert parallel: MoE experts shard over this many devices (dense
    #: models ignore it)
    ep: int = 1
    #: combined topology knob: "tp=N,dp=M[,ep=K][,sp=J]" (the
    #: vLLM-style `--topology` flag; docs/migrating.md). Parsed in
    #: __post_init__ and OVERRIDES the individual dp/tp/sp/ep fields;
    #: unnamed axes keep their defaults. The product must match the
    #: devices the mesh is built over (make_mesh validates). "" = use
    #: the individual fields.
    topology: str = ""
    #: test/bench knob: treat a single-process mesh as multi-host —
    #: the engine takes the multi-controller SPMD code paths
    #: (addressable-shard readbacks, replicated decode outputs,
    #: lockstep-safe scheduling) without a real fabric. Lets CPU tests
    #: and bench.py exercise the cross-host decode pipeline
    #: deterministically. No effect on real multi-process meshes
    #: (already multi-host).
    force_multihost: bool = False
    #: random seed for sampling
    seed: int = 0
    #: enable content-addressed prefix caching
    enable_prefix_caching: bool = True
    #: live fleet telemetry (docs/observability.md "Fleet view & SLO
    #: accounting"): per-request TTFT/ITL/e2e quantile sketches + SLA
    #: counters on the engine, the live MFU gauge, and the worker's
    #: fleet-frame publishing. Host-side metrics only — the token path
    #: is identical either way; off (`--no-fleet-telemetry`) skips the
    #: bookkeeping entirely (bench.py `slo_overhead` prices it <1%).
    fleet_telemetry: bool = True
    #: flight recorder (docs/observability.md "Debugging a slow or stuck
    #: worker"): an always-on bounded ring of per-step records — batch
    #: kind/buckets, page-pool deltas, dispatch/sync/host ms, overlap
    #: hits/rollbacks, compile events, queue depths — served at
    #: GET /v1/debug/flight and shipped in the worker's metrics frames.
    #: Host-side only; off (`--no-flight-recorder`) is bit-identical on
    #: the token path (bench.py `flight_overhead` prices it <1%).
    flight_recorder: bool = True
    #: flight ring capacity (records, one per engine step)
    flight_ring: int = 512
    #: stall watchdog (telemetry/watchdog.py): per-request progress
    #: monitor diagnosing wedged streams (structured JSONL diagnosis +
    #: dynamo_tpu_stalls_total{cause}); runs on the worker event loop
    stall_watchdog: bool = True
    #: a stream is "stalled" after stall_factor × the live ITL-p95
    #: estimate with no emission, floored at stall_min_s (first compiles
    #: legitimately take seconds)
    stall_factor: float = 32.0
    stall_min_s: float = 5.0
    #: admission-wait budget: a request with NO first emission after
    #: this many seconds is diagnosed as cause="queue_wait"
    stall_queue_wait_s: float = 120.0
    #: None (default) = diagnose-only. A number hard-finishes streams
    #: stalled past it with an error frame instead of hanging the
    #: client (`--stall-hard-deadline`)
    stall_hard_deadline_s: Optional[float] = None
    #: KVBM tiering (dynamo_tpu/kvbm): host-DRAM tier byte budget (0 = off)
    host_kv_cache_bytes: int = 0
    #: disk tier byte budget (0 = off; needs disk_kv_cache_dir)
    disk_kv_cache_bytes: int = 0
    #: directory for the disk tier's block files
    disk_kv_cache_dir: Optional[str] = None

    def __post_init__(self):
        if self.topology:
            # Parse before the sp validation below so a topology-set sp
            # goes through the same checks as an explicitly-set one.
            from dynamo_tpu.parallel.mesh import parse_topology

            for axis, n in parse_topology(self.topology).items():
                object.__setattr__(self, axis, n)
        if self.prefill_chunk % self.page_size != 0:
            raise ValueError(
                f"prefill_chunk ({self.prefill_chunk}) must be a multiple of "
                f"page_size ({self.page_size}) — chunks start page-aligned "
                "so the KV write path can land whole-page DMA runs"
            )
        if self.sp > 1 and (32 % self.sp != 0 or self.prefill_chunk % self.sp):
            # Prefill T buckets are powers of two from 32 up to
            # prefill_chunk; sp must divide every one of them or the ring
            # path silently never engages.
            raise ValueError(
                f"sp ({self.sp}) must be a power of two <= 32 that divides "
                f"prefill_chunk ({self.prefill_chunk}) — prefill length "
                "buckets must shard evenly over the sequence-parallel axis"
            )
        if (
            self.prefill_token_budget is not None
            and self.prefill_token_budget < self.page_size
        ):
            raise ValueError(
                f"prefill_token_budget ({self.prefill_token_budget}) must be "
                f">= page_size ({self.page_size}): mid-prompt chunks round "
                "down to page boundaries, so a smaller budget could never "
                "schedule any prefill work"
            )
        if self.kv_quantize not in (None, "int8", "fp8"):
            raise ValueError(
                f"kv_quantize must be None, 'int8' or 'fp8', got "
                f"{self.kv_quantize!r}"
            )
        if self.spec_draft_model is not None and self.spec_ngram > 0:
            raise ValueError(
                "spec_draft_model and spec_ngram are mutually exclusive "
                "speculation modes — configure one of them"
            )
        if self.spec_draft_model is not None and self.spec_draft_tokens < 1:
            raise ValueError(
                f"spec_draft_tokens must be >= 1, got "
                f"{self.spec_draft_tokens}"
            )
        if self.decode_kstep < 1:
            raise ValueError(
                f"decode_kstep must be >= 1, got {self.decode_kstep} "
                "(1 = classic stepping; K>1 fuses K on-device iterations "
                "per dispatch)"
            )
        if self.prefill_budget_policy not in ("fixed", "adaptive"):
            raise ValueError(
                "prefill_budget_policy must be 'fixed' or 'adaptive', got "
                f"{self.prefill_budget_policy!r}"
            )
        if (
            self.prefill_budget_max is not None
            and self.prefill_budget_max < self.effective_prefill_budget
        ):
            raise ValueError(
                f"prefill_budget_max ({self.prefill_budget_max}) must be >= "
                f"the effective budget ({self.effective_prefill_budget}) — "
                "adaptive only ever grows the step budget"
            )

    @property
    def max_context(self) -> int:
        return self.max_pages_per_seq * self.page_size

    @property
    def effective_prefill_budget(self) -> int:
        return self.prefill_token_budget or 4 * self.prefill_chunk

    @property
    def effective_prefill_budget_max(self) -> int:
        """Adaptive-policy ceiling (the single source of the 4× default)."""
        return self.prefill_budget_max or 4 * self.effective_prefill_budget

    def decode_bucket_for(self, n: int) -> int:
        for b in self.decode_buckets:
            if n <= b:
                return b
        return self.decode_buckets[-1]

    @staticmethod
    def for_tests(**overrides) -> "EngineConfig":
        defaults = dict(
            model="tiny",
            num_pages=64,
            page_size=4,
            max_pages_per_seq=8,
            decode_buckets=(1, 2, 4, 8),
            prefill_chunk=16,
            max_seqs=8,
            dtype="float32",
        )
        defaults.update(overrides)
        return EngineConfig(**defaults)

"""JaxEngine: the TPU-native inference engine.

Owns the model params, the device page pool, the host-side allocator and
continuous-batching scheduler, and a small cache of jitted step programs
(one per (kind, bucket) shape). This is the first-class engine the reference
lacks natively (it shells out to vLLM/SGLang/TRT-LLM — SURVEY.md L4);
tokens-in/tokens-out, KV events and worker metrics out.

Execution model per `step()`:
  scheduler -> ScheduledBatch -> pad to bucket -> jitted forward+sample ->
  host sync of sampled ids -> append/finish bookkeeping + page registration.

Overlapped decode (config.overlap_decode, docs/engine.md "The decode
loop"): after dispatching decode step N, the engine speculatively
dispatches step N+1 — same batch, +1 round, sampled ids fed back as a
device array — starts an async host copy of step N's ids, and only then
postprocesses step N. The device therefore computes N+1 while the host
scans N for stops and the next `step()` reads back a one-step-lagged,
already-copied result. The speculation is validated against the next
scheduled batch and rolled back (overshoot discarded, exactly like
decode_multi's post-stop tokens) when a finish, preemption, abort, or a
newly admitted prefill changes the batch.

Multi-chip: pass a MeshConfig; params/KV are device_put with tp/dp
PartitionSpecs and the same jitted programs run SPMD over the mesh.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import KvEvent, PageAllocator
from dynamo_tpu.engine.request import (
    FinishReason,
    Request,
    RequestState,
    SamplingParams,
    StepOutput,
)
from dynamo_tpu.engine.sampling import sample, sample_greedy
from dynamo_tpu.engine.scheduler import ScheduledBatch, Scheduler
from dynamo_tpu.models.registry import ModelAdapter, get_model
from dynamo_tpu.parallel.logical import default_rules
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.parallel.shardings import batch_spec, shardings_for
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


def _canonical_gather(kv, ids, dk: int, dv: int):
    """Pool layout [L, P, S, Hkv, Dpad] -> canonical wire layout
    [L, Hkv, n, S, D] (padding stripped). THE one definition of the
    extract layout — single-process async extract and the multi-host
    sharded extract both trace this, so they can never diverge.

    Quantized pools pack each row's f32 scale into 4 trailing int8
    lanes: wire width becomes D+4 and the array stays ONE narrow-dtype
    tensor, so every downstream plane (KVBM host/disk tiers, disagg
    shm/bulk-TCP/device transfer, G4 serve/adopt) ships quantized bytes
    + scales at half the fp traffic without knowing about quantization
    — byte accounting (np.nbytes) is automatically honest."""
    k = jnp.take(kv.k, ids, axis=1).transpose(0, 3, 1, 2, 4)[..., :dk]
    v = jnp.take(kv.v, ids, axis=1).transpose(0, 3, 1, 2, 4)[..., :dv]
    if kv.k_scale is not None:
        bits = lambda x: jax.lax.bitcast_convert_type(x, jnp.int8)
        ks = jnp.take(kv.k_scale, ids, axis=1).transpose(0, 3, 1, 2)
        vs = jnp.take(kv.v_scale, ids, axis=1).transpose(0, 3, 1, 2)
        # fp8 payloads bitcast to int8 so payload+scale share one dtype
        k = jnp.concatenate([bits(k), bits(ks)], axis=-1)
        v = jnp.concatenate([bits(v), bits(vs)], axis=-1)
    return k, v


def _wire_unpack(arr, d_true: int, pool_dtype):
    """Canonical QUANTIZED wire array [..., D+4] int8 ->
    (payload [..., D] pool dtype, scale [...] f32): inverse of
    _canonical_gather's scale packing."""
    payload = jax.lax.bitcast_convert_type(arr[..., :d_true], pool_dtype)
    scale = jax.lax.bitcast_convert_type(
        arr[..., d_true : d_true + 4], jnp.float32
    )
    return payload, scale


@dataclass
class EngineMetrics:
    """Worker load snapshot published to routers/planner (parity with the
    reference's ForwardPassMetrics — kv_router/protocols.rs:43-69)."""

    num_waiting: int = 0
    num_running: int = 0
    kv_active_pages: int = 0
    kv_free_pages: int = 0
    kv_total_pages: int = 0
    kv_usage: float = 0.0
    #: device bytes the KV pool actually occupies (quantized pages +
    #: scale planes) vs the model-dtype equivalent — their ratio is the
    #: effective cache-capacity multiplier kv_quantize buys
    kv_pool_bytes: int = 0
    kv_pool_bytes_dense_equiv: int = 0
    prefix_hit_rate: float = 0.0
    steps: int = 0
    generated_tokens: int = 0
    #: monotonically increasing arrivals (planner derives request_rate)
    requests_received: int = 0
    #: speculative decoding (prompt lookup) — parity with the reference's
    #: SpecDecodeStats (kv_router/protocols.rs:96)
    spec_drafted: int = 0
    spec_accepted: int = 0
    #: why speculation DIDN'T run, by decode dispatch (observability:
    #: "ineligible" = a sampling/logprob/penalty request in the batch
    #: disables speculation batch-wide; "cooldown" = acceptance fell
    #: below spec_min_accept_rate and the engine is backing off)
    spec_skipped_ineligible: int = 0
    spec_skipped_cooldown: int = 0
    #: live draft-acceptance rate over a ~60 s window of spec steps
    #: (accepted/drafted; 0.0 when speculation is idle) — the lever the
    #: effective tok/s multiplier (1 + rate*S) rides on, exported as the
    #: dynamo_tpu_*_spec_accept_rate gauge on both Prometheus surfaces
    spec_accept_rate: float = 0.0
    #: drafts inside that same window — the rate's denominator/weight,
    #: shipped so aggregators can (a) tell an actively-FAILING draft
    #: (rate 0, window_drafted > 0) from an idle one and (b) compute the
    #: true windowed fleet ratio as a drafted-weighted mean instead of a
    #: lifetime ratio that never moves again
    spec_window_drafted: int = 0
    #: step-phase wall time, cumulative ms (host-loop observability:
    #: time_*_ms − the profiler's pure program time = host overhead,
    #: see scripts/tpu_decode_profile.py / docs/PERF.md). schedule
    #: covers admission + batch packing; prefill/decode cover host
    #: array build + dispatch + device sync + postprocess.
    time_schedule_ms: float = 0.0
    time_prefill_ms: float = 0.0
    time_decode_ms: float = 0.0
    #: mixed prefill+decode steps (config.mixed_steps): wall time and
    #: dispatch count of steps that carried BOTH a prefill chunk and the
    #: decode batch — the stall-free path; decode rows emitted a token
    #: on every one of these instead of waiting out the prefill
    time_mixed_ms: float = 0.0
    #: decode's phase split: dispatch = host array build + program
    #: launch (incl. any speculative next-step launch), sync = blocking
    #: on the sampled ids' device→host copy, host = the stop/finish
    #: scan + page registration. The columns follow the DECODE ROWS
    #: wherever they run: pure decode steps (where they sum to
    #: ~time_decode_ms) and the decode half of mixed steps (whose step
    #: wall time lands in time_mixed_ms instead). Under overlap_decode
    #: the sync column collapses (the copy was started a step earlier)
    #: — the overlap's visibility in bench.py extras.
    time_decode_dispatch_ms: float = 0.0
    time_decode_sync_ms: float = 0.0
    time_decode_host_ms: float = 0.0
    #: program-launch counters. A mixed step normally launches ONE fused
    #: program (mixed_dispatches); its overlap split path launches the
    #: pure prefill program beside the consumed speculation, which also
    #: counts here as a prefill dispatch.
    prefill_dispatches: int = 0
    decode_dispatches: int = 0
    mixed_dispatches: int = 0
    #: overlapped decode pipeline: speculative next-step dispatches
    #: issued / consumed as the real step / rolled back (overshoot
    #: discarded because the batch changed underneath them)
    overlap_dispatches: int = 0
    overlap_hits: int = 0
    overlap_rollbacks: int = 0
    #: on-device K-step decode windows (EngineConfig.decode_kstep):
    #: windows dispatched (speculatively-chained ones included), device
    #: iterations run inside them (steps/windows = the average fused K),
    #: the most recent window's size (gauge), and dispatches where a
    #: configured K>1 window fell back to the classic path (logprobs
    #: rows or an oversized stop set in the batch)
    kstep_windows: int = 0
    kstep_steps: int = 0
    kstep_window_size: int = 0
    kstep_fallbacks: int = 0
    #: cumulative wall ms of K-step window dispatch+sync — with
    #: kstep_windows it is the decode_kstep program family's measured
    #: ms/dispatch column in /v1/debug/programs attainment
    time_kstep_ms: float = 0.0
    #: engine-internals plane (fleet telemetry, docs/observability.md):
    #: jit-cache misses (one full XLA compile each) and their cumulative
    #: wall cost — climbing in steady state means the program family is
    #: churning (the compile hazard the 3-axis mixed family introduced)
    compiles: int = 0
    compile_ms: float = 0.0
    #: page-pool pressure: the high-watermark of active pages since boot
    #: and the scheduler's preemption-by-recompute count — preemptions
    #: climbing while the watermark pins at capacity is the "pool too
    #: small for this workload" signal
    kv_pages_watermark: int = 0
    preemptions: int = 0
    #: live utilization over a sliding window (~10 s): token throughput
    #: and the model-FLOPs utilization it implies against the chip's
    #: roofline peak (2*active-params FLOPs/token / device_peak_flops —
    #: same arithmetic as bench.py's headline MFU; docs/PERF.md maps it
    #: to the measured decode roofline ceiling of ~0.43)
    tokens_per_s: float = 0.0
    mfu: float = 0.0
    #: overload-protection plane (docs/operations.md "Overload &
    #: draining"): requests refused at admission because the bounded
    #: waiting queue (EngineConfig.max_waiting) was full — climbing
    #: means this worker is shedding (raise capacity), while a deep
    #: num_waiting with ZERO rejects means the queue is unbounded
    overload_rejects: int = 0
    #: requests error-finished because their end-to-end deadline passed
    #: (pre-admission drops + mid-decode expiries)
    deadline_expired: int = 0
    #: HBM accounting plane (GET /v1/debug/memory — docs/observability.md
    #: "Reading the perf plane"): byte rollups summed over this process's
    #: addressable devices. weights = the param trees' shard bytes,
    #: kv_pool = the paged KV pool (mirrors kv_pool_bytes but lives in
    #: the hbm_* family the plane exposes), scratch = the largest
    #: compiled program's cost_analysis bytes beyond resident weights+KV
    #: (a transient-buffer ESTIMATE, documented in memory_report), free/
    #: peak from jax device memory_stats on TPU with the accounted CPU
    #: fallback. Refreshed by refresh_memory_metrics() on the publish
    #: cadence — the token path never touches them.
    hbm_weights_bytes: int = 0
    hbm_kv_pool_bytes: int = 0
    hbm_scratch_bytes: int = 0
    hbm_free_bytes: int = 0
    hbm_peak_bytes: int = 0
    #: mesh introspection plane (GET /v1/debug/mesh): this replica's
    #: process index under multi-host SPMD (0 single-host) and the
    #: recent-window decode dispatch p95 — the per-host straggler gauge
    #: the doctor's host-skew rule compares across hosts
    host: int = 0
    dispatch_p95_ms: float = 0.0

    #: the timing plane's field names — the one list consumers (perf
    #: harness, dashboards) should iterate instead of restating
    TIMING_FIELDS = (
        "time_schedule_ms", "time_prefill_ms", "time_decode_ms",
        "time_mixed_ms",
        "time_decode_dispatch_ms", "time_decode_sync_ms",
        "time_decode_host_ms",
        "prefill_dispatches", "decode_dispatches", "mixed_dispatches",
        "overlap_dispatches", "overlap_hits", "overlap_rollbacks",
        "kstep_windows", "kstep_steps", "time_kstep_ms",
    )

    def to_dict(self) -> dict:
        return dict(self.__dict__)


@dataclass
class _InflightDecode:
    """One speculatively dispatched decode step whose sampled ids are
    still on device (async host copy already started). It becomes the
    real step iff the next scheduled batch is the same decode batch and
    every request advanced exactly the pending step's token count;
    otherwise it is rolled back (the ids are overshoot, and the KV it
    wrote sits past every live sequence's length or in freed pages that
    later writers fully overwrite before any read)."""

    reqs: tuple
    b_bucket: int
    k_steps: int
    token_ids: object  # device array, [B] (k=1) or [K, B]
    lp_data: Optional[tuple]  # device (chosen, top_ids, top_lps) or None
    #: per-request state the batch must show when this step is consumed
    expected_num_tokens: tuple
    expected_out_len: tuple
    #: program-variant flags at dispatch (same reqs => same flags; kept
    #: so the next speculation reuses them without recomputation)
    greedy: bool = False
    lp: int = -1
    bias: bool = False
    #: dispatched through the decode_kstep program family (on-device
    #: stop masks); the chained re-speculation stays in the family
    kstep: bool = False


@dataclass
class _InflightSpec:
    """One speculatively chained spec-fused dispatch (draft-model
    speculation composing with the overlap pipeline): its catch-up
    window is the PREVIOUS spec dispatch's on-device outputs — out_ids
    masked by n_acc feed the next draft+verify program with no host
    round-trip between spec steps. It becomes the real step iff the
    host's acceptance scan of the previous step agreed with the
    device's n_acc on every row (no finish/stop truncation — the device
    cannot see those) and the decode batch is unchanged; otherwise it
    rolls back exactly like _InflightDecode."""

    reqs: tuple
    b_bucket: int
    out_ids: object  # device [B, S+1]
    draft_ids: object  # device [B, S]
    n_acc: object  # device [B] i32
    counters_v0: object  # [B] verify-start draw counters (device or host)
    greedy: bool = False
    bias: bool = False
    #: filled by the previous step's postprocess once the host confirms
    #: the device acceptance; None means "not yet validated" and the
    #: speculation can never be consumed
    expected_num_tokens: Optional[tuple] = None
    expected_out_len: Optional[tuple] = None


class JaxEngine:
    def __init__(
        self,
        config: EngineConfig,
        params=None,
        mesh_config: Optional[MeshConfig] = None,
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
        checkpoint_path: Optional[str] = None,
        on_tier_event=None,
    ):
        from dynamo_tpu.platform import enable_persistent_compile_cache

        enable_persistent_compile_cache()
        self.config = config
        mc = mesh_config or MeshConfig(
            dp=config.dp, tp=config.tp, sp=config.sp, ep=config.ep
        )
        impl = config.attention_impl
        if impl not in ("auto", "xla", "pallas", "hybrid"):
            raise ValueError(
                f"unknown attention_impl {impl!r}; use auto|xla|pallas|hybrid"
            )
        if impl == "auto":
            impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        self.mesh = make_mesh(mc) if mc.num_devices > 1 else None
        #: mesh spans >1 process: multi-controller lockstep mode. Host
        #: batch arrays become global arrays assembled per-host from the
        #: (identical) replicated numpy copies; small jit outputs are
        #: replicated so every host reads every sampled token
        #: (engine/spmd.py keeps the hosts' schedulers in lockstep).
        self._multiproc = self.mesh is not None and (
            len({d.process_index for d in self.mesh.devices.flat}) > 1
            or config.force_multihost
        )
        self._batched_put_ok = True
        if self._multiproc:
            from jax.sharding import NamedSharding, PartitionSpec

            self._rep_sharding = NamedSharding(self.mesh, PartitionSpec())
        else:
            self._rep_sharding = None
        # Under a mesh the Pallas kernels run shard_mapped over tp (heads
        # are embarrassingly parallel); the model needs the mesh object.
        self.adapter: ModelAdapter = get_model(
            config.model, dtype=config.dtype, attention_impl=impl,
            mesh=self.mesh,
        )
        if mc.tp > 1:
            acfg = self.adapter.config
            if not hasattr(acfg, "num_heads"):
                acfg = acfg.base
            # MLA's shared-latent cache replicates over tp (the q heads
            # still shard) — only head-sharded caches need kv divisibility.
            kv_ok = (
                getattr(acfg, "mqa_latent_cache", False)
                or acfg.num_kv_heads % mc.tp == 0
            )
            if acfg.num_heads % mc.tp or not kv_ok:
                raise ValueError(
                    f"tp={mc.tp} must divide num_heads ({acfg.num_heads}) "
                    f"and num_kv_heads ({acfg.num_kv_heads}) for "
                    "head-sharded attention"
                )
        if config.host_kv_cache_bytes > 0 or config.disk_kv_cache_bytes > 0:
            from dynamo_tpu.kvbm import TieredPageAllocator

            # Cross-host meshes tier PER-HOST SHARDS: every replica runs
            # the same (lockstep-deterministic) tier decisions, extract
            # hands each host its own Hkv slice, inject reassembles the
            # global array from the local slices — so G2/G3 capacity
            # scales with hosts and no host ever addresses a remote
            # shard. The async double-buffered extract stays
            # single-process (its staged arrays materialize via
            # np.asarray, which a multi-host global array refuses).
            disk_dir = config.disk_kv_cache_dir
            if self._multiproc and disk_dir:
                # disk entries are keyed by seq_hash alone; co-located
                # processes sharing one dir would overwrite each other's
                # per-host slices (same shapes, silently wrong heads)
                disk_dir = os.path.join(
                    disk_dir, f"host{jax.process_index()}"
                )
            self.allocator: PageAllocator = TieredPageAllocator(
                config.num_pages,
                config.page_size,
                extract_fn=self.extract_pages,
                extract_async_fn=(
                    None if self._multiproc else self.extract_pages_async
                ),
                inject_fn=self.inject_pages,
                host_bytes=config.host_kv_cache_bytes,
                disk_bytes=config.disk_kv_cache_bytes,
                disk_dir=disk_dir,
                on_event=on_kv_event,
                on_tier_event=on_tier_event,
            )
        else:
            self.allocator = PageAllocator(
                config.num_pages, config.page_size, on_event=on_kv_event
            )
        self.scheduler = Scheduler(config, self.allocator)
        self.metrics = EngineMetrics(kv_total_pages=config.num_pages - 1)
        #: mid-decode deadline expiries, bumped by the runner (its abort
        #: path) — folded with the scheduler's pre-admission drops into
        #: metrics.deadline_expired
        self._runner_deadline_expired = 0
        self._jit_cache: dict[tuple, Callable] = {}
        #: compile counter by program kind (prefill/decode/mixed/...) —
        #: published in the worker's fleet frame as per-kind labels
        self.compiles_by_kind: dict[str, int] = {}
        #: per-program cost table (docs/observability.md "Debugging a
        #: slow or stuck worker"): cache_key -> {kind, compile_ms,
        #: flops, bytes} from the compiled program's cost_analysis();
        #: programs_report() joins it with measured per-kind dispatch
        #: time into roofline %-attainment (GET /v1/debug/programs)
        self.programs: dict[tuple, dict] = {}
        #: flight recorder (config.flight_recorder): bounded ring of
        #: per-step records appended at deque cost from step(); None
        #: when disabled — the token path is bit-identical either way
        if config.flight_recorder:
            from dynamo_tpu.telemetry.flight import FlightRecorder

            self.flight: Optional["FlightRecorder"] = FlightRecorder(
                config.flight_ring
            )
        else:
            self.flight = None
        #: armed jax.profiler capture (request_profile): {steps_left,
        #: dir, started}; consumed by _profile_tick on the engine thread
        self._profile: Optional[dict] = None
        self._profile_lock = threading.Lock()
        #: set (weakly) by AsyncEngineRunner when a stall watchdog is
        #: attached, so the in-process debug surface can list diagnoses
        self._watchdog_ref = None
        # in-process debug surface (GET /v1/debug/*): weak registration,
        # a GC'd engine drops out
        from dynamo_tpu.telemetry import debug as _debug

        self.debug_name = _debug.register_engine(self)
        #: fleet telemetry plane (config.fleet_telemetry; mutable so the
        #: bench A/B can toggle one warm engine): SLO sketches + the MFU
        #: window. All host-side — the token path never reads them.
        self._fleet_telemetry = config.fleet_telemetry
        if self._fleet_telemetry:
            from dynamo_tpu.telemetry.slo import SloTracker

            self.slo: Optional["SloTracker"] = SloTracker()
        else:
            self.slo = None
        #: per-request SLO marks: rid -> [ttft_ms|None, itl_sum_ms,
        #: itl_samples, last_emit_perf_t]
        self._slo_marks: dict[str, list] = {}
        #: (perf_t, tokens_computed) per recent step, for the windowed
        #: tokens/s + MFU gauges
        from collections import deque

        self._thru_window: deque = deque()
        self._thru_window_s = 10.0
        #: running sum of the window's token counts (kept in step with
        #: append/popleft so _refresh_metrics stays O(evicted), not
        #: O(window) — the window holds thousands of entries at speed)
        self._thru_tokens = 0
        #: adaptive speculation: steps left on the fused path after a
        #: low-acceptance spec dispatch
        self._spec_cooldown = 0
        #: draft-model speculation (config.spec_draft_model): a second
        #: adapter + param tree + its own page pool. The pool shares the
        #: TARGET allocator's page ids/accounting — request page tables
        #: address both pools, so no second allocator exists.
        self._spec_draft = config.spec_draft_model is not None
        self.draft_adapter: Optional[ModelAdapter] = None
        self.draft_params = None
        self.draft_kv = None
        #: chained spec dispatch in flight (overlap pipeline for the
        #: draft path; None when idle or chaining is off)
        self._inflight_spec: Optional[_InflightSpec] = None
        #: (perf_t, drafted, accepted) per spec step + running sums, for
        #: the windowed live acceptance-rate gauge
        self._spec_window: deque = deque()  # deque imported above
        self._spec_window_s = 60.0
        self._spec_win_drafted = 0
        self._spec_win_accepted = 0
        #: overlapped decode: the one speculative in-flight dispatch (or
        #: None). Carried ACROSS hosts since the logical-axis refactor:
        #: chained dispatch feeds tokens on-device (replicated outputs),
        #: so the readback in _consume_inflight is the only per-window
        #: host sync and it is identical on every lockstep replica. Off
        #: under prompt-lookup speculation (drafts need host tokens).
        self._inflight: Optional[_InflightDecode] = None
        self._overlap_enabled = (
            config.overlap_decode and config.spec_ngram <= 0
        )
        #: stall-free mixed prefill+decode steps: off under prompt-lookup
        #: speculation (the verify program owns the decode batch). The
        #: scheduler only emits `mixed` when this holds. Multi-host runs
        #: keep it: batch assembly is event-log deterministic, the fused
        #: program's sampled ids come back replicated.
        self._mixed_enabled = (
            config.mixed_steps and config.spec_ngram <= 0
        )
        self.scheduler.mixed_enabled = self._mixed_enabled
        #: on-device K-step decode windows (config.decode_kstep): same
        #: policy surface as overlap/mixed — off under BOTH speculation
        #: modes (they already batch steps per dispatch); stays ON for
        #: multi-process meshes (the scan keeps feedback, stop checks,
        #: and page-table state on-device; the [K, B] readback is
        #: replicated). _decode_kstep is the live window target (bench
        #: A/B toggles it on a warm engine); per-dispatch eligibility
        #: (logprobs rows, stop-set size, page runway) is decided in
        #: _pick_kstep.
        self._decode_kstep = config.decode_kstep
        self._kstep_enabled = (
            config.decode_kstep > 1
            and config.spec_ngram <= 0
            and not self._spec_draft
        )
        if config.decode_kstep > 1 and not self._kstep_enabled:
            logger.info(
                "decode_kstep=%d auto-disabled: speculative decoding "
                "already batches steps per dispatch",
                config.decode_kstep,
            )
        #: live K-step window state: the last dispatched window size
        #: (the stall watchdog floors its threshold at a multiple of it)
        #: and the device-measured per-step ms of that window (spreads
        #: window emissions in the decode-stall histogram so a healthy
        #: K-wide gap is not booked as a prefill stall)
        self._kstep_live = 1
        self._kstep_step_ms = 0.0
        #: per-request last token-emission mark for the decode-stall
        #: histogram: request_id -> (perf_counter at emission, prefill+
        #: mixed dispatch count at emission). A later emission whose
        #: dispatch count advanced observes the gap as
        #: dynamo_tpu_phase_decode_stall_ms — prefill-attributed stalls
        #: only, which is exactly what mixed steps collapse.
        self._last_emit: dict[str, tuple[float, int]] = {}

        pre_quantized = False
        if params is None:
            checkpoint_path = checkpoint_path or self.adapter.default_checkpoint
            if checkpoint_path is not None and self.adapter.load_params:
                params = self.adapter.load_params(checkpoint_path)
            elif (
                config.quantize == "int8"
                and self.adapter.init_params_quantized is not None
            ):
                # straight into int8 layout: init+quantize would peak at
                # full-dtype model size (16GB for 8B — over v5e HBM)
                logger.info(
                    "initializing random int8 params for %s", config.model
                )
                params = self.adapter.init_params_quantized(jax.random.key(0))
                pre_quantized = True
            else:
                logger.info("initializing random params for %s", config.model)
                params = self.adapter.init_params(jax.random.key(0))
        if config.quantize and not pre_quantized:
            if config.quantize != "int8":
                raise ValueError(
                    f"unsupported quantize={config.quantize!r}; use int8"
                )
            if self.adapter.quantize_params is None:
                raise ValueError(
                    f"--quantize int8: the {config.model!r} adapter has no "
                    "quantized layout (Llama-family models support it)"
                )
            params = self.adapter.quantize_params(params)
        kv = self.adapter.init_kv(
            config.num_pages, config.page_size,
            kv_quantize=config.kv_quantize,
        )
        if self.mesh is not None:
            specs = self.adapter.param_specs(quantized=bool(config.quantize))
            params = self._put_global(params, shardings_for(self.mesh, specs))
            kv = self._put_global(
                kv,
                shardings_for(
                    self.mesh,
                    self.adapter.kv_spec(kv_quantize=config.kv_quantize),
                ),
            )
        self.params = params
        self.kv = kv
        if self._spec_draft:
            self._init_draft_model(config, impl)
        # Live-MFU constants: FLOPs/token follow the ACTIVE parameters
        # (MoE: top_k of E experts — total params would overstate ~8x),
        # against the chip's public peak (nominal off-TPU so the gauge
        # stays a plausible (0,1] number on dev boxes).
        from dynamo_tpu.platform import device_peak_flops

        self._peak_flops = device_peak_flops()
        self._n_active_params = self._active_param_count(params)
        # KV-pool byte gauges: actual device bytes (quantized pages +
        # scale planes) vs what the same pool costs at the model dtype —
        # the ~2x effective-capacity claim, measured not asserted.
        m = self.metrics
        m.kv_pool_bytes = int(
            sum(x.nbytes for x in jax.tree.leaves(kv))
            + sum(x.nbytes for x in jax.tree.leaves(self.draft_kv))
        )
        model_itemsize = jnp.dtype(
            getattr(self.adapter.config, "dtype", None)
            or self.adapter.config.base.dtype
        ).itemsize
        m.kv_pool_bytes_dense_equiv = int(
            (kv.k.size + kv.v.size) * model_itemsize
        )
        m.kv_free_pages = self.allocator.num_free
        # HBM accounting plane (GET /v1/debug/memory): the param trees
        # never change after construction, so their per-device shard
        # bytes and per-sharding-spec grouping are computed once here;
        # memory_report() joins them with the live KV pool / program
        # scratch / device memory_stats on every call.
        self._weights_by_device = self._per_device_bytes(
            (self.params, self.draft_params)
        )
        self._param_groups = self._param_group_specs()
        self.refresh_memory_metrics()
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            # ndim 3 covers mm_embeds [B, T, H]
            self._batch_shardings = {
                nd: NamedSharding(self.mesh, batch_spec(nd))
                for nd in (1, 2, 3)
            }
        else:
            self._batch_shardings = None

    def _put_global(self, tree, shardings):
        """Place a host pytree onto the mesh. Single-process: device_put.
        Multi-process: every host holds the identical full copy, so each
        assembles its addressable shards via make_array_from_callback
        (device_put cannot target non-addressable devices)."""
        if not self._multiproc:
            return jax.device_put(tree, shardings)

        def put(x, sh):
            h = np.asarray(x)
            return jax.make_array_from_callback(
                h.shape, sh, lambda idx: h[idx]
            )

        return jax.tree.map(put, tree, shardings)

    def _dev(self, arr: np.ndarray):
        """Host batch array -> device, dp-sharded along dim 0 on a mesh.

        Single-process: batches not divisible by dp (B=1 prefill, small
        decode buckets) are left for jit to reshard — an explicit
        device_put would raise. Multi-process: every input must be an
        explicit global array (replicated when not dp-divisible); the
        host copies are identical by the lockstep contract."""
        if self._multiproc:
            arr = np.asarray(arr)
            dp = self.mesh.shape.get("dp", 1)
            if dp > 1 and arr.shape[0] % dp == 0:
                sh = self._batch_shardings[arr.ndim]
            else:
                sh = self._rep_sharding
            return jax.make_array_from_callback(
                arr.shape, sh, lambda idx: arr[idx]
            )
        x = jnp.asarray(arr)
        if self._batch_shardings is not None:
            dp = self.mesh.shape.get("dp", 1)
            if dp > 1 and arr.shape[0] % dp == 0:
                x = jax.device_put(x, self._batch_shardings[arr.ndim])
        return x

    def _dev_tree(self, tree):
        """All host inputs of one dispatch -> device in a SINGLE batched
        transfer. A dispatch ships ~4-14 small arrays (tokens/positions/
        valid/page-table + sampling/penalty/bias planes); putting them one
        by one costs a transfer round trip each — over a tunneled TPU
        that per-message latency rivals the decode step itself. On the
        plain single-chip path jax.device_put of the whole pytree lands
        everything in one batched_device_put; sharded/multi-process paths
        keep the per-leaf placement rules of _dev.

        Defensive fallback: the axon PJRT backend has shipped with missing
        transfer features before (no CreateBuffersForAsyncHostToDevice —
        disagg/transfer.py) — if the batched put raises there, drop to
        per-leaf jnp.asarray once and stay there for the engine's life."""
        if self._multiproc or self._batch_shardings is not None:
            return jax.tree.map(self._dev, tree)
        if self._batched_put_ok:
            try:
                return jax.device_put(tree)
            except Exception as e:
                # latch the fallback ONLY for capability errors — a
                # transient failure (OOM, tunnel hiccup) must surface,
                # not silently degrade every later dispatch
                msg = str(e).lower()
                if not any(
                    s in msg
                    for s in ("unimplemented", "not implemented",
                              "unsupported", "not supported")
                ):
                    raise
                self._batched_put_ok = False
                logger.warning(
                    "batched device_put unsupported on this backend (%s); "
                    "falling back to per-leaf transfers", e,
                )
        return jax.tree.map(self._dev, tree)

    def _init_draft_model(self, config: EngineConfig, impl: str) -> None:
        """Load the speculation draft model (config.spec_draft_model): a
        second adapter + param tree and a second KV pool addressed by the
        SAME page ids as the target pool — request page tables index both,
        so the PageAllocator's accounting covers draft pages for free.

        Self-draft (draft name == target model, no draft checkpoint)
        shares the target's param tree instead of loading a copy: zero
        extra HBM, acceptance ~1 under greedy — the pipeline-validation /
        upper-bound harness bench.py's spec_ab uses."""
        if self._multiproc:
            raise ValueError(
                "spec_draft_model is not supported on multi-process SPMD "
                "meshes yet (the chained dispatch feedback is per-process)"
            )
        self.draft_adapter = get_model(
            config.spec_draft_model, dtype=config.dtype,
            attention_impl=impl, mesh=self.mesh,
        )
        if self.draft_adapter.vocab_size != self.adapter.vocab_size:
            raise ValueError(
                f"draft model {config.spec_draft_model!r} has vocab "
                f"{self.draft_adapter.vocab_size} but target "
                f"{config.model!r} has {self.adapter.vocab_size} — "
                "speculation requires a shared tokenizer/vocabulary"
            )
        ckpt = (
            config.spec_draft_checkpoint
            or self.draft_adapter.default_checkpoint
        )
        if (
            config.spec_draft_model == config.model
            and config.spec_draft_checkpoint is None
        ):
            # self-draft: share the tree. Checked BEFORE the checkpoint
            # branch — when the model name IS a checkpoint dir/GGUF the
            # adapter carries a default_checkpoint, and loading it again
            # would duplicate the full target weights in HBM
            dparams = self.params
        elif ckpt is not None and self.draft_adapter.load_params:
            dparams = self.draft_adapter.load_params(ckpt)
        else:
            logger.info(
                "initializing random draft params for %s (acceptance "
                "will sit at chance until real weights are loaded)",
                config.spec_draft_model,
            )
            dparams = self.draft_adapter.init_params(jax.random.key(0))
        dkv = self.draft_adapter.init_kv(config.num_pages, config.page_size)
        if self.mesh is not None:
            if dparams is not self.params:
                dparams = self._put_global(
                    dparams,
                    shardings_for(
                        self.mesh, self.draft_adapter.param_specs()
                    ),
                )
            dkv = self._put_global(
                dkv, shardings_for(self.mesh, self.draft_adapter.kv_spec())
            )
        self.draft_params = dparams
        self.draft_kv = dkv

    # -- public API --------------------------------------------------------

    def add_request(
        self,
        request_id: str,
        prompt_tokens: Sequence[int],
        sampling: Optional[SamplingParams] = None,
        mm_embeds: Optional[np.ndarray] = None,
        mm_positions: Sequence[int] = (),
        deadline: Optional[float] = None,
    ) -> Request:
        self._validate_bias(sampling)
        if mm_embeds is not None:
            mm_embeds = np.asarray(mm_embeds, np.float32)
            if len(mm_positions) != len(mm_embeds):
                raise ValueError(
                    f"{len(mm_embeds)} multimodal embeddings but "
                    f"{len(mm_positions)} placeholder positions"
                )
            hdim = self._hidden_size
            if mm_embeds.ndim != 2 or mm_embeds.shape[-1] != hdim:
                # Reject here, where the runner returns the error to THIS
                # client — a bad shape surfacing inside step() would wedge
                # the whole batch loop instead.
                raise ValueError(
                    f"multimodal embeddings must be [n, {hdim}] for this "
                    f"model; got {mm_embeds.shape}"
                )
            if any(
                not 0 <= p < len(prompt_tokens) for p in mm_positions
            ):
                raise ValueError(
                    "mm_positions out of range for the prompt"
                )
        req = Request(
            request_id=request_id,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            arrival_time=time.time(),
            deadline=deadline,
            mm_embeds=mm_embeds,
            mm_positions=tuple(mm_positions),
        )
        self.scheduler.add_request(req)
        self.metrics.requests_received += 1
        return req

    def abort_request(self, request_id: str) -> bool:
        self._last_emit.pop(request_id, None)
        self._slo_marks.pop(request_id, None)
        return self.scheduler.abort_request(request_id) is not None

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self) -> list[StepOutput]:
        if self._profile is not None:
            self._profile_start()  # armed capture opens BEFORE this step
        t0 = time.perf_counter()
        batch = self.scheduler.schedule()
        t1 = time.perf_counter()
        self.metrics.time_schedule_ms += (t1 - t0) * 1000.0
        outputs = self._drain_doomed()
        if batch is None or batch.kind not in ("decode", "mixed"):
            # A speculated decode step can only be the next decode step
            # or the decode half of a mixed step; a pure prefill (or a
            # drained queue) invalidates it.
            why = "no batch" if batch is None else "prefill scheduled"
            if self._inflight is not None:
                self._discard_inflight(why)
            if self._inflight_spec is not None:
                self._discard_inflight_spec(why)
        if batch is not None:
            t2 = time.perf_counter()  # after the drain: phase time is
            # dispatch+sync+postprocess only, as the field docs promise
            gen0 = self.metrics.generated_tokens
            from dynamo_tpu.telemetry import phases

            # Dispatch counters increment BEFORE the run so emissions
            # inside it record the post-step mark (the decode-stall
            # histogram compares marks across emissions).
            # exemplar for this dispatch's bucket: any traced request in
            # the batch (None when tracing is off — zero extra work)
            batch_tid = self._batch_trace_id(batch)
            if batch.kind == "prefill":
                self.metrics.prefill_dispatches += 1
                outputs += self._run_prefill(batch)
                dt_ms = (time.perf_counter() - t2) * 1000.0
                self.metrics.time_prefill_ms += dt_ms
                phases.observe("prefill_ms", dt_ms, trace_id=batch_tid)
            elif batch.kind == "mixed":
                self.metrics.mixed_dispatches += 1
                outputs += self._run_mixed(batch)
                dt_ms = (time.perf_counter() - t2) * 1000.0
                self.metrics.time_mixed_ms += dt_ms
                phases.observe("mixed_step_ms", dt_ms, trace_id=batch_tid)
            else:
                self.metrics.decode_dispatches += 1
                outputs += self._run_decode(batch)
                dt_ms = (time.perf_counter() - t2) * 1000.0
                self.metrics.time_decode_ms += dt_ms
                phases.observe("decode_step_ms", dt_ms, trace_id=batch_tid)
            self.metrics.steps += 1
            if self._fleet_telemetry:
                # tokens this step pushed through the model (prefill
                # chunk tokens + emitted decode tokens — a conservative
                # undercount of forward-pass work, so MFU never flatters)
                step_toks = sum(p.length for p in batch.prefill) + (
                    self.metrics.generated_tokens - gen0
                )
                self._thru_window.append((time.perf_counter(), step_toks))
                self._thru_tokens += step_toks
            if self.flight is not None:
                self.flight.record_step(
                    self.metrics,
                    kind=batch.kind,
                    step_ms=dt_ms,
                    n_decode=len(batch.decode),
                    b_decode=(
                        self.config.decode_bucket_for(len(batch.decode))
                        if batch.decode
                        else 0
                    ),
                    n_prefill=len(batch.prefill),
                    t_bucket=(
                        max(self._bucket_t(p.length) for p in batch.prefill)
                        if batch.prefill
                        else 0
                    ),
                    prefill_tokens=sum(p.length for p in batch.prefill),
                    waiting=self.scheduler.num_waiting(),
                    running=self.scheduler.num_running(),
                    free_pages=self.allocator.num_free,
                    active_pages=self.allocator.num_active,
                    watermark=max(
                        getattr(self.allocator, "watermark", 0),
                        self.metrics.kv_pages_watermark,
                    ),
                )
        if self._profile is not None and batch is not None:
            self._profile_count()  # one dispatched step captured
        if not self.scheduler.has_work:
            # the wave ended on a sampled stop the speculation couldn't
            # predict: drop any dangling dispatch so device arrays free
            if self._inflight is not None:
                self._discard_inflight("idle")
            if self._inflight_spec is not None:
                self._discard_inflight_spec("idle")
        self._refresh_metrics()
        return outputs

    def _drain_doomed(self) -> list[StepOutput]:
        """Finish requests the scheduler proved can never progress (or
        whose deadline expired pre-admission — those finish as ERROR)."""
        outputs = []
        for req, why, reason in self.scheduler.doomed:
            logger.error("request %s cannot progress: %s", req.request_id, why)
            self._last_emit.pop(req.request_id, None)
            self._slo_marks.pop(req.request_id, None)
            req.state = RequestState.FINISHED
            req.finish_reason = reason
            outputs.append(
                StepOutput(
                    request_id=req.request_id,
                    new_token_ids=(),
                    finish_reason=reason,
                )
            )
        self.scheduler.doomed.clear()
        return outputs

    def run_to_completion(self) -> dict[str, list[int]]:
        """Drain all queued work; returns request_id -> generated tokens."""
        done: dict[str, list[int]] = {}
        while self.has_work:
            for out in self.step():
                done.setdefault(out.request_id, []).extend(out.new_token_ids)
        return done

    # -- prefill -----------------------------------------------------------

    def _bucket_t(self, n: int) -> int:
        cap = max(self.config.prefill_chunk, 32)
        if n > cap:
            # The cap used to silently round DOWN, which would have
            # truncated the valid mask of an oversized piece. The
            # scheduler chunks at prefill_chunk, so this can only fire on
            # a scheduler bug — fail loudly instead of corrupting KV.
            raise ValueError(
                f"prefill piece of {n} tokens exceeds the T-bucket cap "
                f"{cap} (pieces must be chunked at prefill_chunk)"
            )
        t = 32
        while t < n:
            t *= 2
        return min(t, cap)

    @staticmethod
    def _bucket_b(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _run_prefill(
        self, batch: ScheduledBatch, mixed: bool = False
    ) -> list[StepOutput]:
        """Pieces grouped by T bucket run as one batched [B, T] program —
        many prompts prefill per dispatch instead of serial B=1 launches.
        `mixed` marks outputs emitted as part of a mixed step (the
        overlap split path runs the prefill half through here)."""
        outputs: list[StepOutput] = []
        if self._spec_draft:
            # the draft pool prefills alongside the target pool — this
            # also covers the prefix-cached region the target skipped
            # (cached pages hold target KV only; the draft must compute
            # its own), so spec_draft_pos reaches each piece's end
            self._spec_draft_cover(
                [
                    (p.request, p.start + p.length)
                    for p in batch.prefill
                    if p.request.mm_embeds is None
                ]
            )
        groups: dict[int, list] = {}
        for piece in batch.prefill:
            groups.setdefault(self._bucket_t(piece.length), []).append(piece)
        mp = self.config.max_pages_per_seq
        for t_bucket, pieces in sorted(groups.items()):
            b = len(pieces)
            b_bucket = self._bucket_b(b)
            tokens = np.zeros((b_bucket, t_bucket), np.int32)
            positions = np.zeros((b_bucket, t_bucket), np.int32)
            valid = np.zeros((b_bucket, t_bucket), bool)
            pt = np.zeros((b_bucket, mp), np.int32)
            last_idx = np.zeros(b_bucket, np.int32)
            any_last = False
            any_mm = any(p.request.mm_embeds is not None for p in pieces)
            mm_embeds = mm_mask = None
            if any_mm:
                mm_embeds = np.zeros(
                    (b_bucket, t_bucket, self._hidden_size), np.float32
                )
                mm_mask = np.zeros((b_bucket, t_bucket), bool)
            for i, piece in enumerate(pieces):
                req = piece.request
                chunk = req.all_tokens[piece.start : piece.start + piece.length]
                tokens[i, : piece.length] = chunk
                positions[i] = np.arange(t_bucket, dtype=np.int32) + piece.start
                valid[i, : piece.length] = True
                pt[i, : len(req.pages)] = req.pages
                last_idx[i] = piece.length - 1
                if piece.start + piece.length >= len(req.prompt_tokens):
                    any_last = True
                if req.mm_embeds is not None:
                    for j, pos in enumerate(req.mm_positions):
                        off = pos - piece.start
                        if 0 <= off < piece.length:
                            mm_embeds[i, off] = req.mm_embeds[j]
                            mm_mask[i, off] = True

            host = {"base": (tokens, positions, valid, pt)}
            if any_mm:
                host["mm"] = (mm_embeds, mm_mask)
            # Every piece starting at 0 (un-chunked prompts, no prefix
            # hits — the common case) compiles a history-free program:
            # attention over the in-register chunk only, no page gather.
            first_chunk = all(p.start == 0 for p in pieces)
            lp_data = None
            if any_last:
                reqs = [p.request for p in pieces]
                samp, all_greedy = self._sampling_arrays(reqs, pad_to=b_bucket)
                lp = self._batch_logprobs(reqs)
                # Penalties at prefill-sample time only matter when a
                # penalized request already HAS generated history — i.e. a
                # preempted request resuming via recompute.
                pen = self._batch_penalty_bucket(reqs)
                if pen and not any(self._penalty_history(r) for r in reqs):
                    pen = 0
                host.update(
                    samp=samp, last=last_idx,
                    pen=self._penalty_arrays(reqs, b_bucket, pen)
                    if pen else (),
                )
                bias = self._batch_bias(reqs)
                if bias:
                    host["bias"] = self._bias_arrays(reqs, b_bucket)
                dev = self._dev_tree(host)
                args = (self.params, *dev["base"][:3], self.kv,
                        dev["base"][3])
                fn = self._get_step_fn(
                    "prefill", b_bucket, t_bucket, greedy=all_greedy,
                    mm=any_mm, first_chunk=first_chunk, lp=lp, pen=pen,
                    bias=bias,
                )
                # mm/bias ride as keywords: the positional tail of the
                # shared step_fn signature belongs to the penalty args.
                mm_kwargs = (
                    {"mm_embeds": dev["mm"][0], "mm_mask": dev["mm"][1]}
                    if any_mm
                    else {}
                )
                bias_kwargs = dev.get("bias", {})
                if lp >= 0:
                    token_ids, lp_raw, self.kv = fn(
                        *args, dev["last"], *dev["samp"], *dev["pen"],
                        **bias_kwargs, **mm_kwargs
                    )
                    lp_data = tuple(np.asarray(x) for x in lp_raw)
                else:
                    token_ids, self.kv = fn(
                        *args, dev["last"], *dev["samp"], *dev["pen"],
                        **bias_kwargs, **mm_kwargs
                    )
                ids = np.asarray(token_ids)
            else:
                # No piece finishes its prompt: KV writes only — skip the
                # vocab-sized logits + sampling entirely.
                dev = self._dev_tree(host)
                args = (self.params, *dev["base"][:3], self.kv,
                        dev["base"][3])
                fn = self._get_step_fn(
                    "prefill_nosample", b_bucket, t_bucket, mm=any_mm,
                    first_chunk=first_chunk,
                )
                self.kv = fn(*args, *dev.get("mm", ()))
                ids = None
            for i, piece in enumerate(pieces):
                req = piece.request
                req.num_computed_tokens += piece.length
                self._register_pages(req)
                if req.prefill_done:
                    req.state = RequestState.DECODE
                    lps = tops = None
                    if lp_data is not None and req.sampling.logprobs >= 0:
                        lps = (float(lp_data[0][i]),)
                        nk = req.sampling.logprobs
                        if nk > 0:
                            tops = (
                                tuple(
                                    (int(lp_data[1][i, j]), float(lp_data[2][i, j]))
                                    for j in range(min(nk, lp_data[1].shape[-1]))
                                ),
                            )
                    outputs.extend(
                        self._accept_token(
                            req, int(ids[i]), first=True, lps=lps,
                            tops=tops, mixed=mixed,
                        )
                    )
        return outputs

    # -- decode ------------------------------------------------------------

    @staticmethod
    def _pow2_floor(k: int) -> int:
        """Largest power of two <= k (k >= 1). Fused-step counts snap to
        powers of two so the decode_multi program family stays
        log-sized — every distinct k is a full-model compile."""
        p = 1
        while p * 2 <= k:
            p *= 2
        return p

    def _pick_decode_steps(self, reqs: list[Request]) -> int:
        """Fused steps for this dispatch: capped by config, by remaining
        context room, and dropped to 1 when admission is pending (so new
        arrivals don't wait K steps) or when the pool can't pre-grow every
        sequence's page table K tokens ahead."""
        k = self.config.decode_steps
        if k <= 1:
            return 1
        # Admission pending AND actually possible this step: stay responsive.
        # (A backlog that can't admit anyway must not forfeit fusion.)
        if self.scheduler.num_waiting() > 0 and self.scheduler.can_admit_head():
            return 1
        cap_tokens = self.config.max_pages_per_seq * self.config.page_size
        for req in reqs:
            k = min(k, self.config.max_context - req.num_tokens + 1)
            k = min(k, cap_tokens - req.num_tokens + 1)
        # Cover the longest remaining completion rounded UP to a power of
        # two (the decode_multi program family stays small — every distinct
        # k is a full-model compile). Requests finishing mid-scan discard
        # their overshoot in the accept loop, so the tail of a wave runs as
        # ONE dispatch instead of a halving ladder of dispatches, each a
        # full host sync (the sync, not the compute, is what costs).
        rem_max = 0
        for req in reqs:
            s = req.sampling
            rem_max = max(
                rem_max,
                s.max_tokens - len(req.output_tokens) - req.num_emitted,
            )
        p = 1
        while p < max(1, rem_max):
            p *= 2
        k = min(k, p)
        # The context/page caps above can leave an arbitrary k: snap DOWN
        # so cap-bound sequences don't each compile a fresh decode_multi
        # program (k=37, 35, 33, ... would).
        k = self._pow2_floor(k)
        if k <= 1:
            return 1
        if not self._grow_pages_for(reqs, k - 1):
            return 1  # single-step path handles pressure via preemption
        return k

    def _grow_pages_for(self, reqs: list[Request], ahead: int) -> bool:
        """Grow every request's page table to cover num_tokens + ahead,
        with an aggregate need-vs-free pre-check so pool pressure never
        half-grows the batch. False => nothing was allocated."""
        ps = self.config.page_size
        need = 0
        per_req = []
        for req in reqs:
            extra = -(-(req.num_tokens + ahead) // ps) - len(req.pages)
            per_req.append(max(0, extra))
            need += max(0, extra)
        if need > self.allocator.num_free:
            return False
        for req, extra in zip(reqs, per_req):
            if extra:
                got = self.allocator.allocate(extra)
                if got is None:
                    return False  # unreachable given the pre-check
                req.pages.extend(got)
        return True

    # -- on-device K-step decode windows (config.decode_kstep) -------------

    def _kstep_stop_ids(self, req: Request) -> Optional[tuple[int, ...]]:
        """This request's device-side stop set (eos ∪ stop_token_ids; an
        ignore_eos request stops on NOTHING — `_finish_reason_for`
        ignores both sets for it), or None when it exceeds the static
        STOP_SLOTS packing and the window must fall back to the
        host-side finish scan."""
        from dynamo_tpu.engine.sampling import STOP_SLOTS

        s = req.sampling
        if s.ignore_eos:
            return ()
        ids = tuple(
            dict.fromkeys(
                tuple(self.config.eos_token_ids) + tuple(s.stop_token_ids)
            )
        )
        return ids if len(ids) <= STOP_SLOTS else None

    def _kstep_candidate(self, reqs: list[Request]) -> bool:
        """Side-effect-free eligibility for a K-step window over these
        rows: configured on, policy-enabled, no logprobs rows (the fused
        window threads no per-position logprob state), every stop set
        fits STOP_SLOTS. Mixed steps use this to decide whether to split
        the K-window out as their decode leg; _pick_kstep layers the
        stateful clamps (admission latency, page runway) on top."""
        if self._decode_kstep <= 1 or not self._kstep_enabled:
            return False
        if self._batch_logprobs(reqs) >= 0:
            return False
        return all(self._kstep_stop_ids(r) is not None for r in reqs)

    def _pick_kstep(self, reqs: list[Request]) -> int:
        """Window size for this decode dispatch; 1 => take the classic
        decode/decode_multi path. Mirrors _pick_decode_steps' admission
        rule (drop to 1 when an admissible request waits) and its
        pow2 snapping, but the page headroom is reserved UP FRONT for
        the whole window via the scheduler's runway clamp — the
        on-device loop can never ask the host for a page mid-window."""
        if self._decode_kstep <= 1 or not self._kstep_enabled:
            return 1
        if not self._kstep_candidate(reqs):
            self.metrics.kstep_fallbacks += 1
            logger.debug(
                "kstep fallback: logprobs rows or oversized stop set"
            )
            return 1
        if self.scheduler.num_waiting() > 0 and self.scheduler.can_admit_head():
            return 1  # stay responsive: new arrivals don't wait K steps
        k = self._pow2_floor(self._decode_kstep)
        # context/page-table room: growing a window past max_context or
        # max_pages_per_seq would overflow the [B, mp] page table (same
        # per-request caps as _pick_decode_steps)
        cap_tokens = self.config.max_pages_per_seq * self.config.page_size
        for req in reqs:
            k = min(k, self.config.max_context - req.num_tokens + 1)
            k = min(k, cap_tokens - req.num_tokens + 1)
        # cover the longest remaining completion, rounded up to a power
        # of two (same reasoning as _pick_decode_steps: the tail of a
        # wave runs as one window, the program family stays log-sized)
        rem_max = 0
        for req in reqs:
            s = req.sampling
            rem_max = max(
                rem_max,
                s.max_tokens - len(req.output_tokens) - req.num_emitted,
            )
        p = 1
        while p < max(1, rem_max):
            p *= 2
        k = self._pow2_floor(min(k, p))
        if k <= 1:
            return 1
        # scheduler-guaranteed page runway for the WHOLE window (or a
        # clamped one); _grow_pages_for then actually reserves it
        k = self.scheduler.clamp_kstep_window(reqs, k)
        while k > 1 and not self._grow_pages_for(reqs, k - 1):
            k //= 2  # pool raced smaller than the clamp's view
        return max(1, k)

    def _kstep_arrays(
        self, reqs: list[Request], pad_to: int, emitted_ahead: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        """Device inputs for the window's on-device finish evaluation:
        per-row packed stop slots (−1-padded) and per-row emission
        budgets — the EXACT token counts `_finish_reason_for` would
        allow (max_tokens and max_context legs), so the device freeze
        decisions and the host finish scan agree position-for-position.
        `emitted_ahead` discounts a pending overlapped window's tokens
        when building the chained window's budgets. Padding rows get
        budget 0 and empty stop sets (they are never alive anyway)."""
        from dynamo_tpu.engine.sampling import STOP_SLOTS

        stops = np.full((pad_to, STOP_SLOTS), -1, np.int32)
        budgets = np.zeros(pad_to, np.int32)
        for i, req in enumerate(reqs):
            ids = self._kstep_stop_ids(req)  # eligibility pre-checked
            if ids:
                stops[i, : len(ids)] = ids
            s = req.sampling
            budgets[i] = max(
                0,
                min(
                    s.max_tokens
                    - len(req.output_tokens)
                    - req.num_emitted,
                    self.config.max_context - req.num_tokens,
                )
                - emitted_ahead,
            )
        return stops, budgets

    # -- speculative decode (prompt lookup / n-gram) ------------------------

    def _spec_eligible(self, reqs: list[Request]) -> bool:
        """Draft-model speculation verifies any sampling configuration
        the on-device accept scan threads — temperature/top-p/top-k
        (exact rejection sampling), penalties and logit_bias/min_tokens
        ride the same row-space plumbing as the plain programs. Only
        logprob reporting (per-position logprob state isn't threaded)
        and multimodal requests (the draft has no mm path) fall back to
        plain decode. Draft-free prompt lookup keeps its greedy-only
        restriction: its verify program has no sampling plane at all."""
        if self._spec_draft:
            return not any(
                r.sampling.logprobs >= 0 or r.mm_embeds is not None
                for r in reqs
            )
        if self.config.spec_ngram <= 0:
            return False
        for r in reqs:
            s = r.sampling
            if (
                s.temperature > 0.0
                or s.logprobs >= 0
                or s.frequency_penalty
                or s.presence_penalty
                or s.repetition_penalty != 1.0
                or s.logit_bias
                or s.min_tokens
            ):
                return False
        return True

    def _spec_active(self, reqs: list[Request]) -> bool:
        """Whether THIS step's decode batch runs through a speculative
        verify program. One bookkeeping point for eligibility + the
        acceptance cooldown, shared by _run_decode and _run_mixed (which
        asks before splitting the spec verify out as its decode leg) —
        call it at most once per engine step."""
        if not (self._spec_draft or self.config.spec_ngram > 0):
            return False
        if self._spec_eligible(reqs):
            if self._spec_cooldown <= 0:
                return True
            self._spec_cooldown -= 1
            self.metrics.spec_skipped_cooldown += 1
        else:
            self.metrics.spec_skipped_ineligible += 1
        return False

    def _propose_drafts(self, req: Request, s: int) -> list[int]:
        """Prompt-lookup proposal: the s tokens that followed the LAST
        earlier occurrence of the sequence's trailing n-gram. No match =>
        zero-pads (they simply fail verification; one token still lands).

        The n-gram index is maintained incrementally on the request —
        each position is indexed exactly once over the request's lifetime
        (amortized O(1) per decode step instead of an O(L) rescan)."""
        n = self.config.spec_ngram_match
        if req.num_tokens <= n:
            return [0] * s
        if req.spec_index is not None and (
            req.num_tokens - len(req.spec_ctx) > len(req.output_tokens)
        ):
            # Preemption folded outputs into the prompt while spec state
            # was stale — the delta can no longer be read off
            # output_tokens. Rebuild rather than desync the index.
            req.spec_index = None
        if req.spec_index is None:
            req.spec_index = {}
            req.spec_ctx = req.all_tokens  # one full copy, then appended
            req.spec_indexed_upto = 0
        elif len(req.spec_ctx) < req.num_tokens:
            delta = req.num_tokens - len(req.spec_ctx)
            req.spec_ctx.extend(req.output_tokens[-delta:])
        ctx = req.spec_ctx
        # index every n-gram start except the trailing one (a tail must
        # match an EARLIER occurrence)
        for j in range(req.spec_indexed_upto, len(ctx) - n):
            req.spec_index[tuple(ctx[j : j + n])] = j
        req.spec_indexed_upto = max(req.spec_indexed_upto, len(ctx) - n)
        j = req.spec_index.get(tuple(ctx[-n:]))
        if j is None:
            return [0] * s
        cont = ctx[j + n : j + n + s]
        return cont + [0] * (s - len(cont))

    def _run_decode_spec(self, reqs: list[Request]) -> list[StepOutput]:
        """One verify dispatch: [last_token, draft_0..draft_{S-1}] runs
        through the model like a prefill chunk (causal over the window,
        paged KV behind it); target tokens are the argmax at every
        position. Accept matched drafts + the model's token at the first
        mismatch — per request, 1..S+1 tokens per step. Stale KV written
        for rejected positions is overwritten when the real tokens reach
        those positions; attention never reads past a sequence's length."""
        s = self.config.spec_ngram
        b_bucket = self.config.decode_bucket_for(len(reqs))
        mp = self.config.max_pages_per_seq
        t = s + 1
        cap_tokens = mp * self.config.page_size
        # Pre-grow pages to cover the verify window; pressure => no spec
        # (the aggregate pre-check in _grow_pages_for means a refusal
        # claims nothing).
        for req in reqs:
            if req.num_tokens + s > min(cap_tokens, self.config.max_context):
                return self._run_decode_plain(reqs)
        if not self._grow_pages_for(reqs, s):
            return self._run_decode_plain(reqs)

        t0 = time.perf_counter()
        tokens = np.zeros((b_bucket, t), np.int32)
        positions = np.zeros((b_bucket, t), np.int32)
        valid = np.zeros((b_bucket, t), bool)
        pt = np.zeros((b_bucket, mp), np.int32)
        drafts = np.zeros((b_bucket, s), np.int32)
        for i, req in enumerate(reqs):
            d = self._propose_drafts(req, s)
            drafts[i] = d
            tokens[i, 0] = req.all_tokens[-1]
            tokens[i, 1:] = d
            positions[i] = np.arange(t, dtype=np.int32) + req.num_tokens - 1
            valid[i] = True
            pt[i, : len(req.pages)] = req.pages

        fn = self._get_step_fn("spec_verify", b_bucket, t)
        d_tokens, d_positions, d_valid, d_pt = self._dev_tree(
            (tokens, positions, valid, pt)
        )
        target_ids, self.kv = fn(
            self.params, d_tokens, d_positions, d_valid, self.kv, d_pt,
        )
        # timing parity with _run_decode_plain (flight-recorder deltas
        # and the dispatch/sync/host split must not go blind under
        # speculation): array build + launch = dispatch, the blocking
        # device→host read = sync, the accept scan = host
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        t1 = time.perf_counter()
        target = np.asarray(target_ids)  # [B, t]
        self.metrics.time_decode_sync_ms += (
            time.perf_counter() - t1
        ) * 1000.0
        t2 = time.perf_counter()
        outputs: list[StepOutput] = []
        step_drafted = step_accepted = 0
        for i, req in enumerate(reqs):
            accepted: list[int] = []
            finish: Optional[FinishReason] = None
            for j in range(t):
                tok = int(target[i, j])
                accepted.append(tok)
                finish = self._finish_reason_for(req, tok, len(accepted))
                if finish is not None:
                    break
                if j < s and int(drafts[i, j]) != tok:
                    break  # draft diverged: the model's token still lands
            step_drafted += s
            step_accepted += len(accepted) - 1
            req.num_computed_tokens += len(accepted)
            outputs.extend(
                self._accept_tokens(req, accepted, finish, spec=True)
            )
            self._register_pages(req)
        self._note_spec_step(step_drafted, step_accepted)
        if (
            step_drafted
            and step_accepted / step_drafted < self.config.spec_min_accept_rate
        ):
            # Lookup is missing on this workload: revert to fused multi-
            # step decode for a while, then probe speculation again.
            self._spec_cooldown = self.config.spec_cooldown_steps
        self.metrics.time_decode_host_ms += (
            time.perf_counter() - t2
        ) * 1000.0
        return outputs

    # -- speculative decode (draft model, fused on-device acceptance) ------

    def _note_spec_step(self, drafted: int, accepted: int) -> None:
        """Counters + the sliding window behind the live acceptance-rate
        gauge (shared by the prompt-lookup and draft-model paths)."""
        self.metrics.spec_drafted += drafted
        self.metrics.spec_accepted += accepted
        self._spec_window.append((time.perf_counter(), drafted, accepted))
        self._spec_win_drafted += drafted
        self._spec_win_accepted += accepted

    def _spec_draft_cover(self, spans) -> None:
        """Bring the DRAFT pool's KV up to date over `spans` = [(req,
        upto)]: chunked draft-model forward (KV writes only) over
        [req.spec_draft_pos, upto). The target prefill path calls this
        per piece — so the draft rides every prefill step, including the
        prefix-cached region the target skipped — and the spec decode
        path calls it when a request arrives in decode with a stale
        draft pool (disagg add_prefilled, fused-mixed prefills during an
        acceptance cooldown). Chunk r of every span runs before chunk
        r+1 of any (a mid-sequence chunk's attention reads the previous
        chunk's KV); within a round chunks batch by T bucket exactly
        like _run_prefill."""
        chunk = self.config.prefill_chunk
        mp = self.config.max_pages_per_seq
        rounds: list[list[tuple]] = []
        for req, upto in spans:
            start = req.spec_draft_pos
            r = 0
            while start < upto:
                take = min(chunk, upto - start)
                if r >= len(rounds):
                    rounds.append([])
                rounds[r].append((req, start, take))
                start += take
                r += 1
            req.spec_draft_pos = max(req.spec_draft_pos, upto)
        for round_items in rounds:
            groups: dict[int, list] = {}
            for item in round_items:
                groups.setdefault(self._bucket_t(item[2]), []).append(item)
            for t_bucket, items in sorted(groups.items()):
                b_bucket = self._bucket_b(len(items))
                tokens = np.zeros((b_bucket, t_bucket), np.int32)
                positions = np.zeros((b_bucket, t_bucket), np.int32)
                valid = np.zeros((b_bucket, t_bucket), bool)
                pt = np.zeros((b_bucket, mp), np.int32)
                for i, (req, start, length) in enumerate(items):
                    tokens[i, :length] = req.all_tokens[start : start + length]
                    positions[i] = (
                        np.arange(t_bucket, dtype=np.int32) + start
                    )
                    valid[i, :length] = True
                    pt[i, : len(req.pages)] = req.pages
                first_chunk = all(it[1] == 0 for it in items)
                fn = self._get_step_fn(
                    "spec_draft_prefill", b_bucket, t_bucket,
                    first_chunk=first_chunk,
                )
                d_tokens, d_positions, d_valid, d_pt = self._dev_tree(
                    (tokens, positions, valid, pt)
                )
                self.draft_kv = fn(
                    self.draft_params, d_tokens, d_positions, d_valid,
                    self.draft_kv, d_pt,
                )

    def _run_decode_spec_draft(
        self, reqs: list[Request], mixed: bool = False
    ) -> list[StepOutput]:
        """One draft-model spec step: a single fused program runs draft
        catch-up (the tokens accepted since the draft's last committed
        position) + S greedy draft proposals + the target verify forward
        + ON-DEVICE acceptance (bit-exact argmax for greedy rows, exact
        rejection sampling otherwise — sampling.spec_accept_step). Per
        request 1..S+1 tokens land per step. Composes with the overlap
        pipeline: when the batch is stable the NEXT spec dispatch chains
        off this one's device outputs (accepted window = out_ids masked
        by n_acc) before this one's ids reach the host."""
        s = self.config.spec_draft_tokens
        w = s + 1
        mp = self.config.max_pages_per_seq
        cap_tokens = mp * self.config.page_size
        for req in reqs:
            if req.num_tokens + s > min(cap_tokens, self.config.max_context):
                self._discard_inflight_spec("window over context cap")
                return self._run_decode_plain(reqs, mixed=mixed)
        if not self._grow_pages_for(reqs, s):
            self._discard_inflight_spec("page pressure")
            return self._run_decode_plain(reqs, mixed=mixed)
        if self._inflight is not None:
            # a plain speculative dispatch (primed during a cooldown)
            # cannot serve the verify path
            self._discard_inflight("spec verify owns the decode batch")
        spans = [
            (req, req.num_tokens - 1)
            for req in reqs
            if req.num_tokens - req.spec_draft_pos > w
        ]
        if spans:
            self._spec_draft_cover(spans)
        b_bucket = self.config.decode_bucket_for(len(reqs))
        inflight, self._inflight_spec = self._inflight_spec, None
        if inflight is not None:
            if self._spec_inflight_matches(inflight, reqs):
                # the chained dispatch IS this step: chain the next one
                # (device never drains), then materialize the lagged ids
                self.metrics.overlap_hits += 1
                self._maybe_chain_spec(
                    reqs, b_bucket, inflight.out_ids, inflight.n_acc,
                    inflight.counters_v0, greedy=inflight.greedy,
                    bias=inflight.bias,
                )
                t1 = time.perf_counter()
                out = np.asarray(inflight.out_ids)
                drafts = np.asarray(inflight.draft_ids)
                n_acc = np.asarray(inflight.n_acc)
                self.metrics.time_decode_sync_ms += (
                    time.perf_counter() - t1
                ) * 1000.0
                return self._spec_postprocess(
                    reqs, out, drafts, n_acc, mixed=mixed
                )
            self._inflight_spec = inflight
            self._discard_inflight_spec("decode batch changed")
        t0 = time.perf_counter()
        win_tokens = np.zeros((b_bucket, w), np.int32)
        win_len = np.zeros(b_bucket, np.int32)
        pos0 = np.zeros(b_bucket, np.int32)
        pt = np.zeros((b_bucket, mp), np.int32)
        for i, req in enumerate(reqs):
            toks = req.all_tokens[req.spec_draft_pos :]
            win_tokens[i, : len(toks)] = toks
            win_len[i] = len(toks)
            pos0[i] = req.spec_draft_pos
            pt[i, : len(req.pages)] = req.pages
        samp, all_greedy = self._sampling_arrays(reqs, pad_to=b_bucket)
        pen = self._batch_penalty_bucket(reqs)
        pen_args = self._penalty_arrays(reqs, b_bucket, pen) if pen else ()
        bias = self._batch_bias(reqs)
        bias_kwargs = self._bias_arrays(reqs, b_bucket) if bias else {}
        host = {
            "base": (win_tokens, win_len, pos0, pt),
            "samp": samp, "pen": pen_args, "bias": bias_kwargs,
        }
        dev = self._dev_tree(host)
        d_tokens, d_len, d_pos0, d_pt = dev["base"]
        fn = self._get_step_fn(
            "spec_fused", b_bucket, w, greedy=all_greedy, pen=pen,
            bias=bias,
        )
        out_ids, draft_ids, n_acc, self.kv, self.draft_kv = fn(
            self.params, self.draft_params, d_tokens, d_len, d_pos0,
            self.kv, self.draft_kv, d_pt, *dev["samp"], *dev["pen"],
            **dev["bias"],
        )
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        # keep the device busy past this step BEFORE blocking on its
        # result (same discipline as _run_decode_plain)
        self._maybe_chain_spec(
            reqs, b_bucket, out_ids, n_acc, samp[4],
            greedy=all_greedy, bias=bias,
        )
        t1 = time.perf_counter()
        out = np.asarray(out_ids)
        drafts = np.asarray(draft_ids)
        n_acc_h = np.asarray(n_acc)
        self.metrics.time_decode_sync_ms += (
            time.perf_counter() - t1
        ) * 1000.0
        return self._spec_postprocess(reqs, out, drafts, n_acc_h, mixed=mixed)

    def _spec_postprocess(
        self, reqs: list[Request], out: np.ndarray, drafts: np.ndarray,
        n_acc: np.ndarray, mixed: bool = False,
    ) -> list[StepOutput]:
        """Host half of a draft-spec step: the same accept loop as the
        prompt-lookup path (accept matched drafts + the device's token at
        the first mismatch — the on-device scan already made out[i, j]
        the canonical token at each position), plus chain validation: a
        finish/stop truncation the device could not see invalidates the
        chained next dispatch."""
        t0 = time.perf_counter()
        s = self.config.spec_draft_tokens
        outputs: list[StepOutput] = []
        step_drafted = step_accepted = 0
        chain = self._inflight_spec  # the dispatch chained for the NEXT step
        chain_ok = chain is not None
        for i, req in enumerate(reqs):
            accepted: list[int] = []
            finish: Optional[FinishReason] = None
            for j in range(s + 1):
                tok = int(out[i, j])
                accepted.append(tok)
                finish = self._finish_reason_for(req, tok, len(accepted))
                if finish is not None:
                    break
                if j < s and int(drafts[i, j]) != tok:
                    break
            step_drafted += s
            step_accepted += len(accepted) - 1
            # catch-up committed through the old last token; the accepted
            # tokens are the next step's window
            req.spec_draft_pos = req.num_tokens
            req.num_computed_tokens += len(accepted)
            if finish is not None or len(accepted) != int(n_acc[i]):
                chain_ok = False
            outputs.extend(
                self._accept_tokens(
                    req, accepted, finish, mixed=mixed, spec=True
                )
            )
            self._register_pages(req)
        self._note_spec_step(step_drafted, step_accepted)
        if chain is not None:
            if chain_ok:
                chain.expected_num_tokens = tuple(
                    r.num_tokens for r in reqs
                )
                chain.expected_out_len = tuple(
                    len(r.output_tokens) for r in reqs
                )
            else:
                self._discard_inflight_spec("acceptance diverged or finish")
        if (
            step_drafted
            and step_accepted / step_drafted
            < self.config.spec_min_accept_rate
        ):
            # the draft is missing on this workload: fall back to the
            # plain (overlapped/fused) path for a while, then probe again
            self._spec_cooldown = self.config.spec_cooldown_steps
            self._discard_inflight_spec("acceptance cooldown")
        self.metrics.time_decode_host_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        return outputs

    def _maybe_chain_spec(
        self, reqs: list[Request], b_bucket: int, out_ids, n_acc,
        counters_v0, greedy: bool, bias: bool,
    ) -> None:
        """Dispatch the NEXT spec step before the pending one's ids reach
        the host: its catch-up window is the pending step's accepted
        tokens, derived ON DEVICE from (out_ids, n_acc) — the same
        token-feedback trick the plain overlap loop uses, generalized to
        a data-dependent window length. Only when the scheduler
        guarantees batch stability (mixed steps count: the chained
        dispatch lands as the decode leg of the next mixed step), no
        request can finish inside the pending window's worst case, pages
        can pre-grow to cover both windows, and no penalty history (host
        state) is in play."""
        if not self._overlap_enabled:
            return
        if not self.scheduler.decode_batch_stable():
            if not (
                self._mixed_enabled
                and self.scheduler.decode_rows_stable(reqs)
            ):
                return
        if self._batch_penalty_bucket(reqs):
            return
        s = self.config.spec_draft_tokens
        w = s + 1
        cap = min(
            self.config.max_context,
            self.config.max_pages_per_seq * self.config.page_size,
        )
        for req in reqs:
            sp = req.sampling
            if (
                len(req.output_tokens) + req.num_emitted + w
                >= sp.max_tokens
            ):
                return  # the pending step may finish it
            if req.num_tokens + w + s > cap:
                return
        if not self._grow_pages_for(reqs, 2 * s + 1):
            return
        t0 = time.perf_counter()
        mp = self.config.max_pages_per_seq
        pos0 = np.zeros(b_bucket, np.int32)
        pt = np.zeros((b_bucket, mp), np.int32)
        for i, req in enumerate(reqs):
            pos0[i] = req.num_tokens  # accepted tokens land at n, n+1, …
            pt[i, : len(req.pages)] = req.pages
        samp, _ = self._sampling_arrays(reqs, pad_to=b_bucket)
        bias_kwargs = self._bias_arrays(reqs, b_bucket) if bias else {}
        host = {"base": (pos0, pt), "samp": samp[:4], "bias": bias_kwargs}
        try:
            dev = self._dev_tree(host)
            d_pos0, d_pt = dev["base"]
            # verify-start counters advance by the pending acceptance —
            # a device add, no host round-trip
            cv0 = jnp.asarray(counters_v0) + n_acc
            fn = self._get_step_fn(
                "spec_fused", b_bucket, w, greedy=greedy, pen=0, bias=bias,
            )
            out2, drafts2, nacc2, self.kv, self.draft_kv = fn(
                self.params, self.draft_params, out_ids, n_acc, d_pos0,
                self.kv, self.draft_kv, d_pt, *dev["samp"], cv0,
                **dev["bias"],
            )
        except Exception:
            # a failed chained dispatch must never take down the real
            # step it was riding on: latch the pipeline off
            logger.exception(
                "chained spec dispatch failed; disabling overlap_decode"
            )
            self._overlap_enabled = False
            return
        for arr in (out2, drafts2, nacc2):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass  # older jax array types; np.asarray will sync-copy
        self.metrics.overlap_dispatches += 1
        self._inflight_spec = _InflightSpec(
            reqs=tuple(reqs),
            b_bucket=b_bucket,
            out_ids=out2,
            draft_ids=drafts2,
            n_acc=nacc2,
            counters_v0=cv0,
            greedy=greedy,
            bias=bias,
        )
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0

    def _spec_inflight_matches(
        self, inflight: _InflightSpec, reqs: list[Request]
    ) -> bool:
        """A chained spec dispatch is this step iff the previous step's
        postprocess validated it (host acceptance == device n_acc, no
        finish) and the batch is the same requests, each advanced
        exactly as validated."""
        if inflight.expected_num_tokens is None:
            return False
        if len(reqs) != len(inflight.reqs):
            return False
        for r, spec_r, exp_nt, exp_out in zip(
            reqs, inflight.reqs, inflight.expected_num_tokens,
            inflight.expected_out_len,
        ):
            if (
                r is not spec_r
                or r.num_tokens != exp_nt
                or len(r.output_tokens) != exp_out
            ):
                return False
        return True

    def _discard_inflight_spec(self, why: str) -> None:
        """Roll back a chained spec dispatch. Like _discard_inflight, the
        sampled ids are overshoot and its KV writes (target AND draft
        pool) are benign: surviving requests' true tokens overwrite
        those positions before any read, and freed pages' next owners
        fully overwrite them."""
        inflight, self._inflight_spec = self._inflight_spec, None
        if inflight is None:
            return
        self.metrics.overlap_rollbacks += 1
        logger.debug("spec chain rollback: %s", why)

    def _run_decode(self, batch: ScheduledBatch) -> list[StepOutput]:
        reqs = list(batch.decode)
        if self._spec_active(reqs):
            if self._spec_draft:
                return self._run_decode_spec_draft(reqs)
            return self._run_decode_spec(reqs)
        if self._inflight_spec is not None:
            # cooldown or an ineligible batch routes to the plain path:
            # a chained spec dispatch can never land there
            self._discard_inflight_spec("speculation inactive")
        return self._run_decode_plain(reqs)

    def _run_decode_plain(
        self, reqs: list[Request], mixed: bool = False
    ) -> list[StepOutput]:
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            if self._inflight_matches(inflight, reqs):
                return self._consume_inflight(inflight, mixed=mixed)
            self._inflight = inflight  # hand back for the metrics/log
            self._discard_inflight("decode batch changed")
        t0 = time.perf_counter()
        b_bucket = self.config.decode_bucket_for(len(reqs))
        mp = self.config.max_pages_per_seq
        # On-device K-step window first (config.decode_kstep): finish
        # conditions evaluate ON DEVICE, so no overshoot compute past a
        # stop; k_win == 1 falls through to the classic path (which is
        # then bit-identical to a decode_kstep-free build).
        k_win = self._pick_kstep(reqs)
        k_steps = k_win if k_win > 1 else self._pick_decode_steps(reqs)
        tokens = np.zeros((b_bucket, 1), np.int32)
        positions = np.zeros((b_bucket, 1), np.int32)
        valid = np.zeros((b_bucket, 1), bool)
        pt = np.zeros((b_bucket, mp), np.int32)
        for i, req in enumerate(reqs):
            tokens[i, 0] = req.all_tokens[-1]
            positions[i, 0] = req.num_tokens - 1
            valid[i, 0] = True
            pt[i, : len(req.pages)] = req.pages

        samp, all_greedy = self._sampling_arrays(reqs, pad_to=b_bucket)
        lp = self._batch_logprobs(reqs)
        pen = self._batch_penalty_bucket(reqs)
        pen_args = (
            self._penalty_arrays(reqs, b_bucket, pen) if pen else ()
        )
        bias = self._batch_bias(reqs)
        bias_kwargs = self._bias_arrays(reqs, b_bucket) if bias else {}
        host = {
            "base": (tokens, positions, valid, pt), "samp": samp,
            "pen": pen_args, "bias": bias_kwargs,
        }
        if k_win > 1:
            host["stops"], host["budgets"] = self._kstep_arrays(
                reqs, b_bucket
            )
        elif k_steps == 1:
            host["last"] = np.zeros(b_bucket, np.int32)
        dev = self._dev_tree(host)
        samp, pen_args, bias_kwargs = dev["samp"], dev["pen"], dev["bias"]
        d_tokens, d_positions, d_valid, d_pt = dev["base"]
        args = (self.params, d_tokens, d_positions, d_valid, self.kv, d_pt)
        lp_data = None
        n_emit_dev = None
        if k_win > 1:
            # logprobs rows never reach here (_pick_kstep falls back),
            # so the family has no lp variant
            fn = self._get_step_fn(
                "decode_kstep", b_bucket, k_steps, greedy=all_greedy,
                lp=-1, pen=pen, bias=bias,
            )
            token_ids, n_emit_dev, self.kv = fn(
                *args, dev["stops"], dev["budgets"], *samp, *pen_args,
                **bias_kwargs,
            )
            m = self.metrics
            m.kstep_windows += 1
            m.kstep_steps += k_steps
            m.kstep_window_size = k_steps
            self._kstep_live = k_steps
        elif k_steps == 1:
            fn = self._get_step_fn(
                "decode", b_bucket, 1, greedy=all_greedy, lp=lp, pen=pen,
                bias=bias,
            )
            if lp >= 0:
                token_ids, lp_data, self.kv = fn(
                    *args, dev["last"], *samp, *pen_args,
                    **bias_kwargs,
                )
            else:
                token_ids, self.kv = fn(
                    *args, dev["last"], *samp, *pen_args,
                    **bias_kwargs,
                )
        else:
            fn = self._get_step_fn(
                "decode_multi", b_bucket, k_steps, greedy=all_greedy, lp=lp,
                pen=pen, bias=bias,
            )
            if lp >= 0:
                token_ids, lp_data, self.kv = fn(
                    *args, *samp, *pen_args, **bias_kwargs
                )
            else:
                token_ids, self.kv = fn(
                    *args, *samp, *pen_args, **bias_kwargs
                )  # [K, B]
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        # Keep the device busy past this step BEFORE blocking on its
        # result: the speculated N+1 dispatch computes while the host
        # scans this step's ids for stops below.
        self._maybe_speculate(
            reqs, b_bucket, k_steps, token_ids,
            greedy=all_greedy, lp=lp, bias=bias, kstep=k_win > 1,
        )
        t1 = time.perf_counter()
        ids = np.asarray(token_ids).reshape(k_steps, b_bucket)
        lp_arrays = self._materialize_lp(lp_data, k_steps, b_bucket)
        self.metrics.time_decode_sync_ms += (
            time.perf_counter() - t1
        ) * 1000.0
        if k_win > 1:
            # window wall (dispatch+sync) is the measured column for the
            # decode_kstep family's attainment; /k is the per-step time
            # the stall histogram spreads window emissions by
            window_ms = (time.perf_counter() - t0) * 1000.0
            self.metrics.time_kstep_ms += window_ms
            self._kstep_step_ms = window_ms / k_steps
            outputs = self._decode_postprocess(
                reqs, k_steps, ids, lp_arrays, mixed=mixed, kstep=True
            )
            # device freeze decisions vs the host finish scan: they are
            # the same arithmetic — disagreement means a program bug, so
            # surface it loudly rather than silently trusting either
            host_emitted = sum(len(o.new_token_ids) for o in outputs)
            dev_emitted = int(np.asarray(n_emit_dev)[: len(reqs)].sum())
            if host_emitted != dev_emitted:
                logger.warning(
                    "decode_kstep window disagreement: device emitted "
                    "%d tokens, host accepted %d (K=%d, B=%d)",
                    dev_emitted, host_emitted, k_steps, len(reqs),
                )
            return outputs
        return self._decode_postprocess(
            reqs, k_steps, ids, lp_arrays, mixed=mixed
        )

    @staticmethod
    def _materialize_lp(lp_data, k_steps: int, b_bucket: int):
        """Device logprob outputs -> host (chosen, top_ids, top_lps),
        reshaped to [K, B(, N)]; None passes through."""
        if lp_data is None:
            return None
        return (
            np.asarray(lp_data[0]).reshape(k_steps, b_bucket),
            np.asarray(lp_data[1]).reshape(k_steps, b_bucket, -1),
            np.asarray(lp_data[2]).reshape(k_steps, b_bucket, -1),
        )

    def _decode_postprocess(
        self, reqs: list[Request], k_steps: int, ids: np.ndarray, lp_arrays,
        mixed: bool = False, kstep: bool = False,
    ) -> list[StepOutput]:
        """Host half of a decode step: scan sampled ids for finish
        conditions (dropping overshoot past a stop), append accepted
        tokens, and register newly filled pages. Under overlap_decode
        this runs while the device computes the NEXT step."""
        t0 = time.perf_counter()
        outputs: list[StepOutput] = []
        for i, req in enumerate(reqs):
            accepted: list[int] = []
            finish: Optional[FinishReason] = None
            for kk in range(k_steps):
                accepted.append(int(ids[kk, i]))
                finish = self._finish_reason_for(req, int(ids[kk, i]),
                                                 len(accepted))
                if finish is not None:
                    break
            req.num_computed_tokens += len(accepted)
            lps = tops = None
            if lp_arrays is not None and req.sampling.logprobs >= 0:
                chosen_lp, top_ids, top_lps = lp_arrays
                n = len(accepted)
                lps = tuple(float(chosen_lp[kk, i]) for kk in range(n))
                nk = req.sampling.logprobs
                if nk > 0:
                    tops = tuple(
                        tuple(
                            (int(top_ids[kk, i, j]), float(top_lps[kk, i, j]))
                            for j in range(min(nk, top_ids.shape[-1]))
                        )
                        for kk in range(n)
                    )
            outputs.extend(
                self._accept_tokens(
                    req, accepted, finish, lps=lps, tops=tops, mixed=mixed,
                    kstep=kstep,
                )
            )
            self._register_pages(req)
        self.metrics.time_decode_host_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        return outputs

    # -- mixed prefill+decode steps ----------------------------------------

    def _run_mixed(self, batch: ScheduledBatch) -> list[StepOutput]:
        """One stall-free step: a bounded prefill chunk AND the decode
        batch fused into a single XLA program — one `_dev_tree` transfer,
        one readback. Decode rows ride the same [B, 1] page-walk path as
        a pure decode step and prefill pieces the same [B, T] chunk path
        as a pure prefill step (pages are per-request disjoint, so the
        halves cannot read each other's writes) — greedy token streams
        are bit-exact vs the XOR scheduler (tests/test_engine_mixed.py).

        Two cases run the halves as separate dispatches instead (same
        semantics, same streams): a matching speculative in-flight decode
        — mixed steps count as decode steps for the overlap pipeline, so
        the speculated ids land as the decode half and the prefill chunk
        dispatches beside them — and multimodal pieces (the fused program
        has no mm variant)."""
        reqs_d = list(batch.decode)
        pieces = list(batch.prefill)
        if self._spec_draft and self._spec_active(reqs_d):
            # Speculation composes with mixed steps: the fused
            # draft+verify program runs as the DECODE LEG beside the
            # prefill chunk (two dispatches, same stall-free semantics —
            # decode rows emit 1..S+1 tokens while the backlog drains;
            # the chained spec dispatch consumes/primes exactly as in
            # pure decode). The prefill half rides _run_prefill, which
            # also keeps the draft pool covered for the pieces.
            self.metrics.prefill_dispatches += 1
            outputs = self._run_prefill(
                ScheduledBatch(kind="prefill", prefill=batch.prefill),
                mixed=True,
            )
            outputs += self._run_decode_spec_draft(reqs_d, mixed=True)
            return outputs
        if self._inflight_spec is not None:
            self._discard_inflight_spec("speculation inactive")
        inflight = self._inflight
        use_inflight = inflight is not None and self._inflight_matches(
            inflight, reqs_d
        )
        any_mm = any(p.request.mm_embeds is not None for p in pieces)
        # K-step windows compose with mixed steps as the decode LEG
        # beside the prefill chunk (two dispatches, same semantics):
        # the fused mixed program has no kstep variant, and the window
        # path handles its own stops/budgets/runway host arrays.
        if use_inflight or any_mm or self._kstep_candidate(reqs_d):
            self.metrics.prefill_dispatches += 1
            outputs = self._run_prefill(
                ScheduledBatch(kind="prefill", prefill=batch.prefill),
                mixed=True,
            )
            # consumes (or rolls back) the inflight itself and re-primes
            # the pipeline when the decode rows stay stable
            outputs += self._run_decode_plain(reqs_d, mixed=True)
            return outputs
        if inflight is not None:
            self._discard_inflight("mixed composition changed")

        # Pieces must run under EXACTLY the (T bucket, first_chunk)
        # program variants the XOR scheduler would pick — that variant
        # match is what makes the bit-exactness guarantee structural
        # rather than a numerics claim about padded masking. Group like
        # _run_prefill does, fuse the largest-T group (the bulk of the
        # work) with the decode batch, and dispatch any remaining groups
        # through the plain prefill path beside it.
        groups: dict[int, list] = {}
        for piece in pieces:
            groups.setdefault(self._bucket_t(piece.length), []).append(piece)
        t_bucket = max(groups)
        fuse_pieces = groups.pop(t_bucket)
        rest = [p for g in groups.values() for p in g]
        outputs_rest: list[StepOutput] = []
        if rest:
            self.metrics.prefill_dispatches += 1
            outputs_rest = self._run_prefill(
                ScheduledBatch(kind="prefill", prefill=tuple(rest)),
                mixed=True,
            )
        pieces = fuse_pieces

        t0 = time.perf_counter()
        b_dec = self.config.decode_bucket_for(len(reqs_d))
        mp = self.config.max_pages_per_seq
        # decode half: identical arrays to a k=1 decode step
        d_tokens = np.zeros((b_dec, 1), np.int32)
        d_positions = np.zeros((b_dec, 1), np.int32)
        d_valid = np.zeros((b_dec, 1), bool)
        d_pt = np.zeros((b_dec, mp), np.int32)
        for i, req in enumerate(reqs_d):
            d_tokens[i, 0] = req.all_tokens[-1]
            d_positions[i, 0] = req.num_tokens - 1
            d_valid[i, 0] = True
            d_pt[i, : len(req.pages)] = req.pages
        # prefill half: one T-bucket group per fused program keeps the
        # compile family at (b_decode_bucket, t_prefill_bucket,
        # b_prefill_bucket)
        b_pre = self._bucket_b(len(pieces))
        p_tokens = np.zeros((b_pre, t_bucket), np.int32)
        p_positions = np.zeros((b_pre, t_bucket), np.int32)
        p_valid = np.zeros((b_pre, t_bucket), bool)
        p_pt = np.zeros((b_pre, mp), np.int32)
        last_idx = np.zeros(b_pre, np.int32)
        any_last = False
        for i, piece in enumerate(pieces):
            req = piece.request
            chunk = req.all_tokens[piece.start : piece.start + piece.length]
            p_tokens[i, : piece.length] = chunk
            p_positions[i] = np.arange(t_bucket, dtype=np.int32) + piece.start
            p_valid[i, : piece.length] = True
            p_pt[i, : len(req.pages)] = req.pages
            last_idx[i] = piece.length - 1
            if piece.start + piece.length >= len(req.prompt_tokens):
                any_last = True
        first_chunk = all(p.start == 0 for p in pieces)
        # sampled row space: decode rows [0, b_dec); when a piece
        # completes its prompt, prefill rows join at [b_dec, b_dec+b_pre)
        pre_reqs = [p.request for p in pieces]
        samp_d, greedy_d = self._sampling_arrays(reqs_d, pad_to=b_dec)
        if any_last:
            samp_p, greedy_p = self._sampling_arrays(pre_reqs, pad_to=b_pre)
            samp = tuple(
                np.concatenate([a, b]) for a, b in zip(samp_d, samp_p)
            )
            all_greedy = greedy_d and greedy_p
            row_reqs = reqs_d + pre_reqs
        else:
            samp, all_greedy, row_reqs = samp_d, greedy_d, reqs_d
        lp = self._batch_logprobs(row_reqs)
        pen = self._batch_penalty_bucket(row_reqs)
        if pen:
            pen_d = self._penalty_arrays(reqs_d, b_dec, pen)
            if any_last:
                pen_p = self._penalty_arrays(pre_reqs, b_pre, pen)
                pen_args = tuple(
                    np.concatenate([a, b]) for a, b in zip(pen_d, pen_p)
                )
            else:
                pen_args = pen_d
        else:
            pen_args = ()
        bias = self._batch_bias(row_reqs)
        if bias:
            bias_d = self._bias_arrays(reqs_d, b_dec)
            if any_last:
                bias_p = self._bias_arrays(pre_reqs, b_pre)
                bias_kwargs = {
                    k: np.concatenate([bias_d[k], bias_p[k]]) for k in bias_d
                }
            else:
                bias_kwargs = bias_d
        else:
            bias_kwargs = {}

        host = {
            "based": (d_tokens, d_positions, d_valid, d_pt),
            "basep": (p_tokens, p_positions, p_valid, p_pt),
            "last": last_idx, "samp": samp, "pen": pen_args,
            "bias": bias_kwargs,
        }
        dev = self._dev_tree(host)
        fn = self._get_step_fn(
            "mixed", b_dec, t_bucket, greedy=all_greedy,
            first_chunk=first_chunk, lp=lp, pen=pen, bias=bias,
            b_pre=b_pre, psamp=any_last,
        )
        args = (
            self.params, *dev["based"][:3], self.kv, dev["based"][3],
            *dev["basep"], dev["last"],
        )
        lp_data = None
        if lp >= 0:
            token_ids, lp_data, self.kv = fn(
                *args, *dev["samp"], *dev["pen"], **dev["bias"]
            )
        else:
            token_ids, self.kv = fn(
                *args, *dev["samp"], *dev["pen"], **dev["bias"]
            )
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        if not any_last:
            # No piece joins decode this step, so the decode rows are
            # stable: keep the pipeline primed — the speculated dispatch
            # lands as the decode half of the NEXT mixed (or decode) step.
            self._maybe_speculate(
                reqs_d, b_dec, 1, token_ids,
                greedy=greedy_d, lp=lp, bias=bias,
            )
        t1 = time.perf_counter()
        ids = np.asarray(token_ids)  # [b_dec] or [b_dec + b_pre]
        lp_arrays = self._materialize_lp(lp_data, 1, ids.shape[0])
        self.metrics.time_decode_sync_ms += (
            time.perf_counter() - t1
        ) * 1000.0
        d_lp = None
        if lp_arrays is not None:
            d_lp = tuple(a[:, :b_dec] for a in lp_arrays)
        outputs = outputs_rest + self._decode_postprocess(
            reqs_d, 1, ids[None, :b_dec], d_lp, mixed=True
        )
        for i, piece in enumerate(pieces):
            req = piece.request
            req.num_computed_tokens += piece.length
            self._register_pages(req)
            if req.prefill_done:
                req.state = RequestState.DECODE
                lps = tops = None
                if lp_arrays is not None and req.sampling.logprobs >= 0:
                    row = b_dec + i
                    lps = (float(lp_arrays[0][0, row]),)
                    nk = req.sampling.logprobs
                    if nk > 0:
                        tops = (
                            tuple(
                                (
                                    int(lp_arrays[1][0, row, j]),
                                    float(lp_arrays[2][0, row, j]),
                                )
                                for j in range(
                                    min(nk, lp_arrays[1].shape[-1])
                                )
                            ),
                        )
                outputs.extend(
                    self._accept_token(
                        req, int(ids[b_dec + i]), first=True, lps=lps,
                        tops=tops, mixed=True,
                    )
                )
        return outputs

    # -- overlapped decode (one-step-lagged readback) ----------------------

    def _maybe_speculate(
        self, reqs: list[Request], b_bucket: int, k_prev: int, ids_dev,
        greedy: bool, lp: int, bias: bool, kstep: bool = False,
    ) -> None:
        """Dispatch the NEXT decode step before the pending step's ids
        reach the host: same batch, positions advanced by k_prev, tokens
        = the pending step's last sampled ids sliced ON DEVICE (no host
        round-trip). Only when the scheduler guarantees batch stability
        (no admissible waiting request, nothing mid-prefill), every
        request surely survives the pending step's k_prev tokens, pages
        can pre-grow to cover the window, and no penalty history (which
        would need the pending tokens host-side) is in play."""
        if not self._overlap_enabled:
            return
        if not self.scheduler.decode_batch_stable():
            # Mixed mode: pending prefill work doesn't stall the decode
            # rows — a speculative decode dispatch still lands as the
            # decode half of the next mixed step, provided the row set
            # itself is stable (no admissible arrival, no piece joining
            # decode). Callers that know a piece completes this step
            # skip speculation before getting here.
            if not (
                self._mixed_enabled
                and self.scheduler.decode_rows_stable(reqs)
            ):
                return
        if self._batch_penalty_bucket(reqs):
            return
        cap = min(
            self.config.max_context,
            self.config.max_pages_per_seq * self.config.page_size,
        )
        k_next = k_prev
        for req in reqs:
            s = req.sampling
            if (
                len(req.output_tokens) + req.num_emitted + k_prev
                >= s.max_tokens
            ):
                return  # pending step finishes it: batch will change
            if req.num_tokens + k_prev >= self.config.max_context:
                return
            # never write KV past the page-table cap
            k_next = min(k_next, cap - (req.num_tokens + k_prev) + 1)
        if k_next < 1:
            return
        k_next = self._pow2_floor(k_next)  # reuse the program family
        if not self._grow_pages_for(reqs, k_prev + k_next - 1):
            return
        t0 = time.perf_counter()
        mp = self.config.max_pages_per_seq
        positions = np.zeros((b_bucket, 1), np.int32)
        valid = np.zeros((b_bucket, 1), bool)
        pt = np.zeros((b_bucket, mp), np.int32)
        for i, req in enumerate(reqs):
            positions[i, 0] = req.num_tokens - 1 + k_prev
            valid[i, 0] = True
            pt[i, : len(req.pages)] = req.pages
        samp, _ = self._sampling_arrays(reqs, pad_to=b_bucket)
        # the pending step advances every draw counter by its k
        samp[4][: len(reqs)] += k_prev
        bias_kwargs = self._bias_arrays(reqs, b_bucket) if bias else {}
        host = {
            "base": (positions, valid, pt), "samp": samp,
            "bias": bias_kwargs,
        }
        use_kstep = kstep and k_next > 1
        if use_kstep:
            # chain the next K-window through the SAME decode_kstep
            # family: budgets discount the pending window's k_prev
            # tokens (the early-outs above already guarantee no row
            # LENGTH-finishes inside the pending window; a sampled stop
            # still rolls the chained window back at consume time)
            host["stops"], host["budgets"] = self._kstep_arrays(
                reqs, b_bucket, emitted_ahead=k_prev
            )
        elif k_next == 1:
            host["last"] = np.zeros(b_bucket, np.int32)
        try:
            dev = self._dev_tree(host)
            d_positions, d_valid, d_pt = dev["base"]
            # on-device token feedback: [B] or [K, B] -> last step [B, 1]
            d_tokens = (
                ids_dev if ids_dev.ndim == 2 else ids_dev[None]
            )[-1][:, None].astype(jnp.int32)
            args = (
                self.params, d_tokens, d_positions, d_valid, self.kv, d_pt
            )
            lp_data = None
            if use_kstep:
                # kstep eligibility pinned lp == -1 at the original
                # dispatch; the chained window inherits it
                fn = self._get_step_fn(
                    "decode_kstep", b_bucket, k_next, greedy=greedy,
                    lp=-1, pen=0, bias=bias,
                )
                token_ids, _n_emit, self.kv = fn(
                    *args, dev["stops"], dev["budgets"], *dev["samp"],
                    **dev["bias"]
                )
                m = self.metrics
                m.kstep_windows += 1
                m.kstep_steps += k_next
                m.kstep_window_size = k_next
                self._kstep_live = k_next
            elif k_next == 1:
                fn = self._get_step_fn(
                    "decode", b_bucket, 1, greedy=greedy, lp=lp, pen=0,
                    bias=bias,
                )
                if lp >= 0:
                    token_ids, lp_data, self.kv = fn(
                        *args, dev["last"], *dev["samp"], **dev["bias"]
                    )
                else:
                    token_ids, self.kv = fn(
                        *args, dev["last"], *dev["samp"], **dev["bias"]
                    )
            else:
                fn = self._get_step_fn(
                    "decode_multi", b_bucket, k_next, greedy=greedy, lp=lp,
                    pen=0, bias=bias,
                )
                if lp >= 0:
                    token_ids, lp_data, self.kv = fn(
                        *args, *dev["samp"], **dev["bias"]
                    )
                else:
                    token_ids, self.kv = fn(
                        *args, *dev["samp"], **dev["bias"]
                    )
        except Exception:
            # A failed speculative dispatch must never take down the real
            # step it was riding on: latch overlap off for this engine.
            logger.exception(
                "overlap dispatch failed; disabling overlap_decode"
            )
            self._overlap_enabled = False
            return
        # one-step-lagged readback: start the device→host copy now so the
        # next step's sync finds the bytes already landed
        for arr in (token_ids, *(lp_data or ())):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass  # older jax array types; np.asarray will sync-copy
        self.metrics.overlap_dispatches += 1
        self._inflight = _InflightDecode(
            reqs=tuple(reqs),
            b_bucket=b_bucket,
            k_steps=k_next,
            token_ids=token_ids,
            lp_data=lp_data,
            expected_num_tokens=tuple(r.num_tokens + k_prev for r in reqs),
            expected_out_len=tuple(
                len(r.output_tokens) + k_prev for r in reqs
            ),
            greedy=greedy,
            lp=lp,
            bias=bias,
            kstep=use_kstep,
        )
        self.metrics.time_decode_dispatch_ms += (
            time.perf_counter() - t0
        ) * 1000.0

    def _inflight_matches(
        self, inflight: _InflightDecode, reqs: list[Request]
    ) -> bool:
        """The speculation is this step iff the scheduled batch is the
        SAME requests (identity — an aborted+resubmitted id is a new
        object) in the same rows, and each advanced exactly the pending
        step's k tokens (a preemption/recompute resets output_tokens and
        fails here even though num_tokens survives the fold)."""
        if len(reqs) != len(inflight.reqs):
            return False
        for r, spec_r, exp_nt, exp_out in zip(
            reqs, inflight.reqs, inflight.expected_num_tokens,
            inflight.expected_out_len,
        ):
            if (
                r is not spec_r
                or r.num_tokens != exp_nt
                or len(r.output_tokens) != exp_out
            ):
                return False
        return True

    def _consume_inflight(
        self, inflight: _InflightDecode, mixed: bool = False
    ) -> list[StepOutput]:
        """The speculated dispatch IS this step: speculate the next one
        (so the device never drains), then materialize the one-step-
        lagged ids — their async copy started last step, so this sync is
        (near) free — and postprocess."""
        self.metrics.overlap_hits += 1
        reqs = list(inflight.reqs)
        self._maybe_speculate(
            reqs, inflight.b_bucket, inflight.k_steps, inflight.token_ids,
            greedy=inflight.greedy, lp=inflight.lp, bias=inflight.bias,
            kstep=inflight.kstep,
        )
        t0 = time.perf_counter()
        ids = np.asarray(inflight.token_ids).reshape(
            inflight.k_steps, inflight.b_bucket
        )
        lp_arrays = self._materialize_lp(
            inflight.lp_data, inflight.k_steps, inflight.b_bucket
        )
        self.metrics.time_decode_sync_ms += (
            time.perf_counter() - t0
        ) * 1000.0
        return self._decode_postprocess(
            reqs, inflight.k_steps, ids, lp_arrays, mixed=mixed,
            kstep=inflight.kstep,
        )

    def _discard_inflight(self, why: str) -> None:
        """Roll back a speculated dispatch. The sampled ids are overshoot
        — dropped exactly like decode_multi's post-stop tokens. Its KV
        writes are benign: for surviving requests they used the true
        tokens at the true positions (the real dispatch overwrites them
        before any read); for finished/preempted requests they sit in
        released pages whose next owner's writes are stream-ordered
        after them. Pages grown for the window stay with their requests."""
        inflight, self._inflight = self._inflight, None
        if inflight is None:
            return
        self.metrics.overlap_rollbacks += 1
        logger.debug("overlap rollback: %s", why)

    def drain_overlap(self) -> None:
        """Public: discard any speculative in-flight decode dispatch
        (idle/stop paths; also pins the sync/overlap boundary in tests)."""
        self._discard_inflight("drained")
        self._discard_inflight_spec("drained")

    # -- shared ------------------------------------------------------------

    @staticmethod
    def _batch_logprobs(reqs: list[Request]) -> int:
        """Program-variant selector: -1 when no request wants logprobs,
        else the largest top-N requested (the program computes one top-k;
        per-request N slices it host-side). Snapped to the small OpenAI
        range {0,1,..,20} so the compile family stays bounded."""
        lp = -1
        for r in reqs:
            lp = max(lp, min(r.sampling.logprobs, 20))
        return lp

    @staticmethod
    def _penalty_history(req: Request) -> list[int]:
        """Every token this request has GENERATED — the history the OpenAI
        penalties run over. Preemption-by-recompute folds generated tokens
        into prompt_tokens (scheduler._preempt_youngest); num_emitted counts
        them, so the folded tail stays part of the history."""
        hist = req.output_tokens
        if req.num_emitted:
            hist = req.prompt_tokens[-req.num_emitted :] + hist
        return hist

    def _batch_penalty_bucket(self, reqs: list[Request]) -> int:
        """0 when no request carries a frequency/presence penalty; else the
        generated-history bucket O (power of two) the penalty programs
        index. The bucket, not the batch, keys the program variant — the
        family grows log2(max_tokens) deep."""
        if not any(
            r.sampling.frequency_penalty
            or r.sampling.presence_penalty
            or r.sampling.repetition_penalty != 1.0
            for r in reqs
        ):
            return 0
        longest = max(len(self._penalty_history(r)) for r in reqs)
        o = 1
        while o < max(1, longest):
            o *= 2
        return o

    def _penalty_arrays(self, reqs: list[Request], pad_to: int, o_bucket: int):
        """(freq [B], pres [B], rep [B], out_tokens [B, O], out_valid
        [B, O]) — the generated-token history the penalties are computed
        over. Padding rows carry rep=1 (multiplicative no-op)."""
        freq = np.zeros(pad_to, np.float32)
        pres = np.zeros(pad_to, np.float32)
        rep = np.ones(pad_to, np.float32)
        out_toks = np.zeros((pad_to, o_bucket), np.int32)
        out_valid = np.zeros((pad_to, o_bucket), bool)
        for i, r in enumerate(reqs):
            freq[i] = r.sampling.frequency_penalty
            pres[i] = r.sampling.presence_penalty
            rep[i] = r.sampling.repetition_penalty or 1.0
            hist = self._penalty_history(r)
            n = min(len(hist), o_bucket)
            if n:
                out_toks[i, :n] = hist[-n:]
                out_valid[i, :n] = True
        return (freq, pres, rep, out_toks, out_valid)

    def _validate_bias(self, sampling: Optional[SamplingParams]) -> None:
        """Reject over-limit / out-of-vocab logit_bias at admission, where
        the runner returns the error to THIS client (a failure inside
        step() would wedge the whole batch loop)."""
        if sampling is None or not (sampling.logit_bias or sampling.min_tokens):
            return
        from dynamo_tpu.engine.sampling import BIAS_SLOTS

        need = len(sampling.logit_bias or ())
        if sampling.min_tokens > 0:
            ban = set(sampling.stop_token_ids)
            if not sampling.ignore_eos:
                ban |= set(self.config.eos_token_ids)
            need += len(ban)
        if need > BIAS_SLOTS:
            raise ValueError(
                f"logit_bias entries + min_tokens eos/stop bans need "
                f"{need} slots; at most {BIAS_SLOTS} supported"
            )
        v = self.adapter.vocab_size
        for tid, _ in sampling.logit_bias or ():
            if not 0 <= tid < v:
                raise ValueError(
                    f"logit_bias token id {tid} outside vocab [0,{v})"
                )

    @staticmethod
    def _batch_bias(reqs: list[Request]) -> bool:
        """Program-variant selector for the sparse logit-bias/min_tokens
        path (sampling.apply_logit_bias)."""
        return any(
            r.sampling.logit_bias or r.sampling.min_tokens for r in reqs
        )

    def _bias_row(self, req: Request):
        """Per-request packed bias slots, computed once and cached on the
        request — the rows are invariant for its lifetime (only the
        counters vary per step, and those ride the sampling arrays)."""
        row = getattr(req, "_bias_row", None)
        if row is not None:
            return row
        from dynamo_tpu.engine.sampling import BIAS_SLOTS

        ids = np.zeros(BIAS_SLOTS, np.int32)
        vals = np.zeros(BIAS_SLOTS, np.float32)
        gated = np.zeros(BIAS_SLOTS, bool)
        s = req.sampling
        slot = 0
        for tid, bv in s.logit_bias or ():
            ids[slot] = tid
            vals[slot] = bv
            slot += 1
        if s.min_tokens > 0:
            ban = set(s.stop_token_ids)
            if not s.ignore_eos:
                ban |= set(self.config.eos_token_ids)
            for tid in sorted(ban):
                if slot >= BIAS_SLOTS:
                    break  # bounded at admission; belt and braces
                ids[slot] = tid
                vals[slot] = -1e30
                gated[slot] = True
                slot += 1
        row = (ids, vals, gated, s.min_tokens)
        req._bias_row = row
        return row

    def _bias_arrays(self, reqs: list[Request], pad_to: int) -> dict:
        """kwargs for the bias program variants: user logit_bias entries
        plus min_tokens' gated eos/stop bans packed into BIAS_SLOTS."""
        from dynamo_tpu.engine.sampling import BIAS_SLOTS

        ids = np.zeros((pad_to, BIAS_SLOTS), np.int32)
        vals = np.zeros((pad_to, BIAS_SLOTS), np.float32)
        gated = np.zeros((pad_to, BIAS_SLOTS), bool)
        mins = np.zeros(pad_to, np.int32)
        for i, r in enumerate(reqs):
            row_ids, row_vals, row_gated, row_min = self._bias_row(r)
            ids[i] = row_ids
            vals[i] = row_vals
            gated[i] = row_gated
            mins[i] = row_min
        return {
            "bias_ids": ids,
            "bias_vals": vals,
            "bias_gated": gated,
            "min_toks": mins,
        }

    def _sampling_arrays(self, reqs: list[Request], pad_to: Optional[int] = None):
        """Returns ((temps, top_ps, top_ks, seeds, counters), all_greedy).
        all_greedy selects the argmax-only program variant — temperature-0
        batches never pay for top-k/gumbel."""
        n = pad_to or len(reqs)
        temps = np.zeros(n, np.float32)
        top_ps = np.ones(n, np.float32)
        top_ks = np.zeros(n, np.int32)
        seeds = np.zeros(n, np.uint32)
        counters = np.zeros(n, np.int32)
        all_greedy = True
        for i, r in enumerate(reqs):
            temps[i] = r.sampling.temperature
            top_ps[i] = r.sampling.top_p
            top_ks[i] = r.sampling.top_k
            seeds[i] = self._request_seed(r)
            # num_emitted keeps the draw counter monotonic across preemption
            counters[i] = r.num_emitted + len(r.output_tokens)
            if r.sampling.temperature > 0.0:
                all_greedy = False
        return ((temps, top_ps, top_ks, seeds, counters), all_greedy)

    def _request_seed(self, req: Request) -> int:
        if req.sampling.seed is not None:
            return req.sampling.seed & 0xFFFFFFFF
        import xxhash

        return (
            xxhash.xxh32_intdigest(req.request_id.encode(), seed=self.config.seed)
            & 0xFFFFFFFF
        )

    def _active_param_count(self, params) -> int:
        """Parameters active per token (MoE: routed-expert leaves scaled
        by top_k/E) — the FLOPs/token basis of the live MFU gauge."""
        n_params = sum(int(x.size) for x in jax.tree.leaves(params))
        acfg = self.adapter.config
        n_experts = getattr(acfg, "n_routed_experts", 0) or getattr(
            acfg, "num_experts", 0
        )
        top_k = getattr(acfg, "num_experts_per_tok", None) or getattr(
            acfg, "top_k", 0
        )
        if not (n_experts and top_k):
            return n_params
        expert_elems = sum(
            int(leaf.size)
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if any(
                getattr(k, "key", "").startswith("we_")
                and not getattr(k, "key", "").endswith("_scale")
                for k in path
            )
        )
        return n_params - expert_elems + expert_elems * top_k // n_experts

    @staticmethod
    def _cost_scalars(cost) -> tuple[Optional[float], Optional[float]]:
        """Normalize a cost_analysis() result — a dict on current jax,
        a per-device list of dicts on older ones, occasionally None —
        into (flops, bytes_accessed)."""
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return None, None

        def pick(*keys):
            for k in keys:
                v = cost.get(k)
                if isinstance(v, (int, float)) and v == v and v >= 0:
                    return float(v)
            return None

        return pick("flops"), pick("bytes accessed", "bytes_accessed")

    def _program_cost(self, jitted: Callable, args, kwargs):
        """Trace+lower the jitted program (NO XLA compile — ~ms, vs the
        compile's 10s of ms to seconds) and read the lowering's
        cost_analysis() flops / bytes accessed: the cost-model numerator
        of /v1/debug/programs' roofline attainment. Deliberately NOT
        `.lower().compile()`: caching the AOT Compiled object would skip
        jax's C++ jit fastpath on every steady-state dispatch (~6%
        per-call measured), and the AOT executable cache is disjoint
        from the traced path's, so it would also compile twice. Returns
        (None, None) on any refusal: cost analysis varies by backend and
        the serving path must never depend on it."""
        try:
            cost = jitted.lower(*args, **kwargs).cost_analysis()
        except Exception:
            logger.debug("lowered cost_analysis unavailable", exc_info=True)
            return None, None
        return self._cost_scalars(cost)

    def _cache_jit(self, kind: str, cache_key, jitted: Callable) -> Callable:
        """Install a jitted program into the cache wrapped so its FIRST
        invocation — where XLA actually compiles — is counted, timed
        (dynamo_tpu_phase_compile_ms; wall time of compile+first run,
        compile-dominated), spanned in the trace ring, and cost-modeled
        (the lowering's cost_analysis flops/bytes land in self.programs
        for GET /v1/debug/programs). The wrapper replaces itself with
        the bare jitted fn after that one call, so the steady-state
        dispatch path pays nothing."""

        def first_call(*args, **kwargs):
            import time as _time

            from dynamo_tpu import telemetry
            from dynamo_tpu.telemetry import phases

            t0 = _time.perf_counter()
            with telemetry.span(
                "engine.compile", service="engine",
                attrs={"kind": kind, "key": str(cache_key)},
            ):
                flops, nbytes = self._program_cost(jitted, args, kwargs)
                out = jitted(*args, **kwargs)
            dt_ms = (_time.perf_counter() - t0) * 1000.0
            self.metrics.compiles += 1
            self.metrics.compile_ms += dt_ms
            self.compiles_by_kind[kind] = (
                self.compiles_by_kind.get(kind, 0) + 1
            )
            phases.observe("compile_ms", dt_ms)
            self._jit_cache[cache_key] = jitted
            self.programs[cache_key] = {
                "kind": kind,
                "key": str(cache_key),
                "compile_ms": round(dt_ms, 3),
                "flops": flops,
                "bytes": nbytes,
            }
            return out

        self._jit_cache[cache_key] = first_call
        return first_call

    def _get_step_fn(
        self, kind: str, b: int, t: int, greedy: bool = False,
        mm: bool = False, first_chunk: bool = False, lp: int = -1,
        pen: int = 0, bias: bool = False, b_pre: int = 0,
        psamp: bool = False,
    ) -> Callable:
        cache_key = (
            kind, b, t, greedy, mm, first_chunk, lp, pen, bias, b_pre,
            psamp,
        )
        fn = self._jit_cache.get(cache_key)
        if fn is not None:
            return fn
        adapter = self.adapter
        rep_sh = self._rep_sharding

        def rep(x):
            """Replicate a small output across the whole mesh so every
            host of a multi-process mesh can read it (sampled ids drive
            the replicated schedulers); no-op single-process."""
            if rep_sh is None or x is None:
                return x
            return jax.tree.map(
                lambda y: jax.lax.with_sharding_constraint(y, rep_sh), x
            )

        def maybe_logprobs(logits, ids):
            """(chosen_lp, top_ids, top_lps) when this variant reports
            logprobs, else None (OpenAI semantics — unscaled, unpenalized
            model distribution)."""
            if lp < 0:
                return None
            from dynamo_tpu.engine.sampling import token_logprobs

            return token_logprobs(logits, ids, lp)

        def pick(logits, samp_args, counts=None, freq=None, pres=None,
                 rep_p=None, bias_args=None):
            """Sample ids [B] from (possibly penalty/bias-adjusted)
            logits; logprob reporting reads the raw logits separately.
            bias_args = (bias_ids, bias_vals, bias_gated, min_toks); the
            min-token gating reads the CURRENT counters from samp_args,
            so fused-scan steps gate correctly as the count advances."""
            eff = logits
            if counts is not None:
                from dynamo_tpu.engine.sampling import apply_penalties

                eff = apply_penalties(logits, counts, freq, pres, rep_p)
            if bias_args is not None:
                from dynamo_tpu.engine.sampling import apply_logit_bias

                b_ids, b_vals, b_gated, b_min = bias_args
                eff = apply_logit_bias(
                    eff, b_ids, b_vals, b_gated, samp_args[4], b_min
                )
            if greedy:
                ids = sample_greedy(eff)
            else:
                ids = sample(eff, *samp_args)
            return ids

        if kind == "embed":

            def embed_fn(params, tokens, positions, valid, kv, pt):
                hidden, kv = adapter.forward_hidden(
                    params, tokens, positions, valid, kv, pt
                )
                # masked sum over the chunk; the host accumulates across
                # chunks and divides by the true token count
                pooled = jnp.sum(
                    hidden.astype(jnp.float32) * valid[..., None], axis=1
                )
                return rep(pooled), kv

            jitted = jax.jit(embed_fn, donate_argnums=(4,))
            logger.info("compiled %s program B=%d T=%d", kind, b, t)
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "decode_multi":
            k_steps = t  # the (b, t) slot carries (bucket, fused steps)

            def multi_fn(params, tokens, positions, valid, kv, pt,
                         temps, top_ps, top_ks, seeds, counters,
                         freq=None, pres=None, rep_p=None,
                         out_toks=None, out_valid=None,
                         bias_ids=None, bias_vals=None, bias_gated=None,
                         min_toks=None):
                if pen:
                    from dynamo_tpu.engine.sampling import build_output_counts

                    counts0 = build_output_counts(
                        out_toks, out_valid, adapter.vocab_size
                    )
                else:
                    counts0 = jnp.zeros((), jnp.float32)  # unused carry

                def body(carry, _):
                    tokens, positions, kv, counters, counts = carry
                    hidden, kv = adapter.forward_hidden(
                        params, tokens, positions, valid, kv, pt
                    )
                    logits = adapter.compute_logits(params, hidden[:, -1])
                    ids = pick(
                        logits, (temps, top_ps, top_ks, seeds, counters),
                        counts=counts if pen else None, freq=freq, pres=pres,
                        rep_p=rep_p,
                        bias_args=(
                            (bias_ids, bias_vals, bias_gated, min_toks)
                            if bias
                            else None
                        ),
                    )
                    if pen:
                        # Each fused step extends the history it penalizes.
                        rows = jnp.arange(ids.shape[0])
                        counts = counts.at[rows, ids].add(1.0)
                    out = (ids, maybe_logprobs(logits, ids))
                    return (
                        (ids[:, None], positions + 1, kv, counters + 1, counts),
                        out,
                    )

                (_, _, kv, _, _), (all_ids, all_lp) = jax.lax.scan(
                    body, (tokens, positions, kv, counters, counts0), None,
                    length=k_steps,
                )
                if lp >= 0:
                    return rep(all_ids), rep(all_lp), kv  # [K, B] (+ lp)
                return rep(all_ids), kv  # [K, B]

            jitted = jax.jit(multi_fn, donate_argnums=(4,))
            logger.info(
                "compiled decode_multi program B=%d K=%d greedy=%s",
                b, k_steps, greedy,
            )
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "decode_kstep":
            # K decode iterations with ON-DEVICE finish evaluation
            # (config.decode_kstep): like decode_multi's fused scan, but
            # an `alive` mask carries each row's stop/budget state so
            # finished rows freeze mid-window — their lanes compute
            # masked garbage, their KV writes redirect to the null page
            # (forward_hidden valid=False => ops/kv_update.paged_write
            # page 0), their positions/draw counters/penalty counts stop
            # advancing. Because counters and counts advance only while
            # alive, every surviving row's gumbel stream and penalty
            # state are IDENTICAL to K=1 sequential stepping (where the
            # finished row simply leaves the batch) — the bit-exactness
            # contract tests/test_engine_kstep.py pins. The host reads
            # back [K, B] ids + per-row emitted counts once per window.
            # No logprobs variant: logprobs rows fall back (lp == -1).
            k_steps = t

            def kstep_fn(params, tokens, positions, valid, kv, pt,
                         stops, budgets,
                         temps, top_ps, top_ks, seeds, counters,
                         freq=None, pres=None, rep_p=None,
                         out_toks=None, out_valid=None,
                         bias_ids=None, bias_vals=None, bias_gated=None,
                         min_toks=None):
                from dynamo_tpu.engine.sampling import stop_mask

                if pen:
                    from dynamo_tpu.engine.sampling import (
                        build_output_counts,
                    )

                    counts0 = build_output_counts(
                        out_toks, out_valid, adapter.vocab_size
                    )
                else:
                    counts0 = jnp.zeros((), jnp.float32)  # unused carry
                alive0 = valid[:, 0]  # padding rows start frozen
                n0 = jnp.zeros((valid.shape[0],), jnp.int32)

                def body(carry, _):
                    (tokens, positions, kv, counters, counts, alive,
                     n_emit) = carry
                    v = valid & alive[:, None]
                    hidden, kv = adapter.forward_hidden(
                        params, tokens, positions, v, kv, pt
                    )
                    logits = adapter.compute_logits(params, hidden[:, -1])
                    ids = pick(
                        logits, (temps, top_ps, top_ks, seeds, counters),
                        counts=counts if pen else None, freq=freq,
                        pres=pres, rep_p=rep_p,
                        bias_args=(
                            (bias_ids, bias_vals, bias_gated, min_toks)
                            if bias
                            else None
                        ),
                    )
                    emit_i = alive.astype(jnp.int32)
                    n_emit = n_emit + emit_i
                    if pen:
                        rows = jnp.arange(ids.shape[0])
                        counts = counts.at[rows, ids].add(
                            alive.astype(jnp.float32)
                        )
                    # emit-then-freeze: a stop token (or the budget's
                    # last token) IS emitted — the row freezes for the
                    # REST of the window, matching the host scan that
                    # appends the token and then breaks on its finish
                    alive = (
                        alive
                        & ~stop_mask(ids, stops)
                        & (n_emit < budgets)
                    )
                    return (
                        (ids[:, None], positions + emit_i[:, None], kv,
                         counters + emit_i, counts, alive, n_emit),
                        ids,
                    )

                (_, _, kv, _, _, _, n_emit), all_ids = jax.lax.scan(
                    body,
                    (tokens, positions, kv, counters, counts0, alive0, n0),
                    None, length=k_steps,
                )
                return rep(all_ids), rep(n_emit), kv  # [K, B], [B]

            jitted = jax.jit(kstep_fn, donate_argnums=(4,))
            logger.info(
                "compiled decode_kstep program B=%d K=%d greedy=%s",
                b, k_steps, greedy,
            )
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "mixed":
            # One fused program per (b=decode bucket, t=prefill T bucket,
            # b_pre=prefill row bucket): prefill chunk KV+decode token in
            # a single dispatch. The halves run the SAME forward paths as
            # the pure programs (decode [B, 1] page walk, prefill [B, T]
            # chunk), so per-row numerics — and greedy token streams —
            # are identical to the XOR scheduler's. psamp selects whether
            # prefill rows sample (some piece completes its prompt);
            # without it only decode rows pay the lm_head.

            def mixed_fn(params, d_tokens, d_positions, d_valid, kv, d_pt,
                         p_tokens, p_positions, p_valid, p_pt, last_idx,
                         temps, top_ps, top_ks, seeds, counters,
                         freq=None, pres=None, rep_p=None,
                         out_toks=None, out_valid=None,
                         bias_ids=None, bias_vals=None, bias_gated=None,
                         min_toks=None):
                # prefill half first (the XOR policy's order); page
                # tables are per-request disjoint, so neither half can
                # read the other's writes
                hidden_p, kv = adapter.forward_hidden(
                    params, p_tokens, p_positions, p_valid, kv, p_pt,
                    first_chunk=first_chunk,
                )
                hidden_d, kv = adapter.forward_hidden(
                    params, d_tokens, d_positions, d_valid, kv, d_pt
                )
                last_h = hidden_d[:, -1]  # [B_dec, H] (T=1)
                if psamp:
                    rows_p = jnp.arange(hidden_p.shape[0])
                    last_h = jnp.concatenate(
                        [last_h, hidden_p[rows_p, last_idx]], axis=0
                    )
                logits = adapter.compute_logits(params, last_h)
                counts = None
                if pen:
                    from dynamo_tpu.engine.sampling import (
                        build_output_counts,
                    )

                    counts = build_output_counts(
                        out_toks, out_valid, adapter.vocab_size
                    )
                ids = pick(
                    logits, (temps, top_ps, top_ks, seeds, counters),
                    counts=counts, freq=freq, pres=pres, rep_p=rep_p,
                    bias_args=(
                        (bias_ids, bias_vals, bias_gated, min_toks)
                        if bias
                        else None
                    ),
                )
                if lp >= 0:
                    return rep(ids), rep(maybe_logprobs(logits, ids)), kv
                return rep(ids), kv

            jitted = jax.jit(mixed_fn, donate_argnums=(4,))
            logger.info(
                "compiled mixed program Bdec=%d T=%d Bpre=%d psamp=%s",
                b, t, b_pre, psamp,
            )
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "spec_verify":

            def verify_fn(params, tokens, positions, valid, kv, pt):
                hidden, kv = adapter.forward_hidden(
                    params, tokens, positions, valid, kv, pt
                )
                bsz, tlen, h = hidden.shape
                logits = adapter.compute_logits(
                    params, hidden.reshape(bsz * tlen, h)
                )
                ids = jnp.argmax(logits, axis=-1).reshape(bsz, tlen)
                return rep(ids.astype(jnp.int32)), kv

            jitted = jax.jit(verify_fn, donate_argnums=(4,))
            logger.info("compiled %s program B=%d T=%d", kind, b, t)
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "spec_draft_prefill":
            draft_adapter = self.draft_adapter

            def draft_pre_fn(draft_params, tokens, positions, valid,
                             draft_kv, pt):
                _, draft_kv = draft_adapter.forward_hidden(
                    draft_params, tokens, positions, valid, draft_kv, pt,
                    first_chunk=first_chunk,
                )
                return draft_kv

            jitted = jax.jit(draft_pre_fn, donate_argnums=(4,))
            logger.info("compiled %s program B=%d T=%d", kind, b, t)
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "spec_fused":
            # One program per spec step (docs/engine.md "Speculative
            # decoding"): draft catch-up over the accepted window + S
            # greedy draft proposals (on-device feedback, own KV pool) +
            # the target verify forward over [last, d_0..d_{S-1}] + the
            # sequential acceptance scan. The (b, t) slot carries
            # (decode bucket, S+1). Inputs are window tokens + per-row
            # lengths so the HOST-fed first dispatch and the DEVICE-fed
            # chained dispatch (win_tokens=prev out_ids, win_len=prev
            # n_acc) share one compiled program.
            draft_adapter = self.draft_adapter
            s_steps = self.config.spec_draft_tokens
            vocab = adapter.vocab_size
            b_static = b

            def spec_fn(params, draft_params, win_tokens, win_len, pos0,
                        kv, draft_kv, pt,
                        temps, top_ps, top_ks, seeds, counters_v0,
                        freq=None, pres=None, rep_p=None,
                        out_toks=None, out_valid=None,
                        bias_ids=None, bias_vals=None, bias_gated=None,
                        min_toks=None):
                from dynamo_tpu.engine.sampling import spec_accept_step

                rows = jnp.arange(b_static)
                w = s_steps + 1
                w_positions = (
                    pos0[:, None] + jnp.arange(w, dtype=jnp.int32)[None]
                )
                w_valid = (
                    jnp.arange(w, dtype=jnp.int32)[None]
                    < win_len[:, None]
                )
                live = win_len > 0  # padding rows never write KV
                last_idx = jnp.maximum(win_len - 1, 0)
                # draft catch-up: commits the window tokens' draft KV and
                # yields the hidden state the first proposal reads
                hid_d, draft_kv = draft_adapter.forward_hidden(
                    draft_params, win_tokens, w_positions, w_valid,
                    draft_kv, pt,
                )
                h = hid_d[rows, last_idx]
                pos_last = pos0 + last_idx  # [B] = num_tokens - 1
                d0 = jnp.argmax(
                    draft_adapter.compute_logits(draft_params, h), axis=-1
                ).astype(jnp.int32)
                if s_steps > 1:

                    def propose(carry, j):
                        tok, dkv = carry
                        hj, dkv = draft_adapter.forward_hidden(
                            draft_params, tok[:, None],
                            (pos_last + 1 + j)[:, None], live[:, None],
                            dkv, pt,
                        )
                        nxt = jnp.argmax(
                            draft_adapter.compute_logits(
                                draft_params, hj[:, -1]
                            ),
                            axis=-1,
                        ).astype(jnp.int32)
                        return (nxt, dkv), nxt

                    (_, draft_kv), rest = jax.lax.scan(
                        propose, (d0, draft_kv),
                        jnp.arange(s_steps - 1, dtype=jnp.int32),
                    )
                    draft_ids = jnp.concatenate(
                        [d0[:, None], rest.T], axis=1
                    )  # [B, S]
                else:
                    draft_ids = d0[:, None]
                # target verify over [last accepted, d_0 .. d_{S-1}]
                last_tok = win_tokens[rows, last_idx]
                v_tokens = jnp.concatenate(
                    [last_tok[:, None], draft_ids], axis=1
                )
                v_positions = (
                    pos_last[:, None]
                    + jnp.arange(w, dtype=jnp.int32)[None]
                )
                v_valid = jnp.broadcast_to(live[:, None], (b_static, w))
                hid_t, kv = adapter.forward_hidden(
                    params, v_tokens, v_positions, v_valid, kv, pt
                )
                bsz, tlen, hdim = hid_t.shape
                logits = adapter.compute_logits(
                    params, hid_t.reshape(bsz * tlen, hdim)
                ).reshape(bsz, tlen, -1)
                # sequential acceptance: position j emits iff every
                # earlier draft was accepted; penalties extend their
                # history per emitted token exactly like decode_multi
                if pen:
                    from dynamo_tpu.engine.sampling import (
                        build_output_counts,
                    )

                    counts = build_output_counts(out_toks, out_valid, vocab)
                else:
                    counts = None
                alive = live
                n_acc = jnp.zeros(b_static, jnp.int32)
                outs = []
                for j in range(w):
                    eff = logits[:, j]
                    if pen:
                        from dynamo_tpu.engine.sampling import (
                            apply_penalties,
                        )

                        eff = apply_penalties(
                            eff, counts, freq, pres, rep_p
                        )
                    if bias:
                        from dynamo_tpu.engine.sampling import (
                            apply_logit_bias,
                        )

                        eff = apply_logit_bias(
                            eff, bias_ids, bias_vals, bias_gated,
                            counters_v0 + j, min_toks,
                        )
                    draft_j = (
                        draft_ids[:, j]
                        if j < s_steps
                        else jnp.zeros(b_static, jnp.int32)
                    )
                    if greedy:
                        chosen = jnp.argmax(eff, axis=-1).astype(jnp.int32)
                        acc = (
                            chosen == draft_j
                            if j < s_steps
                            else jnp.ones(b_static, bool)
                        )
                    else:
                        chosen, acc = spec_accept_step(
                            eff, draft_j, j < s_steps, temps, top_ps,
                            top_ks, seeds, counters_v0 + j,
                        )
                    outs.append(chosen)
                    n_acc = n_acc + alive.astype(jnp.int32)
                    if pen:
                        counts = counts.at[rows, chosen].add(
                            alive.astype(jnp.float32)
                        )
                    alive = alive & acc
                out_ids = jnp.stack(outs, axis=1)  # [B, S+1]
                return (
                    rep(out_ids), rep(draft_ids), rep(n_acc), kv, draft_kv
                )

            jitted = jax.jit(spec_fn, donate_argnums=(5, 6))
            logger.info(
                "compiled spec_fused program B=%d S=%d greedy=%s pen=%s "
                "bias=%s", b, s_steps, greedy, pen, bias,
            )
            return self._cache_jit(kind, cache_key, jitted)

        if kind == "prefill_nosample":

            def nosample_fn(params, tokens, positions, valid, kv, pt,
                            mm_embeds=None, mm_mask=None):
                _, kv = adapter.forward_hidden(
                    params, tokens, positions, valid, kv, pt,
                    mm_embeds=mm_embeds, mm_mask=mm_mask,
                    first_chunk=first_chunk,
                )
                return kv

            jitted = jax.jit(nosample_fn, donate_argnums=(4,))
            logger.info("compiled %s program B=%d T=%d", kind, b, t)
            return self._cache_jit(kind, cache_key, jitted)

        def step_fn(params, tokens, positions, valid, kv, pt, last_idx,
                    temps, top_ps, top_ks, seeds, counters,
                    freq=None, pres=None, rep_p=None,
                    out_toks=None, out_valid=None,
                    bias_ids=None, bias_vals=None, bias_gated=None,
                    min_toks=None, mm_embeds=None, mm_mask=None):
            hidden, kv = adapter.forward_hidden(
                params, tokens, positions, valid, kv, pt,
                mm_embeds=mm_embeds, mm_mask=mm_mask,
                first_chunk=first_chunk,
            )
            rows = jnp.arange(hidden.shape[0])
            last_hidden = hidden[rows, last_idx]  # [B, H] — lm_head only here
            logits = adapter.compute_logits(params, last_hidden)
            counts = None
            if pen:
                from dynamo_tpu.engine.sampling import build_output_counts

                counts = build_output_counts(
                    out_toks, out_valid, adapter.vocab_size
                )
            ids = pick(
                logits, (temps, top_ps, top_ks, seeds, counters),
                counts=counts, freq=freq, pres=pres, rep_p=rep_p,
                bias_args=(
                    (bias_ids, bias_vals, bias_gated, min_toks)
                    if bias
                    else None
                ),
            )
            if lp >= 0:
                return rep(ids), rep(maybe_logprobs(logits, ids)), kv
            return rep(ids), kv

        jitted = jax.jit(step_fn, donate_argnums=(4,))
        logger.info("compiled %s program B=%d T=%d", kind, b, t)
        return self._cache_jit(kind, cache_key, jitted)

    def _finish_reason_for(
        self, req: Request, token: int, n_new: int
    ) -> Optional[FinishReason]:
        """Finish check for the n_new'th newly-sampled token of this
        dispatch (token not yet appended to the request)."""
        s = req.sampling
        if not s.ignore_eos and (
            token in self.config.eos_token_ids or token in s.stop_token_ids
        ):
            return FinishReason.STOP
        if len(req.output_tokens) + n_new + req.num_emitted >= s.max_tokens:
            return FinishReason.LENGTH
        if req.num_tokens + n_new >= self.config.max_context:
            return FinishReason.LENGTH
        return None

    @staticmethod
    def _batch_trace_id(batch) -> Optional[str]:
        """Any traced request's trace id in this dispatch — the phase
        histogram's exemplar for the bucket the step lands in. Always
        None when tracing is off (no Request carries a trace_id then),
        so the disabled path pays one short loop over the batch."""
        for req in batch.decode:
            if req.trace_id is not None:
                return req.trace_id
        for piece in batch.prefill:
            if piece.request.trace_id is not None:
                return piece.request.trace_id
        return None

    def _observe_emission(
        self, req: Request, finished: bool, n_tokens: int = 1,
        kstep: bool = False,
    ) -> None:
        """Decode-stall histogram bookkeeping: observe the gap since this
        request's previous token emission whenever a prefill-carrying
        dispatch (pure prefill or mixed) ran in between — the prefill-
        attributed stall one running request experienced. Under the XOR
        scheduler these gaps are whole backlog drains; under mixed steps
        they collapse to one step.

        A K-step window delivers its K tokens in one host visit, so the
        raw gap is K× the per-token cadence even when nothing stalled:
        discount the device-measured healthy window time (per-step ms ×
        n_tokens) before observing, leaving only true prefill-induced
        excess in the histogram."""
        now = time.perf_counter()
        mark = self.metrics.prefill_dispatches + self.metrics.mixed_dispatches
        prev = self._last_emit.get(req.request_id)
        if prev is not None and mark > prev[1]:
            from dynamo_tpu.telemetry import phases

            stall_ms = (now - prev[0]) * 1000.0
            if kstep and n_tokens > 1:
                stall_ms = max(
                    0.0, stall_ms - self._kstep_step_ms * n_tokens
                )
            if req.trace_id is not None:
                # traced request: accumulate so the final StepOutput can
                # carry the request's TOTAL prefill-induced stall onto
                # its engine.generate span (timeline breakdown)
                req.stall_accum_ms += stall_ms
            phases.observe(
                "decode_stall_ms", stall_ms, trace_id=req.trace_id
            )
        if finished:
            self._last_emit.pop(req.request_id, None)
        else:
            self._last_emit[req.request_id] = (now, mark)

    def _observe_slo(self, req: Request, n_tokens: int, finished: bool) -> None:
        """Feed the worker-side SLO sketches (config.fleet_telemetry):
        TTFT on the first emission, per-token ITL on later ones (a fused
        K-step emission spreads its gap over its K tokens), e2e + the
        SLA/goodput judgement at finish. arrival_time is 0.0 for
        directly-constructed Requests (unit tests, tools) — those skip
        the wall-clock metrics rather than record epoch-sized garbage."""
        now = time.perf_counter()
        mark = self._slo_marks.get(req.request_id)
        if mark is None:
            ttft_ms = None
            if req.arrival_time:
                ttft_ms = max(0.0, (time.time() - req.arrival_time) * 1000.0)
                self.slo.observe("ttft_ms", ttft_ms)
            mark = self._slo_marks[req.request_id] = [ttft_ms, 0.0, 0, now]
        else:
            gap_ms = (now - mark[3]) * 1000.0 / max(1, n_tokens)
            self.slo.observe("itl_ms", gap_ms)
            mark[1] += gap_ms
            mark[2] += 1
            mark[3] = now
        if finished:
            self._slo_marks.pop(req.request_id, None)
            e2e_ms = None
            if req.arrival_time:
                e2e_ms = max(0.0, (time.time() - req.arrival_time) * 1000.0)
                self.slo.observe("e2e_ms", e2e_ms)
            self.slo.finish_request(
                ttft_ms=mark[0],
                itl_ms=mark[1] / mark[2] if mark[2] else None,
                e2e_ms=e2e_ms,
                tokens=len(req.output_tokens) + req.num_emitted,
            )

    def _accept_tokens(
        self,
        req: Request,
        tokens: Sequence[int],
        finish: Optional[FinishReason],
        first: bool = False,
        lps: Optional[tuple[float, ...]] = None,
        tops: Optional[tuple] = None,
        mixed: bool = False,
        spec: bool = False,
        kstep: bool = False,
    ) -> list[StepOutput]:
        chain = self.scheduler.chains.get(req.request_id)
        for tok in tokens:
            req.output_tokens.append(tok)
            if chain is not None:
                chain.append(tok)
        self.metrics.generated_tokens += len(tokens)
        if tokens:
            self._observe_emission(
                req, finished=finish is not None,
                n_tokens=len(tokens), kstep=kstep,
            )
            if self.slo is not None:
                self._observe_slo(req, len(tokens), finish is not None)
        if finish is not None:
            self.scheduler.finish(req)
            req.finish_reason = finish
        return [
            StepOutput(
                request_id=req.request_id,
                new_token_ids=tuple(tokens),
                finish_reason=finish,
                is_first=first,
                logprobs=lps,
                top_logprobs=tops,
                # prefix-cache accounting rides the first output (OpenAI
                # usage.prompt_tokens_details.cached_tokens)
                cached_tokens=req.num_cached_prompt_tokens if first else None,
                mixed=mixed,
                spec=spec,
                kstep=kstep,
                # tracing enrichment (traced requests only; None — and
                # absent from the wire — otherwise): queue wait on the
                # first output, accumulated decode stall on the last
                queue_wait_ms=(
                    req.queue_wait_ms
                    if first and req.trace_id is not None
                    else None
                ),
                stall_ms=(
                    round(req.stall_accum_ms, 3)
                    if finish is not None
                    and req.trace_id is not None
                    and req.stall_accum_ms > 0.0
                    else None
                ),
            )
        ]

    def _accept_token(
        self, req: Request, token: int, first: bool = False,
        lps: Optional[tuple[float, ...]] = None, tops: Optional[tuple] = None,
        mixed: bool = False,
    ) -> list[StepOutput]:
        finish = self._finish_reason_for(req, token, 1)
        return self._accept_tokens(
            req, [token], finish, first=first, lps=lps, tops=tops,
            mixed=mixed,
        )

    # -- embeddings --------------------------------------------------------

    def embed(
        self, prompts: Sequence[Sequence[int]], normalize: bool = True
    ) -> np.ndarray:
        """Mean-pooled (optionally L2-normalized) last-layer hidden states,
        one vector per prompt (the /v1/embeddings engine path — the
        reference delegates this to its engines; here it shares the prefill
        programs' chunked execution and page pool). Pages are scratch:
        allocated for attention across chunks, freed before returning."""
        out: list[np.ndarray] = []
        ps = self.config.page_size
        mp = self.config.max_pages_per_seq
        for toks in prompts:
            toks = list(toks)
            if not toks:
                raise ValueError("cannot embed an empty token sequence")
            need = -(-len(toks) // ps)
            if need > mp:
                raise ValueError(
                    f"prompt of {len(toks)} tokens needs {need} KV pages; "
                    f"max_pages_per_seq is {mp}"
                )
            pages = self.allocator.allocate(need)
            if pages is None:
                raise RuntimeError("no KV pages free for embedding")
            try:
                acc: Optional[np.ndarray] = None
                for start in range(0, len(toks), self.config.prefill_chunk):
                    chunk = toks[start : start + self.config.prefill_chunk]
                    t_bucket = self._bucket_t(len(chunk))
                    tokens = np.zeros((1, t_bucket), np.int32)
                    tokens[0, : len(chunk)] = chunk
                    positions = (
                        np.arange(t_bucket, dtype=np.int32)[None] + start
                    )
                    valid = np.zeros((1, t_bucket), bool)
                    valid[0, : len(chunk)] = True
                    pt = np.zeros((1, mp), np.int32)
                    pt[0, : len(pages)] = pages
                    fn = self._get_step_fn("embed", 1, t_bucket)
                    d_tokens, d_positions, d_valid, d_pt = self._dev_tree(
                        (tokens, positions, valid, pt)
                    )
                    pooled, self.kv = fn(
                        self.params, d_tokens, d_positions, d_valid,
                        self.kv, d_pt,
                    )
                    vec = np.asarray(pooled, np.float32)[0]
                    acc = vec if acc is None else acc + vec
                mean = acc / len(toks)
            finally:
                self.allocator.free(pages)
            if normalize:
                norm = float(np.linalg.norm(mean))
                if norm > 0:
                    mean = mean / norm
            out.append(mean)
        return np.stack(out)

    # -- disaggregated prefill/decode hooks -------------------------------
    # (decode side pre-allocates pages; a prefill worker computes the KV,
    #  extracts it from its own pool, and the transfer service injects it
    #  here — the reference's NIXL RDMA write path, dynamo_flow.md:36-38,
    #  re-done as explicit page movement through host/DCN for TPU.)

    @property
    def _hidden_size(self) -> int:
        cfg = self.adapter.config
        return (
            cfg.hidden_size
            if hasattr(cfg, "hidden_size")
            else cfg.base.hidden_size
        )

    @property
    def _canonical_head_dims(self) -> tuple:
        """The true last-dim widths of (k, v) — the wire/host format for
        extracted pages. The device cache may be lane-padded
        (cfg.kv_head_dim) when the Pallas kernel is active; extract strips
        the padding and inject restores it, so disagg peers and KVBM tiers
        with different attention impls interoperate (and host/disk tiers
        don't store zero lanes). MLA caches are ASYMMETRIC (k = latent,
        v = rope key) and unpadded — their widths come straight from the
        cache."""
        cfg = self.adapter.config
        if hasattr(cfg, "kv_lora_rank"):  # MLA: unpadded, asymmetric
            return (self.kv.k.shape[-1], self.kv.v.shape[-1])
        d = cfg.head_dim if hasattr(cfg, "head_dim") else cfg.base.head_dim
        return (d, d)

    def extract_pages(self, page_ids: Sequence[int]):
        """Pull KV pages to host in the canonical wire format:
        (k, v) as [L, Hkv, n, page_size, D] — layout- and padding-agnostic
        so disagg peers and KVBM tiers interoperate across engine configs.
        (Device cache is [L, P, S, Hkv, Dpad].)

        Cross-host meshes return the PROCESS-LOCAL Hkv slice: each host
        tiers its own shard and `inject_pages` reassembles the global
        array from the per-host slices (reference KVBM has no
        single-process restriction either, block_manager.rs:69-78)."""
        if not self._multiproc:
            k, v = self.extract_pages_async(page_ids)
            return np.asarray(k), np.asarray(v)
        n = len(page_ids)
        fn = self._jit_cache.get(("extract_mp", n))
        if fn is None:
            dk, dv = self._canonical_head_dims
            fn = jax.jit(
                lambda kv, ids: _canonical_gather(kv, ids, dk, dv),
                out_shardings=(
                    self._canonical_kv_sharding(self.kv.k),
                    self._canonical_kv_sharding(self.kv.v),
                ),
            )
            fn = self._cache_jit("extract", ("extract_mp", n), fn)
        k, v = fn(self.kv, jnp.asarray(np.asarray(page_ids, np.int32)))
        return self._process_local_np(k), self._process_local_np(v)

    def _canonical_kv_sharding(self, pool):
        """Sharding of the canonical [L, Hkv, n, S, D] layout matching
        `pool`'s [L, P, S, Hkv, Dpad] placement: the Hkv axis keeps the
        pool's mesh axis (tp for head-sharded caches, replicated for
        MLA's shared latent), everything else replicates."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = getattr(pool.sharding, "spec", None)
        head_axis = spec[3] if spec is not None and len(spec) > 3 else None
        return NamedSharding(self.mesh, P(None, head_axis, None, None, None))

    @staticmethod
    def _process_local_np(arr) -> np.ndarray:
        """This process's slice of a canonical global array as numpy:
        dedupe the addressable shards by their Hkv offset (dp replicas
        carry identical bytes) and concatenate the distinct slices."""
        by_start: dict = {}
        for s in arr.addressable_shards:
            sl = s.index[1]
            start = sl.start or 0
            if start not in by_start:
                by_start[start] = np.asarray(s.data)
        starts = sorted(by_start)
        parts = [by_start[i] for i in starts]
        # make_array_from_process_local_data needs one contiguous local
        # block per process — standard mesh construction guarantees it
        for a, b, p in zip(starts, starts[1:], parts):
            assert a + p.shape[1] == b, (
                "non-contiguous local KV shards; mesh device order is "
                "not process-contiguous"
            )
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)

    def extract_pages_async(self, page_ids: Sequence[int]):
        """Async variant: the page gather + canonical transpose run on
        device and the device→host copy is started without blocking; the
        returned jax arrays materialize on first np.asarray. The gather is
        enqueued on the device stream BEFORE any later dispatch can
        overwrite the pages, so content is captured even though the pool
        may hand the page ids out immediately (KVBM's double-buffered
        offload rides this — the reference overlaps offload DMA the same
        way, block_manager/offload.rs)."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        dk, dv = self._canonical_head_dims
        k, v = _canonical_gather(self.kv, ids, dk, dv)
        try:
            k.copy_to_host_async()
            v.copy_to_host_async()
        except AttributeError:
            pass  # older jax array types; np.asarray will sync-copy
        return k, v

    def inject_pages(self, page_ids: Sequence[int], k: np.ndarray, v: np.ndarray) -> None:
        """Write transferred KV pages (canonical [L, Hkv, n, S, D]) into
        this engine's pool in place. Host arrays become uncommitted device
        arrays, so the jitted scatter reshards them onto whatever mesh the
        pool lives on. Cross-host meshes take the PROCESS-LOCAL Hkv slice
        (what `extract_pages` returned on this host) and assemble the
        global array from every host's slice."""
        if self._multiproc:
            ksh = self._canonical_kv_sharding(self.kv.k)
            vsh = self._canonical_kv_sharding(self.kv.v)
            hkv = self.kv.k.shape[3]
            gk = jax.make_array_from_process_local_data(
                ksh, np.ascontiguousarray(k),
                (k.shape[0], hkv, *k.shape[2:]),
            )
            gv = jax.make_array_from_process_local_data(
                vsh, np.ascontiguousarray(v),
                (v.shape[0], hkv, *v.shape[2:]),
            )
            self.inject_pages_device(page_ids, gk, gv)
            return
        self.inject_pages_device(page_ids, jnp.asarray(k), jnp.asarray(v))

    def inject_pages_device(self, page_ids: Sequence[int], k, v) -> None:
        """Device-path inject: k/v are jax arrays (canonical
        [L, Hkv, n, S, D] — D+4 int8 with trailing packed scales on
        quantized pools); the unpack, transpose, head-dim pad, and
        scatter all run in one jitted program — no host round-trip on the
        single-chip path (the point of the ICI transfer plane)."""
        pool_sharding = getattr(self.kv.k, "sharding", None)
        if (
            pool_sharding is not None
            and len(pool_sharding.device_set) > 1
            and getattr(k, "sharding", None) is not None
            and k.sharding.device_set != pool_sharding.device_set
        ):
            # Pulled arrays are committed to one device; a jit over a
            # multi-device pool would reject the conflicting placement.
            # Stage through host (per-shard ICI pulls are the future
            # optimization) — jnp.asarray(np) yields uncommitted arrays
            # the scatter can reshard freely.
            k = jnp.asarray(np.asarray(k))
            v = jnp.asarray(np.asarray(v))
        n = len(page_ids)
        quantized = self.kv.k_scale is not None
        scale_lanes = 4 if quantized else 0
        dpad_k = self.kv.k.shape[-1] - (k.shape[-1] - scale_lanes)
        dpad_v = self.kv.v.shape[-1] - (v.shape[-1] - scale_lanes)
        fn = self._jit_cache.get(("inject_dev", n, dpad_k, dpad_v))
        if fn is None:
            def inject_fn(kv, ids, kk, vv):
                kks = vvs = None
                if quantized:
                    kk, kks = _wire_unpack(
                        kk, kv.k.shape[-1] - dpad_k, kv.k.dtype
                    )
                    vv, vvs = _wire_unpack(
                        vv, kv.v.shape[-1] - dpad_v, kv.v.dtype
                    )
                kk = kk.transpose(0, 2, 3, 1, 4)
                vv = vv.transpose(0, 2, 3, 1, 4)
                if dpad_k:
                    kk = jnp.pad(
                        kk, [(0, 0)] * (kk.ndim - 1) + [(0, dpad_k)]
                    )
                if dpad_v:
                    vv = jnp.pad(
                        vv, [(0, 0)] * (vv.ndim - 1) + [(0, dpad_v)]
                    )
                out = kv._replace(
                    k=kv.k.at[:, ids].set(kk.astype(kv.k.dtype)),
                    v=kv.v.at[:, ids].set(vv.astype(kv.v.dtype)),
                )
                if quantized:
                    out = out._replace(
                        k_scale=kv.k_scale.at[:, ids].set(
                            kks.transpose(0, 2, 3, 1)
                        ),
                        v_scale=kv.v_scale.at[:, ids].set(
                            vvs.transpose(0, 2, 3, 1)
                        ),
                    )
                return out
            fn = self._cache_jit(
                "inject", ("inject_dev", n, dpad_k, dpad_v),
                jax.jit(inject_fn, donate_argnums=(0,)),
            )
        self.kv = fn(
            self.kv, jnp.asarray(np.asarray(page_ids, np.int32)), k, v
        )
        # The transfer server acks the sender the moment its write_fn
        # returns, and the sender then reuses its staging buffer (the shm
        # plane reuses the very mmap our jnp.asarray views may alias on
        # the CPU backend, or an async H2D copy may still be reading on
        # TPU). Commit the scatter before returning so the ack really
        # means "bytes landed" — once per transfer, not per token. On the
        # worker path this blocks the ENGINE thread (runner.submit), not
        # the event loop, and the next decode step would queue behind the
        # same device stream anyway.
        jax.block_until_ready(tuple(x for x in self.kv if x is not None))

    # -- G4 remote tier: serve/adopt blocks across workers -----------------
    # (reference: KvBlockManager::export_local_blockset / onboard_blocks —
    # block_manager.rs:121,169)

    def serve_blocks(self, seq_hashes: Sequence[int]):
        """Export the longest locally-resident chain of `seq_hashes` for a
        peer: (metas, k, v) with metas=[(seq_hash, parent, tokens)...] and
        k/v canonical FULL-Hkv [L, Hkv, n, S, D] host arrays; None when
        the first hash isn't here. Device pages are ref-held during
        extraction; the lower tiers are read without promotion.

        Cross-host meshes refuse: extraction (and the tiers) hold only
        this process's Hkv slice, and shipping a partial-head array to a
        peer expecting the full canonical layout would install silently
        wrong KV. (The Worker already bars kv_remote on SPMD groups —
        this guard keeps the contract honest for direct callers.)"""
        if self._multiproc:
            return None
        alloc = self.allocator
        pages = PageAllocator.lookup(alloc, seq_hashes)  # never onboards
        metas: list[tuple] = []
        parts_k: list[np.ndarray] = []
        parts_v: list[np.ndarray] = []
        try:
            if pages:
                k, v = self.extract_pages(pages)
                parts_k.append(k)
                parts_v.append(v)
                metas = [alloc._page_meta[p] for p in pages]
        finally:
            if pages:
                alloc.free(pages)
        tier_get = getattr(alloc, "_tier_get", None)
        if tier_get is not None:
            entries = []
            for h in seq_hashes[len(pages):]:
                e = tier_get(h)
                if e is None:
                    break
                entries.append(e)
            if entries:
                parts_k.append(np.stack([e.k for e in entries], axis=2))
                parts_v.append(np.stack([e.v for e in entries], axis=2))
                metas.extend(
                    (e.seq_hash, e.parent_hash, e.tokens) for e in entries
                )
        if not metas:
            return None
        k = parts_k[0] if len(parts_k) == 1 else np.concatenate(parts_k, axis=2)
        v = parts_v[0] if len(parts_v) == 1 else np.concatenate(parts_v, axis=2)
        return metas, k, v

    def adopt_blocks(self, metas: Sequence[tuple], k, v) -> int:
        """Land a peer-served chain into this engine's prefix cache:
        allocate fresh pages, inject the bytes, register the hashes (which
        also publishes 'stored' events so routers learn the new holder).
        Returns blocks adopted; skips blocks already resident and refuses
        chains whose parent isn't resident (nothing would ever match
        them)."""
        alloc = self.allocator
        tier_contains = getattr(alloc, "tier_contains", lambda h: False)
        start = 0
        while start < len(metas) and alloc.match_length([metas[start][0]]):
            start += 1
        todo = list(metas[start:])
        if not todo:
            return 0
        parent = todo[0][1]
        if (
            parent is not None
            and not alloc.match_length([parent])
            and not tier_contains(parent)
        ):
            return 0
        pages = alloc.allocate(len(todo))
        if pages is None:
            return 0  # pool pressure — skip this time
        self.inject_pages(pages, k[:, :, start:], v[:, :, start:])
        for page, (h, ph, toks) in zip(pages, todo):
            alloc.register_promoted(page, h, ph, tuple(toks))
        # Adopted blocks are cache content, not request-held: release so
        # they stay registered but reclaimable.
        alloc.free(pages)
        return len(todo)

    # -- worker handover: bulk export / adopt of the registered block set
    # (docs/operations.md "Rolling upgrades & worker handover"). The
    # byte movement itself rides the disagg transfer planes via the
    # normal page-addressed write path — these helpers only deal in the
    # allocator's content addressing on either side. ---------------------

    def handover_metas(self) -> list:
        """Topo-ordered (seq_hash, parent_hash, tokens) for every
        device-registered block — the retiring worker's migratable hot
        set, parents before children so any batch prefix is adoptable.
        Cross-host meshes export nothing (same partial-Hkv refusal as
        serve_blocks)."""
        if self._multiproc:
            return []
        from dynamo_tpu.handover import topo_order_metas

        return topo_order_metas(list(self.allocator._page_meta.values()))

    def export_blocks_by_hash(self, seq_hashes: Sequence[int]):
        """Extract the subset of `seq_hashes` still device-registered as
        (metas, k, v) in the canonical wire format — the handover batch
        export. Unlike serve_blocks this addresses blocks individually
        (a topo batch may span branches), holds a reference on each page
        across the extraction, and never touches the lower tiers. None
        when nothing in the batch is still resident (eviction between
        the meta listing and this call is legal — the batch shrinks)."""
        if self._multiproc:
            return None
        alloc = self.allocator
        pages: list[int] = []
        metas: list[tuple] = []
        try:
            for h in seq_hashes:
                got = PageAllocator.lookup(alloc, [h])  # base: no onboard
                if not got:
                    continue
                pages.append(got[0])
                metas.append(alloc._page_meta[got[0]])
            if not pages:
                return None
            k, v = self.extract_pages(pages)
        finally:
            if pages:
                alloc.free(pages)
        return metas, np.asarray(k), np.asarray(v)

    def prepare_handover_adopt(self, metas: Sequence[tuple]):
        """Successor-side reservation: allocate fresh pages for the
        not-yet-resident blocks of `metas`. Returns (pages, kept_metas,
        want_idx) — the transfer write lands bytes into `pages`, then
        commit_handover_adopt registers them (or abort_ frees them).
        Trims to what the pool can take right now: a handover must never
        preempt live work on the successor."""
        alloc = self.allocator
        tier_contains = getattr(alloc, "tier_contains", lambda h: False)
        kept: list[tuple] = []
        want_idx: list[int] = []
        for i, (h, p, toks) in enumerate(metas):
            if alloc.match_length([h]) or tier_contains(h):
                continue
            kept.append((h, p, toks))
            want_idx.append(i)
        n_fit = min(len(kept), alloc.num_free)
        kept, want_idx = kept[:n_fit], want_idx[:n_fit]
        if not kept:
            return None
        pages = alloc.allocate(len(kept))
        if pages is None:
            return None
        return pages, kept, want_idx

    def commit_handover_adopt(self, pages, metas) -> int:
        """The batch's bytes landed (transfer ack fired): content-address
        the reserved pages and release them into the reclaimable cache —
        registration publishes 'stored' events, so routers immediately
        score this worker for the migrated prefixes."""
        for page, (h, p, toks) in zip(pages, metas):
            self.allocator.register_promoted(page, h, p, tuple(toks))
        self.allocator.free(pages)
        return len(pages)

    def abort_handover_adopt(self, pages) -> None:
        """The bytes never landed: the unregistered reservation goes
        straight back to the free list — no leak, no half-adopted KV."""
        self.allocator.free(pages)

    def allocate_for_remote_prefill(
        self,
        request_id: str,
        prompt_tokens: Sequence[int],
        sampling: Optional[SamplingParams] = None,
    ) -> Optional[Request]:
        """Decode-side page reservation: allocate the prompt's pages (plus
        one-token headroom) now so a prefill worker can write into them.
        Returns None when the pool can't take it (caller falls back local)."""
        self._validate_bias(sampling)
        ps = self.config.page_size
        need = -(-(len(prompt_tokens) + 1) // ps)
        pages = self.allocator.allocate(need)
        if pages is None:
            return None
        req = Request(
            request_id=request_id,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            arrival_time=time.time(),
        )
        req.pages = pages
        return req

    def add_prefilled(self, req: Request, first_token: int) -> list[StepOutput]:
        """Admit a remote-prefilled request into decode: its pages hold the
        prompt KV; accept the prefill worker's first sampled token and let
        the normal decode loop continue."""
        chain = TokenBlockSequence(
            req.prompt_tokens, block_size=self.config.page_size,
            salt=self.config.model,
        )
        self.scheduler.add_prefilled(req, chain)
        outputs = self._accept_token(req, first_token, first=True)
        self._register_pages(req)
        self._refresh_metrics()
        return outputs

    def cancel_remote_prefill(self, req: Request) -> None:
        """Transfer failed or timed out: give the reservation back."""
        if req.pages:
            self.allocator.free(req.pages)
            req.pages = []

    def _register_pages(self, req: Request) -> None:
        """Content-address any newly *filled* pages (enables prefix sharing
        and emits 'stored' KV events for routers)."""
        if not self.config.enable_prefix_caching or req.mm_embeds is not None:
            return
        chain = self.scheduler.chains.get(req.request_id)
        if chain is None:
            return
        ps = self.config.page_size
        full_computed = min(req.num_computed_tokens, len(chain) ) // ps
        for bi in range(full_computed):
            if bi >= len(req.pages):
                break
            block = chain.blocks[bi]
            self.allocator.register(
                req.pages[bi],
                block.sequence_hash,
                block.parent_sequence_hash,
                block.tokens,
            )

    def _refresh_metrics(self) -> None:
        # Complete async KVBM offloads started last step (double buffer:
        # the device→host copies overlapped this step's compute).
        self.allocator.flush_offloads()
        m = self.metrics
        m.num_waiting = self.scheduler.num_waiting()
        m.num_running = self.scheduler.num_running()
        m.kv_active_pages = self.allocator.num_active
        m.kv_free_pages = self.allocator.num_free
        m.kv_usage = self.allocator.usage()
        m.prefix_hit_rate = self.allocator.stats.hit_rate
        m.kv_pages_watermark = max(
            getattr(self.allocator, "watermark", 0), m.kv_active_pages,
            m.kv_pages_watermark,
        )
        m.preemptions = self.scheduler.preemptions
        if self._spec_draft or self.config.spec_ngram > 0:
            # live acceptance-rate gauge over the spec-step window
            now_s = time.perf_counter()
            sw = self._spec_window
            while sw and now_s - sw[0][0] > self._spec_window_s:
                _, d, a = sw.popleft()
                self._spec_win_drafted -= d
                self._spec_win_accepted -= a
            m.spec_accept_rate = (
                round(self._spec_win_accepted / self._spec_win_drafted, 4)
                if self._spec_win_drafted
                else 0.0
            )
            m.spec_window_drafted = self._spec_win_drafted
        # pre-admission deadline drops land here; the runner adds its own
        # mid-decode expiries on top (they never reach the scheduler)
        m.deadline_expired = (
            self.scheduler.deadline_drops + self._runner_deadline_expired
        )
        if self._fleet_telemetry:
            # windowed throughput -> live MFU against the roofline peak
            now = time.perf_counter()
            w = self._thru_window
            while w and now - w[0][0] > self._thru_window_s:
                self._thru_tokens -= w.popleft()[1]
            if len(w) >= 2:
                span = now - w[0][0]
                toks = self._thru_tokens
                if span > 1e-3 and toks:
                    rate = toks / span
                    m.tokens_per_s = round(rate, 2)
                    m.mfu = min(
                        1.0,
                        2.0 * self._n_active_params * rate
                        / self._peak_flops,
                    )
            else:
                # window drained: an idle worker must report zero, not
                # its last busy throughput forever
                m.tokens_per_s = 0.0
                m.mfu = 0.0

    # -- debug plane: program cost model + on-demand profiling ------------
    # (docs/observability.md "Debugging a slow or stuck worker")

    #: program kind -> the (cumulative ms, dispatch count) metrics pair
    #: whose ratio is that kind's measured ms/dispatch. Decode-family
    #: kinds share the decode columns; mixed steps land in time_mixed_ms.
    _MEASURED_BY_KIND = {
        "prefill": ("time_prefill_ms", "prefill_dispatches"),
        "prefill_nosample": ("time_prefill_ms", "prefill_dispatches"),
        "decode": ("time_decode_ms", "decode_dispatches"),
        "decode_multi": ("time_decode_ms", "decode_dispatches"),
        "decode_kstep": ("time_kstep_ms", "kstep_windows"),
        "spec_verify": ("time_decode_ms", "decode_dispatches"),
        "spec_fused": ("time_decode_ms", "decode_dispatches"),
        "spec_draft_prefill": ("time_prefill_ms", "prefill_dispatches"),
        "mixed": ("time_mixed_ms", "mixed_dispatches"),
    }

    @staticmethod
    def _roofline_ms(
        flops: Optional[float], nbytes: Optional[float],
        peak_flops: float, peak_bytes_s: float,
    ) -> Optional[float]:
        """Cost-model floor for one dispatch: the slower of the compute
        roof (flops / peak FLOP/s) and the memory roof (bytes accessed /
        peak HBM bytes/s) — the same arithmetic as docs/PERF.md's
        decode-roofline table, per compiled program."""
        t = 0.0
        if flops and peak_flops:
            t = max(t, flops / peak_flops)
        if nbytes and peak_bytes_s:
            t = max(t, nbytes / peak_bytes_s)
        return round(t * 1e3, 6) if t > 0 else None

    def programs_report(self) -> dict:
        """GET /v1/debug/programs: every compiled program's cost model
        (compile ms, cost_analysis flops/bytes, roofline ms) plus a
        per-kind rollup joining the kind's production-shape program (its
        most expensive one — smaller warmup buckets would flatter the
        number) with the measured ms/dispatch from the step-phase
        counters into roofline %-attainment. Note the measured column is
        host wall time per dispatch — under overlap_decode it contains
        host-loop overhead the roofline doesn't, which is exactly the
        gap ROADMAP item 3 (on-device multi-step scheduling) attacks."""
        from dynamo_tpu.platform import device_peak_bytes_per_s

        peak_f = self._peak_flops
        peak_b = device_peak_bytes_per_s()
        m = self.metrics
        programs: list[dict] = []
        kinds: dict[str, dict] = {}
        # list() first: the engine thread inserts on steady-state
        # recompiles (the compile-storm case this report diagnoses)
        # while the publish loop / debug endpoints iterate here
        for p in list(self.programs.values()):
            rl = self._roofline_ms(p["flops"], p["bytes"], peak_f, peak_b)
            programs.append(dict(p, roofline_ms=rl))
            k = kinds.setdefault(
                p["kind"],
                {"programs": 0, "compile_ms": 0.0, "flops": None,
                 "bytes": None, "roofline_ms": None},
            )
            k["programs"] += 1
            k["compile_ms"] = round(k["compile_ms"] + p["compile_ms"], 3)
            if p["flops"] is not None and (
                k["flops"] is None or p["flops"] > k["flops"]
            ):
                k["flops"], k["bytes"], k["roofline_ms"] = (
                    p["flops"], p["bytes"], rl
                )
        for kind, k in kinds.items():
            k["compiles"] = self.compiles_by_kind.get(kind, 0)
            pair = self._MEASURED_BY_KIND.get(kind)
            measured = None
            if pair is not None:
                total_ms, disp = getattr(m, pair[0]), getattr(m, pair[1])
                if disp:
                    measured = round(total_ms / disp, 3)
            k["measured_ms_per_dispatch"] = measured
            # 6 digits: tiny CPU-dev attainments (roofline µs vs a
            # compile-laden first dispatch's 100s of ms) must not round
            # to an indistinguishable 0.0
            k["attainment"] = (
                round(min(1.0, k["roofline_ms"] / measured), 6)
                if k["roofline_ms"] and measured
                else None
            )
        return {
            "peak_flops": peak_f,
            "peak_bytes_per_s": peak_b,
            "programs": programs,
            "kinds": kinds,
        }

    def programs_wire(self) -> dict:
        """The compact per-kind rollup that rides the metrics frame."""
        return self.programs_report()["kinds"]

    # -- HBM accounting & mesh introspection (GET /v1/debug/{memory,
    # mesh} — docs/observability.md "Reading the perf plane"). All
    # host-side, publish-cadence work: the token path never runs any of
    # it, and with collection enabled the emitted tokens are
    # bit-identical (pinned in tests/test_perf_plane.py). ---------------

    @staticmethod
    def _device_key(dev) -> str:
        """Stable per-device label: the jax device id (the `device`
        label of the dynamo_tpu_hbm_* families)."""
        return str(getattr(dev, "id", 0))

    def _per_device_bytes(self, tree) -> dict[str, int]:
        """Bytes each addressable device holds of `tree`: sharded
        jax.Arrays contribute their LOCAL shard bytes to the device each
        shard lives on (so a tp=4 weight counts a quarter per chip);
        host-resident leaves (numpy, before any device_put) are
        attributed to device 0, where the first dispatch places them."""
        out: dict[str, int] = {}
        default = self._device_key(jax.devices()[0])
        for x in jax.tree.leaves(tree):
            shards = getattr(x, "addressable_shards", None)
            if shards:
                for s in shards:
                    k = self._device_key(s.device)
                    out[k] = out.get(k, 0) + int(s.data.nbytes)
            else:
                out[default] = (
                    out.get(default, 0) + int(getattr(x, "nbytes", 0))
                )
        return out

    def _param_group_specs(self) -> dict:
        """Per-sharding-spec param grouping for /v1/debug/mesh:
        spec-string -> {params, bytes, logical}. `logical` lists the
        model-declared logical axis names (models/*_logical_axes
        leaves, e.g. "(layers, None, heads)") that resolved into this
        placement through the rule table — the provenance half of the
        logical-axis system. Meshless engines group everything under
        "replicated"."""
        leaves = jax.tree.leaves(self.params)
        logical: list = [None] * len(leaves)
        if getattr(self.adapter, "logical_axes", None) is not None:
            try:
                from jax.sharding import PartitionSpec as P

                from dynamo_tpu.parallel.logical import AxisNames

                ax = jax.tree.leaves(
                    self.adapter.logical_axes(
                        quantized=bool(self.config.quantize)
                    ),
                    is_leaf=lambda x: isinstance(x, (AxisNames, P)),
                )
                if len(ax) == len(leaves):
                    logical = ax
            except Exception:  # noqa: BLE001 — provenance is advisory;
                # the byte accounting must never fail over it
                logger.exception("logical-axis provenance unavailable")
        groups: dict[str, dict] = {}
        for x, names in zip(leaves, logical):
            spec = getattr(getattr(x, "sharding", None), "spec", None)
            key = str(spec) if spec is not None else "replicated"
            g = groups.setdefault(
                key, {"params": 0, "bytes": 0, "logical": []}
            )
            g["params"] += 1
            g["bytes"] += int(getattr(x, "nbytes", 0))
            if names is not None:
                lbl = "(" + ", ".join(str(n) for n in names) + ")"
                if lbl not in g["logical"]:
                    g["logical"].append(lbl)
        return groups

    def memory_report(self) -> dict:
        """GET /v1/debug/memory: per-device HBM byte breakdown.

        Accounted components: `weights` (param-tree shard bytes, cached
        at construction — they never change), `kv_pool` (paged KV +
        draft KV incl. quantization scale planes), `scratch` — an
        ESTIMATE: the hungriest compiled program's cost_analysis bytes
        accessed beyond the resident weights+KV it streams (the
        transient-buffer proxy PR 7's cost capture affords; XLA exposes
        no true temp-allocation number pre-execution), split evenly
        across local devices. live/free/peak come from jax device
        `memory_stats()` where the backend provides them (TPU); the
        documented CPU fallback is pure accounting — live =
        weights+kv+scratch, free = platform.device_hbm_bytes() − live
        (the shared per-generation table, same sourcing as the program
        cost model's peaks), peak = live. `source` names which path
        produced the live numbers."""
        from dynamo_tpu.platform import device_hbm_bytes

        kv_by_dev = self._per_device_bytes((self.kv, self.draft_kv))
        weights = self._weights_by_device
        total_w = sum(weights.values())
        total_kv = sum(kv_by_dev.values())
        prog_bytes = [
            p["bytes"] for p in list(self.programs.values())
            if p.get("bytes")
        ]
        scratch_total = max(
            0, int(max(prog_bytes, default=0)) - total_w - total_kv
        )
        devs = jax.local_devices()
        scratch_each = scratch_total // max(1, len(devs))
        limit_nominal = int(device_hbm_bytes())
        devices: dict[str, dict] = {}
        source = "accounted"
        for d in devs:
            key = self._device_key(d)
            w = int(weights.get(key, 0))
            kvb = int(kv_by_dev.get(key, 0))
            row = {
                "kind": str(getattr(d, "device_kind", "cpu")),
                "weights_bytes": w,
                "kv_pool_bytes": kvb,
                "scratch_bytes": scratch_each,
            }
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and stats.get("bytes_in_use") is not None:
                source = "memory_stats"
                live = int(stats.get("bytes_in_use") or 0)
                limit = int(stats.get("bytes_limit") or limit_nominal)
                row["live_bytes"] = live
                row["limit_bytes"] = limit
                row["free_bytes"] = max(0, limit - live)
                row["peak_bytes"] = int(
                    stats.get("peak_bytes_in_use") or live
                )
            else:
                live = w + kvb + scratch_each
                row["live_bytes"] = live
                row["limit_bytes"] = limit_nominal
                row["free_bytes"] = max(0, limit_nominal - live)
                row["peak_bytes"] = live
            devices[key] = row
        totals = {
            f: sum(r[f] for r in devices.values())
            for f in (
                "weights_bytes", "kv_pool_bytes", "scratch_bytes",
                "live_bytes", "free_bytes", "peak_bytes",
            )
        }
        return {"source": source, "devices": devices, "totals": totals}

    def refresh_memory_metrics(self) -> dict:
        """Fold memory_report totals into the EngineMetrics hbm_*
        gauges plus the host/dispatch straggler fields (the worker's
        publish loop calls this once per frame). Returns the full
        report so a caller wanting both doesn't pay twice."""
        rep = self.memory_report()
        t = rep["totals"]
        m = self.metrics
        m.hbm_weights_bytes = t["weights_bytes"]
        m.hbm_kv_pool_bytes = t["kv_pool_bytes"]
        m.hbm_scratch_bytes = t["scratch_bytes"]
        m.hbm_free_bytes = t["free_bytes"]
        m.hbm_peak_bytes = t["peak_bytes"]
        try:
            m.host = int(jax.process_index())
        except Exception:
            m.host = 0
        m.dispatch_p95_ms = float(
            self.dispatch_stats().get("p95_ms") or 0.0
        )
        return rep

    #: flight-record kinds whose step wall time counts as a decode
    #: dispatch for the straggler gauge
    _DISPATCH_KINDS = ("decode", "decode_multi", "decode_kstep", "mixed")

    def dispatch_stats(self) -> dict:
        """Recent-window decode dispatch wall-time stats (the per-host
        half of the host-skew gauge, /v1/debug/mesh): p50/p95/mean over
        the flight ring's decode-ish records. With the recorder off,
        the lifetime mean from the cumulative counters stands in for
        every quantile — no window exists to rank."""
        if self.flight is not None:
            vals = sorted(
                float(r.get("step_ms") or 0.0)
                for r in self.flight.snapshot(None)
                if r.get("kind") in self._DISPATCH_KINDS
            )
            if vals:
                def q(p: float) -> float:
                    return round(
                        vals[min(len(vals) - 1, int(p * len(vals)))], 3
                    )

                return {
                    "n": len(vals),
                    "p50_ms": q(0.50),
                    "p95_ms": q(0.95),
                    "mean_ms": round(sum(vals) / len(vals), 3),
                }
        m = self.metrics
        disp = m.decode_dispatches + m.mixed_dispatches + m.kstep_windows
        total = m.time_decode_ms + m.time_mixed_ms + m.time_kstep_ms
        mean = round(total / disp, 3) if disp else None
        return {"n": disp, "p50_ms": mean, "p95_ms": mean, "mean_ms": mean}

    def mesh_report(self) -> dict:
        """GET /v1/debug/mesh: what the SPMD layer actually built —
        mesh shape + axis names, the per-sharding-spec param grouping
        (with each group's logical-axis names), the rule table that
        resolved those names to mesh axes, the KV pool's sharding, this
        replica's process seat, and the recent decode dispatch window
        (the metrics service compares the latter ACROSS hosts into the
        fleet's host-skew view)."""
        mesh_doc = None
        if self.mesh is not None:
            mesh_doc = {
                "axis_names": [str(a) for a in self.mesh.axis_names],
                "shape": {
                    str(k): int(v) for k, v in self.mesh.shape.items()
                },
                "devices": int(self.mesh.devices.size),
            }
        try:
            pi, pc = int(jax.process_index()), int(jax.process_count())
        except Exception:
            pi, pc = 0, 1
        kv_spec = getattr(
            getattr(getattr(self.kv, "k", None), "sharding", None),
            "spec", None,
        )
        return {
            "mesh": mesh_doc,
            "multiprocess": bool(self._multiproc),
            "process_index": pi,
            "process_count": pc,
            "param_groups": self._param_groups,
            "logical_axis_rules": [
                list(r) for r in default_rules().doc()
            ],
            "kv_sharding": (
                str(kv_spec) if kv_spec is not None else "replicated"
            ),
            "dispatch": self.dispatch_stats(),
        }

    def request_profile(self, steps: int, outdir: Optional[str] = None) -> dict:
        """Arm a jax.profiler capture for `steps` engine steps (POST
        /v1/debug/profile). The engine thread starts the trace at the
        end of its next step() and stops it after `steps` dispatched
        steps, so the capture brackets whole dispatches. Thread-safe;
        refuses while a capture is already armed. An idle engine starts
        capturing at its next piece of traffic."""
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        if outdir is None:
            outdir = os.path.join(
                "artifacts", "profile",
                f"{self.config.model.replace('/', '_')}-{int(time.time())}",
            )
        with self._profile_lock:
            if self._profile is not None:
                raise RuntimeError(
                    "a profile capture is already armed/running"
                )
            self._profile = {
                "steps_left": int(steps), "dir": outdir, "started": False,
            }
        return {"dir": outdir, "steps": int(steps)}

    def _profile_start(self) -> None:
        """Engine-thread half of request_profile (1/2): open the trace
        before the first step after arming. Behind a plain None check in
        step() — zero cost unarmed."""
        with self._profile_lock:
            p = self._profile
            if p is None or p["started"]:
                return
            try:
                os.makedirs(p["dir"], exist_ok=True)
                jax.profiler.start_trace(p["dir"])
            except Exception:
                logger.exception("jax.profiler capture failed to start")
                self._profile = None
                return
            p["started"] = True
            logger.info(
                "profiling %d steps into %s", p["steps_left"], p["dir"]
            )

    def _profile_count(self) -> None:
        """Engine-thread half of request_profile (2/2): one dispatched
        step captured; stop after the armed count."""
        with self._profile_lock:
            p = self._profile
            if p is None or not p["started"]:
                return
            p["steps_left"] -= 1
            if p["steps_left"] <= 0:
                try:
                    jax.profiler.stop_trace()
                    logger.info("profile capture done: %s", p["dir"])
                except Exception:
                    logger.exception("jax.profiler stop failed")
                self._profile = None

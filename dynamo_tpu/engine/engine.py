"""JaxEngine: the TPU-native inference engine.

Owns the model params, the device page pool, the host-side allocator and
continuous-batching scheduler, and a small cache of jitted step programs
(one per (kind, bucket) shape). This is the first-class engine the reference
lacks natively (it shells out to vLLM/SGLang/TRT-LLM — SURVEY.md L4);
tokens-in/tokens-out, KV events and worker metrics out.

Execution model per `step()`:
  scheduler -> ScheduledBatch -> pad to bucket -> jitted forward+sample ->
  host sync of sampled ids -> append/finish bookkeeping + page registration.

Multi-chip: pass a MeshConfig; params/KV are device_put with tp/dp
PartitionSpecs and the same jitted programs run SPMD over the mesh.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import KvEvent, PageAllocator
from dynamo_tpu.engine.request import (
    FinishReason,
    Request,
    RequestState,
    SamplingParams,
    StepOutput,
)
from dynamo_tpu.engine.sampling import sample
from dynamo_tpu.engine.scheduler import ScheduledBatch, Scheduler
from dynamo_tpu.models.registry import ModelAdapter, get_model
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.parallel.shardings import batch_spec, shardings_for
from dynamo_tpu.tokens import TokenBlockSequence

logger = logging.getLogger(__name__)


@dataclass
class EngineMetrics:
    """Worker load snapshot published to routers/planner (parity with the
    reference's ForwardPassMetrics — kv_router/protocols.rs:43-69)."""

    num_waiting: int = 0
    num_running: int = 0
    kv_active_pages: int = 0
    kv_total_pages: int = 0
    kv_usage: float = 0.0
    prefix_hit_rate: float = 0.0
    steps: int = 0
    generated_tokens: int = 0
    #: monotonically increasing arrivals (planner derives request_rate)
    requests_received: int = 0

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class JaxEngine:
    def __init__(
        self,
        config: EngineConfig,
        params=None,
        mesh_config: Optional[MeshConfig] = None,
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
        checkpoint_path: Optional[str] = None,
    ):
        self.config = config
        mc = mesh_config or MeshConfig(dp=config.dp, tp=config.tp)
        impl = config.attention_impl
        if impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"unknown attention_impl {impl!r}; use auto|xla|pallas"
            )
        if impl == "pallas" and mc.num_devices > 1:
            raise ValueError(
                "attention_impl='pallas' is single-chip only for now (the "
                "kernel is not shard_map-wrapped for GSPMD); use 'auto'"
            )
        if impl == "auto":
            # The pallas decode kernel is not yet shard_map-wrapped for
            # GSPMD partitioning, so multi-chip meshes stay on the XLA path.
            impl = (
                "pallas"
                if jax.default_backend() == "tpu" and mc.num_devices == 1
                else "xla"
            )
        self.adapter: ModelAdapter = get_model(
            config.model, dtype=config.dtype, attention_impl=impl
        )
        if config.host_kv_cache_bytes > 0 or config.disk_kv_cache_bytes > 0:
            from dynamo_tpu.kvbm import TieredPageAllocator

            self.allocator: PageAllocator = TieredPageAllocator(
                config.num_pages,
                config.page_size,
                extract_fn=self.extract_pages,
                inject_fn=self.inject_pages,
                host_bytes=config.host_kv_cache_bytes,
                disk_bytes=config.disk_kv_cache_bytes,
                disk_dir=config.disk_kv_cache_dir,
                on_event=on_kv_event,
            )
        else:
            self.allocator = PageAllocator(
                config.num_pages, config.page_size, on_event=on_kv_event
            )
        self.scheduler = Scheduler(config, self.allocator)
        self.metrics = EngineMetrics(kv_total_pages=config.num_pages - 1)
        self._outputs_emitted: set[str] = set()
        self._jit_cache: dict[tuple, Callable] = {}

        self.mesh = make_mesh(mc) if mc.num_devices > 1 else None

        if params is None:
            if checkpoint_path is not None and self.adapter.load_params:
                params = self.adapter.load_params(checkpoint_path)
            else:
                logger.info("initializing random params for %s", config.model)
                params = self.adapter.init_params(jax.random.key(0))
        kv = self.adapter.init_kv(config.num_pages, config.page_size)
        if self.mesh is not None:
            params = jax.device_put(
                params, shardings_for(self.mesh, self.adapter.param_specs())
            )
            kv = jax.device_put(kv, shardings_for(self.mesh, self.adapter.kv_spec()))
        self.params = params
        self.kv = kv
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            self._batch_shardings = {
                nd: NamedSharding(self.mesh, batch_spec(nd)) for nd in (1, 2)
            }
        else:
            self._batch_shardings = None

    def _dev(self, arr: np.ndarray):
        """Host batch array -> device, dp-sharded along dim 0 on a mesh.

        Batches not divisible by dp (B=1 prefill, small decode buckets) are
        left for jit to reshard — an explicit device_put would raise."""
        x = jnp.asarray(arr)
        if self._batch_shardings is not None:
            dp = self.mesh.shape.get("dp", 1)
            if dp > 1 and arr.shape[0] % dp == 0:
                x = jax.device_put(x, self._batch_shardings[arr.ndim])
        return x

    # -- public API --------------------------------------------------------

    def add_request(
        self,
        request_id: str,
        prompt_tokens: Sequence[int],
        sampling: Optional[SamplingParams] = None,
    ) -> Request:
        req = Request(
            request_id=request_id,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            arrival_time=time.time(),
        )
        self.scheduler.add_request(req)
        self.metrics.requests_received += 1
        return req

    def abort_request(self, request_id: str) -> bool:
        return self.scheduler.abort_request(request_id) is not None

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self) -> list[StepOutput]:
        batch = self.scheduler.schedule()
        outputs = self._drain_doomed()
        if batch is not None:
            if batch.kind == "prefill":
                outputs += self._run_prefill(batch)
            else:
                outputs += self._run_decode(batch)
            self.metrics.steps += 1
        self._refresh_metrics()
        return outputs

    def _drain_doomed(self) -> list[StepOutput]:
        """Finish requests the scheduler proved can never progress."""
        outputs = []
        for req, why in self.scheduler.doomed:
            logger.error("request %s cannot progress: %s", req.request_id, why)
            req.state = RequestState.FINISHED
            req.finish_reason = FinishReason.LENGTH
            outputs.append(
                StepOutput(
                    request_id=req.request_id,
                    new_token_ids=(),
                    finish_reason=FinishReason.LENGTH,
                )
            )
        self.scheduler.doomed.clear()
        return outputs

    def run_to_completion(self) -> dict[str, list[int]]:
        """Drain all queued work; returns request_id -> generated tokens."""
        done: dict[str, list[int]] = {}
        while self.has_work:
            for out in self.step():
                done.setdefault(out.request_id, []).extend(out.new_token_ids)
        return done

    # -- prefill -----------------------------------------------------------

    def _bucket_t(self, n: int) -> int:
        t = 32
        while t < n:
            t *= 2
        return min(t, max(self.config.prefill_chunk, 32))

    def _run_prefill(self, batch: ScheduledBatch) -> list[StepOutput]:
        outputs: list[StepOutput] = []
        for piece in batch.prefill:
            req = piece.request
            is_last_chunk = (
                piece.start + piece.length >= len(req.prompt_tokens)
            )
            t_bucket = self._bucket_t(piece.length)
            mp = self.config.max_pages_per_seq
            tokens = np.zeros((1, t_bucket), np.int32)
            chunk = req.all_tokens[piece.start : piece.start + piece.length]
            tokens[0, : piece.length] = chunk
            positions = np.arange(t_bucket, dtype=np.int32)[None] + piece.start
            valid = np.zeros((1, t_bucket), bool)
            valid[0, : piece.length] = True
            pt = np.zeros((1, mp), np.int32)
            pt[0, : len(req.pages)] = req.pages

            args = (
                self.params, self._dev(tokens), self._dev(positions),
                self._dev(valid), self.kv, self._dev(pt),
            )
            if is_last_chunk:
                fn = self._get_step_fn("prefill", 1, t_bucket)
                samp = self._sampling_arrays([req])
                last_idx = np.array([piece.length - 1], np.int32)
                token_ids, self.kv = fn(*args, self._dev(last_idx), *samp)
            else:
                # Mid-prompt chunk: KV writes only — skip the vocab-sized
                # logits + sort entirely.
                fn = self._get_step_fn("prefill_nosample", 1, t_bucket)
                self.kv = fn(*args)
            req.num_computed_tokens += piece.length
            self._register_pages(req)
            if req.prefill_done:
                req.state = RequestState.DECODE
                tok = int(np.asarray(token_ids)[0])
                outputs.extend(self._accept_token(req, tok, first=True))
        return outputs

    # -- decode ------------------------------------------------------------

    def _run_decode(self, batch: ScheduledBatch) -> list[StepOutput]:
        reqs = list(batch.decode)
        b_bucket = self.config.decode_bucket_for(len(reqs))
        mp = self.config.max_pages_per_seq
        b = len(reqs)
        tokens = np.zeros((b_bucket, 1), np.int32)
        positions = np.zeros((b_bucket, 1), np.int32)
        valid = np.zeros((b_bucket, 1), bool)
        pt = np.zeros((b_bucket, mp), np.int32)
        for i, req in enumerate(reqs):
            tokens[i, 0] = req.all_tokens[-1]
            positions[i, 0] = req.num_tokens - 1
            valid[i, 0] = True
            pt[i, : len(req.pages)] = req.pages

        fn = self._get_step_fn("decode", b_bucket, 1)
        samp = self._sampling_arrays(reqs, pad_to=b_bucket)
        last_idx = np.zeros(b_bucket, np.int32)
        token_ids, self.kv = fn(
            self.params, self._dev(tokens), self._dev(positions),
            self._dev(valid), self.kv, self._dev(pt),
            self._dev(last_idx), *samp,
        )
        ids = np.asarray(token_ids)
        outputs: list[StepOutput] = []
        for i, req in enumerate(reqs):
            req.num_computed_tokens += 1
            outputs.extend(self._accept_token(req, int(ids[i])))
            self._register_pages(req)
        return outputs

    # -- shared ------------------------------------------------------------

    def _sampling_arrays(self, reqs: list[Request], pad_to: Optional[int] = None):
        n = pad_to or len(reqs)
        temps = np.zeros(n, np.float32)
        top_ps = np.ones(n, np.float32)
        top_ks = np.zeros(n, np.int32)
        seeds = np.zeros(n, np.uint32)
        counters = np.zeros(n, np.int32)
        for i, r in enumerate(reqs):
            temps[i] = r.sampling.temperature
            top_ps[i] = r.sampling.top_p
            top_ks[i] = r.sampling.top_k
            seeds[i] = self._request_seed(r)
            # num_emitted keeps the draw counter monotonic across preemption
            counters[i] = r.num_emitted + len(r.output_tokens)
        return (
            self._dev(temps), self._dev(top_ps), self._dev(top_ks),
            self._dev(seeds), self._dev(counters),
        )

    def _request_seed(self, req: Request) -> int:
        if req.sampling.seed is not None:
            return req.sampling.seed & 0xFFFFFFFF
        import xxhash

        return (
            xxhash.xxh32_intdigest(req.request_id.encode(), seed=self.config.seed)
            & 0xFFFFFFFF
        )

    def _get_step_fn(self, kind: str, b: int, t: int) -> Callable:
        cache_key = (kind, b, t)
        fn = self._jit_cache.get(cache_key)
        if fn is not None:
            return fn
        adapter = self.adapter

        if kind == "prefill_nosample":

            def nosample_fn(params, tokens, positions, valid, kv, pt):
                _, kv = adapter.forward_hidden(
                    params, tokens, positions, valid, kv, pt
                )
                return kv

            jitted = jax.jit(nosample_fn, donate_argnums=(4,))
            self._jit_cache[cache_key] = jitted
            logger.info("compiled %s program B=%d T=%d", kind, b, t)
            return jitted

        def step_fn(params, tokens, positions, valid, kv, pt, last_idx,
                    temps, top_ps, top_ks, seeds, counters):
            hidden, kv = adapter.forward_hidden(params, tokens, positions, valid, kv, pt)
            rows = jnp.arange(hidden.shape[0])
            last_hidden = hidden[rows, last_idx]  # [B, H] — lm_head only here
            logits = adapter.compute_logits(params, last_hidden)
            ids = sample(logits, temps, top_ps, top_ks, seeds, counters)
            return ids, kv

        jitted = jax.jit(step_fn, donate_argnums=(4,))
        self._jit_cache[cache_key] = jitted
        logger.info("compiled %s program B=%d T=%d", kind, b, t)
        return jitted

    def _accept_token(self, req: Request, token: int, first: bool = False) -> list[StepOutput]:
        req.output_tokens.append(token)
        chain = self.scheduler.chains.get(req.request_id)
        if chain is not None:
            chain.append(token)
        self.metrics.generated_tokens += 1
        finish: Optional[FinishReason] = None
        s = req.sampling
        if not s.ignore_eos and (
            token in self.config.eos_token_ids or token in s.stop_token_ids
        ):
            finish = FinishReason.STOP
        elif len(req.output_tokens) + req.num_emitted >= s.max_tokens:
            finish = FinishReason.LENGTH
        elif req.num_tokens >= self.config.max_context:
            finish = FinishReason.LENGTH
        if finish is not None:
            self.scheduler.finish(req)
            req.finish_reason = finish
        return [
            StepOutput(
                request_id=req.request_id,
                new_token_ids=(token,),
                finish_reason=finish,
                is_first=first,
            )
        ]

    # -- disaggregated prefill/decode hooks -------------------------------
    # (decode side pre-allocates pages; a prefill worker computes the KV,
    #  extracts it from its own pool, and the transfer service injects it
    #  here — the reference's NIXL RDMA write path, dynamo_flow.md:36-38,
    #  re-done as explicit page movement through host/DCN for TPU.)

    def extract_pages(self, page_ids: Sequence[int]):
        """Pull KV pages to host: (k, v) as [L, Hkv, n, page_size, D]."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        k = np.asarray(jax.device_get(jnp.take(self.kv.k, ids, axis=2)))
        v = np.asarray(jax.device_get(jnp.take(self.kv.v, ids, axis=2)))
        return k, v

    def inject_pages(self, page_ids: Sequence[int], k: np.ndarray, v: np.ndarray) -> None:
        """Write transferred KV pages into this engine's pool in place."""
        n = len(page_ids)
        fn = self._jit_cache.get(("inject", n))
        if fn is None:
            def inject_fn(kv, ids, kk, vv):
                return type(kv)(
                    k=kv.k.at[:, :, ids].set(kk.astype(kv.k.dtype)),
                    v=kv.v.at[:, :, ids].set(vv.astype(kv.v.dtype)),
                )
            fn = jax.jit(inject_fn, donate_argnums=(0,))
            self._jit_cache[("inject", n)] = fn
        self.kv = fn(
            self.kv, jnp.asarray(np.asarray(page_ids, np.int32)),
            jnp.asarray(k), jnp.asarray(v),
        )

    def allocate_for_remote_prefill(
        self,
        request_id: str,
        prompt_tokens: Sequence[int],
        sampling: Optional[SamplingParams] = None,
    ) -> Optional[Request]:
        """Decode-side page reservation: allocate the prompt's pages (plus
        one-token headroom) now so a prefill worker can write into them.
        Returns None when the pool can't take it (caller falls back local)."""
        ps = self.config.page_size
        need = -(-(len(prompt_tokens) + 1) // ps)
        pages = self.allocator.allocate(need)
        if pages is None:
            return None
        req = Request(
            request_id=request_id,
            prompt_tokens=list(prompt_tokens),
            sampling=sampling or SamplingParams(),
            arrival_time=time.time(),
        )
        req.pages = pages
        return req

    def add_prefilled(self, req: Request, first_token: int) -> list[StepOutput]:
        """Admit a remote-prefilled request into decode: its pages hold the
        prompt KV; accept the prefill worker's first sampled token and let
        the normal decode loop continue."""
        chain = TokenBlockSequence(
            req.prompt_tokens, block_size=self.config.page_size,
            salt=self.config.model,
        )
        self.scheduler.add_prefilled(req, chain)
        outputs = self._accept_token(req, first_token, first=True)
        self._register_pages(req)
        self._refresh_metrics()
        return outputs

    def cancel_remote_prefill(self, req: Request) -> None:
        """Transfer failed or timed out: give the reservation back."""
        if req.pages:
            self.allocator.free(req.pages)
            req.pages = []

    def _register_pages(self, req: Request) -> None:
        """Content-address any newly *filled* pages (enables prefix sharing
        and emits 'stored' KV events for routers)."""
        if not self.config.enable_prefix_caching:
            return
        chain = self.scheduler.chains.get(req.request_id)
        if chain is None:
            return
        ps = self.config.page_size
        full_computed = min(req.num_computed_tokens, len(chain) ) // ps
        for bi in range(full_computed):
            if bi >= len(req.pages):
                break
            block = chain.blocks[bi]
            self.allocator.register(
                req.pages[bi],
                block.sequence_hash,
                block.parent_sequence_hash,
                block.tokens,
            )

    def _refresh_metrics(self) -> None:
        m = self.metrics
        m.num_waiting = self.scheduler.num_waiting()
        m.num_running = self.scheduler.num_running()
        m.kv_active_pages = self.allocator.num_active
        m.kv_usage = self.allocator.usage()
        m.prefix_hit_rate = self.allocator.stats.hit_rate

"""Host-side paged-KV allocator with content-addressed prefix caching.

The device holds one flat page pool (models/llama.py KVPages); this module
owns which page belongs to whom. Three ideas:

1. **Ref-counted pages**: a page can back multiple sequences when they share
   a prefix (same chained block hash ⇒ byte-identical KV).
2. **Prefix cache**: full pages are registered under their TokenBlock
   sequence hash; new requests reuse any cached prefix chain. Freed pages
   stay cached (refcount 0) in an LRU until reclaimed.
3. **KV events**: every cache store/remove emits an event for the KV-aware
   router's global index (parity with the reference's engine-emitted KV
   events — /root/reference lib/llm/src/kv_router/publisher.rs; vLLM's ZMQ
   event stream — and the mocker's KvManager, mocker/kv_manager.rs:121).

Page 0 is the null page (padding writes), never allocated.

The bookkeeping core (free list, refcounts, hash maps, LRU reclaim) runs in
C++ when libdynamo_native is available (native/pool.cpp — reference parity
with the native Rust block pool, lib/llm/src/block_manager/pool.rs); the
pure-Python path below is the fallback and the semantic spec. Page metadata
(parent hashes, token payloads for KV events) and stats stay Python-side in
both modes. Tests assert both paths agree on random workloads.
"""

from __future__ import annotations

import ctypes
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Literal, Optional, Sequence

from dynamo_tpu import native


@dataclass(frozen=True)
class KvEvent:
    """Block stored/removed in this worker's KV cache."""

    kind: Literal["stored", "removed"]
    #: chained sequence hashes (tokens/blocks.py) — one per block
    block_hashes: tuple[int, ...]
    #: parent chain hash for "stored" (None at root)
    parent_hash: Optional[int] = None
    #: token payload for stored events (lets indexers rebuild chains)
    token_blocks: tuple[tuple[int, ...], ...] = ()


@dataclass
class PrefixCacheStats:
    queries: int = 0
    hit_tokens: int = 0
    query_tokens: int = 0
    stored_blocks: int = 0
    evicted_blocks: int = 0
    # KVBM tier movement (dynamo_tpu/kvbm) — zero when tiering is off
    offloaded_blocks: int = 0
    onboarded_blocks: int = 0

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0


class PageAllocator:
    """Free-list + refcount + prefix-cache LRU over a fixed page pool."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        on_event: Optional[Callable[[KvEvent], None]] = None,
    ):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the null page)")
        self.num_pages = num_pages
        self.page_size = page_size
        #: page id -> (seq_hash, parent_hash, tokens) for registered pages
        self._page_meta: dict[int, tuple[int, Optional[int], tuple[int, ...]]] = {}
        self._on_event = on_event
        self.stats = PrefixCacheStats()
        #: high-watermark of active (referenced) pages since boot — the
        #: pool-pressure gauge the fleet plane exports; updated on every
        #: successful allocation, so peaks between metric refreshes are
        #: still captured
        self.watermark = 0
        self._nlib = native.lib()
        if self._nlib is not None:
            self._np = self._nlib.dyn_pool_new(num_pages)
        else:
            self._np = None
        if self._np is None:
            self._free: list[int] = list(range(num_pages - 1, 0, -1))
            self._refcount: dict[int, int] = {}
            #: full pages registered by content: seq_hash -> page id
            self._by_hash: dict[int, int] = {}
            #: refcount-0 registered pages, LRU order (oldest first)
            self._reclaimable: OrderedDict[int, None] = OrderedDict()

    def __del__(self):
        np_, lib = getattr(self, "_np", None), getattr(self, "_nlib", None)
        if np_ is not None and lib is not None:
            lib.dyn_pool_delete(np_)

    # -- capacity ----------------------------------------------------------

    @property
    def num_free(self) -> int:
        """Pages allocatable right now (free list + reclaimable cache)."""
        if self._np is not None:
            return self._nlib.dyn_pool_num_free(self._np)
        return len(self._free) + len(self._reclaimable)

    @property
    def num_active(self) -> int:
        return (self.num_pages - 1) - self.num_free

    def usage(self) -> float:
        return self.num_active / (self.num_pages - 1)

    def _free_slots(self) -> int:
        """Free-list length — pages allocatable without evicting."""
        if self._np is not None:
            return self._nlib.dyn_pool_free_list_len(self._np)
        return len(self._free)

    def _peek_reclaimable(self, n: int) -> list[int]:
        """The first n pages allocate() would evict (LRU-first)."""
        if n <= 0:
            return []
        if self._np is not None:
            out = (ctypes.c_uint32 * n)()
            got = self._nlib.dyn_pool_peek_reclaimable(self._np, out, n)
            return list(out[:got])
        return list(self._reclaimable)[:n]

    # -- allocation --------------------------------------------------------

    def allocate(self, n: int) -> Optional[list[int]]:
        """Get n fresh pages (evicting cached pages LRU-first), or None."""
        if self._np is not None:
            if n > self.num_free:
                return None
            out = (ctypes.c_uint32 * max(1, n))()
            if not self._nlib.dyn_pool_allocate(self._np, n, out):
                return None
            self._drain_evicted()
            self.watermark = max(self.watermark, self.num_active)
            return list(out[:n])
        if n > self.num_free:
            return None
        out_pages = []
        for _ in range(n):
            if self._free:
                page = self._free.pop()
            else:
                page, _ = self._reclaimable.popitem(last=False)
                self._evict(page)
            self._refcount[page] = 1
            out_pages.append(page)
        self.watermark = max(self.watermark, self.num_active)
        return out_pages

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference; registered pages become reclaimable (stay
        cached), unregistered ones return to the free list."""
        if self._np is not None:
            n = len(pages)
            if n == 0:
                return
            arr = (ctypes.c_uint32 * n)(*pages)
            bad = self._nlib.dyn_pool_release(self._np, arr, n)
            if bad >= 0:
                raise ValueError(f"double free of page {pages[bad]}")
            return
        for page in pages:
            rc = self._refcount.get(page)
            if rc is None:
                raise ValueError(f"double free of page {page}")
            if rc > 1:
                self._refcount[page] = rc - 1
                continue
            del self._refcount[page]
            if page in self._page_meta:
                self._reclaimable[page] = None
                self._reclaimable.move_to_end(page)
            else:
                self._free.append(page)

    # -- prefix cache ------------------------------------------------------

    def register(
        self,
        page: int,
        seq_hash: int,
        parent_hash: Optional[int],
        tokens: tuple[int, ...],
    ) -> None:
        """Content-address a *full* page so future requests can share it."""
        if self._np is not None:
            if not self._nlib.dyn_pool_register(
                self._np, page, seq_hash & 0xFFFFFFFFFFFFFFFF
            ):
                return
        else:
            if page in self._page_meta:
                return
            prev = self._by_hash.get(seq_hash)
            if prev is not None and prev != page:
                # Duplicate content under two pages (two seqs computed the
                # same block concurrently). Keep the existing registration.
                return
            self._by_hash[seq_hash] = page
        self._page_meta[page] = (seq_hash, parent_hash, tokens)
        self.stats.stored_blocks += 1
        self._emit(
            KvEvent(
                kind="stored",
                block_hashes=(seq_hash,),
                parent_hash=parent_hash,
                token_blocks=(tokens,),
            )
        )

    def lookup(self, seq_hashes: Sequence[int]) -> list[int]:
        """Longest cached prefix: page ids for leading hashes present.

        Acquires a reference on each returned page.
        """
        if self._np is not None:
            n = len(seq_hashes)
            pages: list[int] = []
            if n:
                harr = (ctypes.c_uint64 * n)(
                    *(h & 0xFFFFFFFFFFFFFFFF for h in seq_hashes)
                )
                out = (ctypes.c_uint32 * n)()
                k = self._nlib.dyn_pool_lookup(self._np, harr, n, out)
                pages = list(out[:k])
        else:
            pages = []
            for h in seq_hashes:
                page = self._by_hash.get(h)
                if page is None:
                    break
                self._acquire(page)
                pages.append(page)
        self.stats.queries += 1
        self.stats.query_tokens += len(seq_hashes) * self.page_size
        self.stats.hit_tokens += len(pages) * self.page_size
        return pages

    def resident_match_length(self, seq_hashes: Sequence[int]) -> int:
        """Alias of match_length on the base allocator; the tiered
        subclass extends the chain through its host/disk tiers."""
        return self.match_length(seq_hashes)

    def register_promoted(
        self,
        page: int,
        seq_hash: int,
        parent_hash: Optional[int],
        tokens: tuple[int, ...],
    ) -> None:
        """Register a block whose bytes were just brought (back) onto the
        device — from a lower tier or a peer. The tiered subclass also
        drops lower-tier copies and counts the onboard."""
        self.register(page, seq_hash, parent_hash, tokens)

    def match_length(self, seq_hashes: Sequence[int]) -> int:
        """Cached-prefix length in blocks, without acquiring references."""
        if self._np is not None:
            n = len(seq_hashes)
            if not n:
                return 0
            harr = (ctypes.c_uint64 * n)(
                *(h & 0xFFFFFFFFFFFFFFFF for h in seq_hashes)
            )
            return self._nlib.dyn_pool_match_length(self._np, harr, n)
        n = 0
        for h in seq_hashes:
            if h not in self._by_hash:
                break
            n += 1
        return n

    # -- internals ---------------------------------------------------------

    def _acquire(self, page: int) -> None:
        rc = self._refcount.get(page, 0)
        if rc == 0:
            self._reclaimable.pop(page, None)
        self._refcount[page] = rc + 1

    def _pre_evict(self, page: int) -> None:
        """Hook: called while the page's metadata (and device bytes) are
        still intact, before the registration is dropped. KVBM offload
        lives here (kvbm/manager.py)."""

    def _evict(self, page: int) -> None:
        """Python-path eviction (native evictions arrive via _drain_evicted)."""
        self._pre_evict(page)
        seq_hash, _, _ = self._page_meta.pop(page)
        del self._by_hash[seq_hash]
        self.stats.evicted_blocks += 1
        self._emit(KvEvent(kind="removed", block_hashes=(seq_hash,)))

    def _drain_evicted(self) -> None:
        """Process evictions queued inside the native pool: run the offload
        hook (device bytes are untouched until the engine's next dispatch),
        drop metadata, emit 'removed' events."""
        pending = self._nlib.dyn_pool_evicted_pending(self._np)
        if not pending:
            return
        pages = (ctypes.c_uint32 * pending)()
        hashes = (ctypes.c_uint64 * pending)()
        got = self._nlib.dyn_pool_drain_evicted(self._np, pages, hashes, pending)
        for i in range(got):
            page = pages[i]
            self._pre_evict(page)
            seq_hash, _, _ = self._page_meta.pop(page)
            self.stats.evicted_blocks += 1
            self._emit(KvEvent(kind="removed", block_hashes=(seq_hash,)))

    def _emit(self, event: KvEvent) -> None:
        if self._on_event is not None:
            self._on_event(event)

    def flush_offloads(self) -> int:
        """Tiered subclass hook: complete in-flight async offloads. The
        base pool has none."""
        return 0

    def clear_cache(self) -> int:
        """Drop all reclaimable cached pages (frontend /clear_kv_blocks)."""
        if self._np is not None:
            n = self._nlib.dyn_pool_clear_cache(self._np)
            self._drain_evicted()
            return n
        n = 0
        while self._reclaimable:
            page, _ = self._reclaimable.popitem(last=False)
            self._evict(page)
            self._free.append(page)
            n += 1
        return n

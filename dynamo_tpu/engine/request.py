"""Engine-facing request/response types.

The engine speaks tokens-in/tokens-out (the preprocessor upstream owns
templates+tokenization; the backend op downstream owns detokenization) —
same split as the reference's PreprocessedRequest contract
(/root/reference lib/llm/src/preprocessor.rs:156, backend.rs:278).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0  # 0 => greedy
    top_p: float = 1.0
    top_k: int = 0  # 0 => disabled
    max_tokens: int = 256
    stop_token_ids: tuple[int, ...] = ()
    ignore_eos: bool = False
    seed: Optional[int] = None
    #: -1 = off; 0 = chosen-token logprob only; N>0 = chosen + top-N
    #: alternatives per emitted token (OpenAI logprobs/top_logprobs)
    logprobs: int = -1
    #: OpenAI penalties over the output-token history (0 = off)
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    #: multiplicative repetition penalty over GENERATED tokens only —
    #: prompt tokens are deliberately not penalized, unlike HF's
    #: RepetitionPenaltyLogitsProcessor (1 = off; reference exposes it
    #: via nvext)
    repetition_penalty: float = 1.0
    #: OpenAI logit_bias: additive per-token-id biases applied in the
    #: sampler (before temperature). Bounded by sampling.BIAS_SLOTS
    #: minus the min_tokens ban slots.
    logit_bias: tuple[tuple[int, float], ...] = ()
    #: suppress eos/stop-token finishes until this many output tokens
    #: (reference: protocols/common.rs min_tokens) — implemented as
    #: sampler-level bans, so the banned ids are never emitted
    min_tokens: int = 0


class FinishReason(str, enum.Enum):
    STOP = "stop"  # eos / stop token
    LENGTH = "length"  # max_tokens or context limit
    CANCELLED = "cancelled"
    ERROR = "error"


class RequestState(str, enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    PREEMPTED = "preempted"
    FINISHED = "finished"


@dataclass
class Request:
    """One in-flight generation inside the engine."""

    request_id: str
    prompt_tokens: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    arrival_time: float = 0.0
    #: absolute end-to-end deadline (epoch seconds; None = none). The
    #: scheduler drops expired requests BEFORE admission; the runner
    #: error-finishes expired streams mid-decode (docs/operations.md)
    deadline: Optional[float] = None
    #: multimodal (llava-style): projected image embeddings [n, H] replacing
    #: the placeholder prompt tokens at mm_positions (absolute indices)
    mm_embeds: Optional["object"] = None  # np.ndarray
    mm_positions: tuple[int, ...] = ()

    # -- engine-managed state ---------------------------------------------
    state: RequestState = RequestState.WAITING
    output_tokens: list[int] = field(default_factory=list)
    pages: list[int] = field(default_factory=list)
    #: tokens whose KV is already in pages (prefix-cache hits + prefilled)
    num_computed_tokens: int = 0
    #: prompt tokens served from the prefix cache at admission
    num_cached_prompt_tokens: int = 0
    #: tokens already emitted before a preemption folded them into the prompt
    #: (keeps the max_tokens budget correct across recompute)
    num_emitted: int = 0
    finish_reason: Optional[FinishReason] = None
    #: disaggregated serving: keep pages allocated after finish so a prefill
    #: worker can extract their KV for transfer (released via release_held)
    hold_pages: bool = False
    #: speculative decoding (engine-managed): incremental n-gram -> last
    #: start position index over the token sequence, plus a persistent
    #: copy of that sequence (all_tokens rebuilds a list per call) and the
    #: next unindexed n-gram start
    spec_index: Optional[dict] = None
    spec_ctx: Optional[list] = None
    spec_indexed_upto: int = 0
    #: draft-model speculation (EngineConfig.spec_draft_model): number of
    #: tokens whose DRAFT KV is committed (positions [0, spec_draft_pos)).
    #: The draft prefill rides the target prefill; each spec step's
    #: catch-up window re-feeds the tokens accepted since. Reset to 0 on
    #: preemption-by-recompute (pages are released; the re-admission
    #: prefill rebuilds both pools).
    spec_draft_pos: int = 0
    #: distributed-tracing enrichment (set by AsyncEngineRunner only
    #: while tracing is ON; None otherwise — the default token path is
    #: untouched): the request's trace id stamps phase-histogram
    #: exemplars, and the measured queue wait / prefill-induced stall
    #: ride the first/last StepOutput onto the engine.generate span so
    #: the assembled trace's timeline breakdown can attribute them
    trace_id: Optional[str] = None
    queue_wait_ms: Optional[float] = None
    stall_accum_ms: float = 0.0

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.output_tokens)

    @property
    def all_tokens(self) -> list[int]:
        return self.prompt_tokens + self.output_tokens

    @property
    def prefill_done(self) -> bool:
        return self.num_computed_tokens >= len(self.prompt_tokens)

    @property
    def is_finished(self) -> bool:
        return self.state == RequestState.FINISHED


@dataclass(frozen=True)
class StepOutput:
    """Per-request result of one engine step."""

    request_id: str
    new_token_ids: tuple[int, ...]
    finish_reason: Optional[FinishReason] = None
    #: set on the first output of a request (TTFT accounting)
    is_first: bool = False
    #: per-token logprob of each new token (when sampling.logprobs >= 0)
    logprobs: Optional[tuple[float, ...]] = None
    #: per-token top-N alternatives [(token_id, logprob), ...]
    top_logprobs: Optional[tuple[tuple[tuple[int, float], ...], ...]] = None
    #: prompt tokens served from the prefix cache (first output only —
    #: OpenAI usage.prompt_tokens_details.cached_tokens)
    cached_tokens: Optional[int] = None
    #: emitted by a mixed prefill+decode step (EngineConfig.mixed_steps) —
    #: surfaces as the `mixed` attribute on the engine.generate trace span
    mixed: bool = False
    #: emitted by a speculative verify step (spec_ngram or
    #: spec_draft_model) — surfaces as the `spec` attribute on the
    #: engine.generate trace span
    spec: bool = False
    #: emitted by an on-device K-step decode window
    #: (EngineConfig.decode_kstep > 1) — surfaces as the `kstep`
    #: attribute on the engine.generate trace span
    kstep: bool = False
    #: tracing enrichment (first output of a TRACED request only; None
    #: otherwise — the wire shape is unchanged when tracing is off):
    #: admission-to-schedule wait, for the trace timeline breakdown
    queue_wait_ms: Optional[float] = None
    #: tracing enrichment (final output of a traced request): total
    #: prefill-induced decode stall this request experienced
    stall_ms: Optional[float] = None

"""Async bridge over JaxEngine + simple test engines.

The engine's step loop is synchronous (device dispatch); AsyncEngineRunner
runs it on a dedicated thread and exposes the universal AsyncEngine
interface: `generate(context, preprocessed) -> async iterator of
{token_ids, finish_reason}` (the reference's AsyncEngine::generate —
engine.rs:207). Echo engines mirror engines.rs EchoFull/EchoCore for
tests/CLI.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, AsyncIterator, Optional, Protocol

from dynamo_tpu import telemetry
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.engine.request import SamplingParams, StepOutput
from dynamo_tpu.engine.scheduler import QueueFullError
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime.context import (
    CANCELLED,
    Context,
    queue_get_or_cancelled,
)
from dynamo_tpu.runtime.overload import (
    OverloadedError,
    estimate_retry_after_s,
)
from dynamo_tpu.testing import faults

logger = logging.getLogger(__name__)


class AsyncEngine(Protocol):
    async def generate(
        self, context: Context, request: PreprocessedRequest
    ) -> AsyncIterator[dict]: ...


def output_to_dict(out: StepOutput) -> dict:
    """The one wire shape for engine stream items."""
    d = {
        "token_ids": list(out.new_token_ids),
        "finish_reason": out.finish_reason.value if out.finish_reason else None,
    }
    if out.logprobs is not None:
        d["logprobs"] = list(out.logprobs)
    if out.top_logprobs is not None:
        d["top_logprobs"] = [
            [[tid, lp] for tid, lp in alts] for alts in out.top_logprobs
        ]
    if out.cached_tokens is not None:
        d["cached_tokens"] = out.cached_tokens
    if out.mixed:
        d["mixed"] = True
    if out.spec:
        d["spec"] = True
    if out.kstep:
        d["kstep"] = True
    # tracing enrichment (traced requests only — these keys are absent
    # from the wire when tracing is off, keeping it bit-identical):
    # measured queue wait / prefill-induced stall for the engine span
    if out.queue_wait_ms is not None:
        d["queue_wait_ms"] = out.queue_wait_ms
    if out.stall_ms is not None:
        d["stall_ms"] = out.stall_ms
    return d


def _sampling_from(req: PreprocessedRequest) -> SamplingParams:
    return SamplingParams(
        temperature=req.temperature,
        top_p=req.top_p,
        top_k=req.top_k,
        max_tokens=req.max_tokens,
        stop_token_ids=tuple(req.stop_token_ids),
        ignore_eos=req.ignore_eos,
        seed=req.seed,
        logprobs=getattr(req, "logprobs", -1),
        frequency_penalty=getattr(req, "frequency_penalty", 0.0),
        presence_penalty=getattr(req, "presence_penalty", 0.0),
        repetition_penalty=getattr(req, "repetition_penalty", 1.0) or 1.0,
        logit_bias=tuple(
            (int(t), float(b))
            for t, b in (getattr(req, "logit_bias", None) or ())
        ),
        min_tokens=int(getattr(req, "min_tokens", 0) or 0),
    )


class AsyncEngineRunner:
    """Thread-backed continuous-batching loop around a JaxEngine.

    With the engine's overlapped decode pipeline (EngineConfig
    .overlap_decode), each `eng.step()` returns step N's outputs while
    step N+1 is already in flight on device — so this loop streams
    tokens to clients (and drains admissions/aborts for the next step)
    exactly in the window the device is computing. When the queue
    drains, any dangling speculative dispatch is discarded before the
    thread sleeps so its device buffers free promptly."""

    def __init__(self, engine: JaxEngine):
        self.engine = engine
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._pending: list[tuple[PreprocessedRequest, SamplingParams]] = []
        self._aborts: list[str] = []
        self._ops: list[tuple] = []
        #: request_id -> absolute epoch deadline; the engine thread
        #: error-finishes expired streams mid-decode (the scheduler
        #: already drops expired WAITING requests pre-admission)
        self._deadlines: dict[str, float] = {}
        #: request_id -> trace id, populated ONLY while tracing is on:
        #: _add_pending stamps it onto the engine-side Request so phase
        #: exemplars and the breakdown enrichment know their trace
        self._trace_ids: dict[str, str] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        #: stall watchdog (telemetry/watchdog.py, config.stall_watchdog):
        #: built in start() — it needs the running event loop, which is
        #: deliberately NOT the engine thread it watches
        self.watchdog = None

    def _start_watchdog(self) -> None:
        cfg = getattr(self.engine, "config", None)
        if cfg is None or not getattr(cfg, "stall_watchdog", False):
            return
        import weakref

        from dynamo_tpu.telemetry.watchdog import StallWatchdog

        eng = self.engine

        def itl_ms():
            """Live ITL-p95 estimate from the SLO plane (None cold)."""
            slo = getattr(eng, "slo", None)
            if slo is None:
                return None
            sk = slo.sketches.get("itl_ms")
            return sk.quantile(0.95) if sk is not None and sk.count else None

        self.watchdog = StallWatchdog(
            itl_estimate_ms=itl_ms,
            flight=getattr(eng, "flight", None),
            stall_factor=cfg.stall_factor,
            stall_min_s=cfg.stall_min_s,
            queue_wait_budget_s=cfg.stall_queue_wait_s,
            hard_deadline_s=cfg.stall_hard_deadline_s,
            on_wedged=self._wedge_request,
            # K-step windows emit once per K tokens: the live window
            # size floors the stall threshold so a healthy K-window is
            # not misread as a stalled stream (decode_kstep bugfix)
            window_steps=lambda: getattr(eng, "_kstep_live", 1),
        )
        self.watchdog.start()
        try:
            eng._watchdog_ref = weakref.ref(self.watchdog)
        except AttributeError:
            pass  # non-JaxEngine test doubles need not carry the slot

    def _wedge_request(self, request_id: str, info: dict) -> None:
        """Hard-deadline action (config.stall_hard_deadline_s): error-
        finish the wedged stream through its output queue — the client
        unblocks even while the engine thread is stuck — and enqueue an
        abort for whenever the engine recovers."""
        self._post(
            request_id,
            {
                "error": (
                    f"stall watchdog: {info.get('cause')} for "
                    f"{info.get('stalled_s')}s; stream error-finished by "
                    "hard deadline"
                )
            },
        )
        self._post(request_id, None)
        with self._lock:
            self._aborts.append(request_id)
        self._wake.set()

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._start_watchdog()
        self._thread = threading.Thread(target=self._run, daemon=True, name="engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- engine thread -----------------------------------------------------

    def _drain_inbox(self):
        with self._lock:
            pending, self._pending = self._pending, []
            aborts, self._aborts = self._aborts, []
            ops, self._ops = self._ops, []
        return pending, aborts, ops

    def _run_ops(self, ops) -> None:
        for fn, fut in ops:
            try:
                res = fn(self.engine)
                self._loop.call_soon_threadsafe(
                    lambda f=fut, r=res: f.done() or f.set_result(r)
                )
            except Exception as e:
                self._loop.call_soon_threadsafe(
                    lambda f=fut, err=e: f.done() or f.set_exception(err)
                )

    def _emit(self, outputs) -> None:
        wd = self.watchdog
        for out in outputs:
            if wd is not None and out.new_token_ids:
                # engine-side progress mark: a wedged engine thread stops
                # exactly these, which is what the watchdog detects
                wd.progress(out.request_id)
            self._post(out.request_id, output_to_dict(out))
            if out.finish_reason is not None:
                if wd is not None:
                    wd.done(out.request_id)
                self._post(out.request_id, None)

    def _add_pending(self, req, sampling) -> None:
        """Admit one queued request on the engine thread; a full waiting
        queue answers 'overloaded' with a Retry-After hint priced from
        the live SLO sketches (docs/operations.md)."""
        eng = self.engine
        kwargs = {}
        deadline = getattr(req, "deadline", None)
        if deadline:
            # only deadline-carrying requests pass the kwarg — engines
            # without deadline support (test doubles, older externals)
            # keep their add_request signature working
            kwargs["deadline"] = deadline
        try:
            req_obj = eng.add_request(
                req.request_id, req.token_ids, sampling,
                mm_embeds=req.mm_embeds,
                mm_positions=req.mm_positions,
                **kwargs,
            )
            tid = self._trace_ids.get(req.request_id)
            if tid is not None and req_obj is not None:
                try:
                    # traced request: the engine-side Request carries its
                    # trace id (exemplars + breakdown enrichment). Set by
                    # attribute so engines with narrower add_request
                    # signatures (test doubles, externals) are untouched.
                    req_obj.trace_id = tid
                except (AttributeError, TypeError):
                    pass
        except QueueFullError as e:
            eng.metrics.overload_rejects += 1
            sched = getattr(eng, "scheduler", None)
            self._post(
                req.request_id,
                {
                    "error": str(e),
                    "overloaded": True,
                    "retry_after_s": estimate_retry_after_s(
                        getattr(eng, "slo", None),
                        queue_depth=(
                            sched.num_waiting() if sched is not None else 0
                        ),
                    ),
                },
            )
            self._post(req.request_id, None)
        except Exception as e:
            self._post(req.request_id, {"error": str(e)})
            self._post(req.request_id, None)

    def _expire_deadlines(self) -> None:
        """Mid-decode deadline enforcement (engine thread): abort expired
        streams and error-finish them — pages free via the abort path,
        and the cost already sunk is the only cost paid."""
        if not self._deadlines:
            return
        now = time.time()
        with self._lock:
            expired = [r for r, d in self._deadlines.items() if now > d]
            for rid in expired:
                del self._deadlines[rid]
        eng = self.engine
        for rid in expired:
            if eng.abort_request(rid):
                try:
                    eng._runner_deadline_expired += 1
                except AttributeError:
                    pass  # non-JaxEngine test doubles
            wd = self.watchdog
            if wd is not None:
                wd.done(rid)
            self._post(rid, {"token_ids": [], "finish_reason": "error"})
            self._post(rid, None)

    def _run(self) -> None:
        eng = self.engine
        while not self._stop:
            pending, aborts, ops = self._drain_inbox()
            self._run_ops(ops)
            for req, sampling in pending:
                self._add_pending(req, sampling)
            for rid in aborts:
                eng.abort_request(rid)
            self._expire_deadlines()
            if not eng.has_work:
                drain = getattr(eng, "drain_overlap", None)
                if drain is not None:
                    drain()
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            wd = self.watchdog
            if wd is not None:
                wd.step_begin()  # a dispatch that never returns is the
                # cause="engine_stuck" signal
            try:
                # fault-injection hook (dynamo_tpu/testing/faults.py): an
                # injected delay stalls the loop (watchdog fodder); an
                # injected error is swallowed like a real step failure
                faults.fire_sync("engine.step")
                outputs = eng.step()
            except Exception:
                logger.exception("engine step failed")
                continue
            finally:
                if wd is not None:
                    wd.step_end()
            self._emit(outputs)

    def _post(self, request_id: str, item) -> None:
        q = self._queues.get(request_id)
        if q is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(q.put_nowait, item)

    # -- async side --------------------------------------------------------

    async def submit(self, fn):
        """Run fn(engine) on the engine thread (the only thread allowed to
        touch the allocator/scheduler/KV); awaitable result. Used by the
        disaggregation path for page reservation, KV injection, and
        prefilled-request admission."""
        fut = asyncio.get_running_loop().create_future()
        with self._lock:
            self._ops.append((fn, fut))
        self._wake.set()
        return await fut

    def watch_request(self, request_id: str) -> asyncio.Queue:
        """Open the output queue for a request admitted out of band (e.g.
        via add_prefilled on the engine thread)."""
        q: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = q
        return q

    def unwatch_request(self, request_id: str) -> None:
        self._queues.pop(request_id, None)
        with self._lock:
            self._deadlines.pop(request_id, None)

    def track_deadline(self, request_id: str, deadline) -> None:
        """Deadline enforcement for requests admitted out of band (the
        disaggregated decode path): drain() untracks on stream end."""
        if deadline:
            with self._lock:
                self._deadlines[request_id] = deadline
            self._wake.set()

    async def generate(
        self, context: Context, request: PreprocessedRequest
    ) -> AsyncIterator[dict]:
        # The engine thread itself is contextvar-free; this async-side
        # span brackets the whole engine residency (submit -> finish) and
        # marks the first token. Phase costs (queue wait, per-dispatch
        # prefill/decode) land in the telemetry phase histograms from the
        # scheduler/step loop.
        with telemetry.span(
            "engine.generate", service="engine",
            attrs={
                "request_id": request.request_id,
                "input_tokens": len(request.token_ids),
            },
        ) as sp:
            q = self.watch_request(request.request_id)
            deadline = getattr(request, "deadline", None)
            if sp.trace_id:
                # tracing on: let the engine thread stamp this request's
                # Request/StepOutputs with the trace (cleaned in drain)
                self._trace_ids[request.request_id] = sp.trace_id
            with self._lock:
                self._pending.append((request, _sampling_from(request)))
                if deadline:
                    self._deadlines[request.request_id] = deadline
            self._wake.set()
            generated = 0
            mixed_seen = False
            spec_seen = False
            kstep_seen = False
            async for item in self.drain(context, request.request_id, q):
                if generated == 0:
                    sp.add_event("first_token")
                if not mixed_seen and item.get("mixed"):
                    # at least one token rode a mixed prefill+decode step
                    mixed_seen = True
                    sp.set_attr("mixed", True)
                if not spec_seen and item.get("spec"):
                    # at least one token rode a speculative verify step
                    spec_seen = True
                    sp.set_attr("spec", True)
                if not kstep_seen and item.get("kstep"):
                    # at least one token rode an on-device K-step window
                    kstep_seen = True
                    sp.set_attr("kstep", True)
                qw = item.get("queue_wait_ms")
                if qw is not None:
                    # measured admission wait (timeline breakdown input)
                    sp.set_attr("queue_wait_ms", round(float(qw), 3))
                stall = item.get("stall_ms")
                if stall is not None:
                    sp.set_attr("decode_stall_ms", round(float(stall), 3))
                generated += len(item.get("token_ids", ()))
                yield item
            sp.set_attr("generated_tokens", generated)

    async def drain(
        self, context: Context, request_id: str, q: asyncio.Queue
    ) -> AsyncIterator[dict]:
        """Stream a watched request's output queue: the single place that
        knows the cancel/sentinel/error protocol (used by generate and the
        disaggregated decode path). Also the single place every streamed
        request enters/leaves the stall watchdog — with its current
        trace/span ids, so a stall diagnosis can name the wedged trace."""
        wd = self.watchdog
        if wd is not None:
            sp = telemetry.current_span()
            wd.track(
                request_id,
                {"trace_id": sp.trace_id, "span_id": sp.span_id}
                if sp is not None
                else None,
            )
        try:
            while True:
                if context.cancelled:
                    with self._lock:
                        self._aborts.append(request_id)
                    self._wake.set()
                    return
                # race the queue against cancellation: a client that
                # disconnects while its request still sits in the
                # WAITING queue (no items ever arrive) must abort it —
                # a bare q.get() would hold the slot forever
                item = await queue_get_or_cancelled(context, q)
                if item is CANCELLED:
                    continue  # loop re-checks context.cancelled -> abort
                if item is None:
                    return
                if "error" in item:
                    if item.get("overloaded"):
                        raise OverloadedError(
                            item["error"], item.get("retry_after_s")
                        )
                    raise RuntimeError(item["error"])
                yield item
        finally:
            if wd is not None:
                wd.done(request_id)
            with self._lock:
                self._deadlines.pop(request_id, None)
            self._queues.pop(request_id, None)
            self._trace_ids.pop(request_id, None)

    async def embed(self, prompts, normalize: bool = True):
        """Embedding vectors via the engine thread (shares the page pool
        and jit cache with the serving loop)."""
        return await self.submit(lambda eng: eng.embed(prompts, normalize))

    @property
    def metrics(self):
        return self.engine.metrics


class SpmdEngineRunner(AsyncEngineRunner):
    """Leader-side runner for one replica of a cross-host lockstep group
    (engine/spmd.py): admissions, aborts, and cache clears ride the
    driver's broadcast so every host's scheduler replica stays identical;
    the jitted steps execute SPMD over the shared mesh.

    Contract differences from the base runner:
    - submit(fn) ops MUST be read-only (metrics snapshots, hit queries) —
      a mutating op would desync the replicas. The one mutating op the
      worker needs, prefix-cache clear, has clear_kv().
    - multimodal requests are refused (embeddings cannot ride the JSON
      event broadcast yet).
    """

    def __init__(self, engine, driver):
        super().__init__(engine)
        self.driver = driver
        self._clears: list[asyncio.Future] = []

    async def clear_kv(self) -> int:
        """Replicated prefix-cache clear; resolves to freed page count."""
        fut = asyncio.get_running_loop().create_future()
        with self._lock:
            self._clears.append(fut)
        self._wake.set()
        return await fut

    async def embed(self, prompts, normalize: bool = True):
        # engine.embed dispatches leader-only jitted SPMD programs and
        # allocates scratch pages — the followers would never join the
        # collectives (cross-host hang) and the allocators would desync.
        raise RuntimeError(
            "embeddings are not supported on a cross-host SPMD group yet"
        )

    def _run(self) -> None:
        drv = self.driver
        eng = self.engine
        while not self._stop:
            pending, aborts, ops = self._drain_inbox()
            with self._lock:
                clears, self._clears = self._clears, []
            self._run_ops(ops)  # read-only by contract
            submitted: list[str] = []
            for req, sampling in pending:
                if req.mm_embeds is not None:
                    self._post(
                        req.request_id,
                        {
                            "error": "multimodal requests are not "
                            "supported on a cross-host SPMD group yet"
                        },
                    )
                    self._post(req.request_id, None)
                    continue
                drv.submit(req.request_id, list(req.token_ids), sampling)
                submitted.append(req.request_id)
            for rid in aborts:
                drv.abort(rid)
            if clears:
                drv.clear_cache()
            if not (drv._pending or eng.has_work):
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                outputs = drv.step()
            except Exception as e:  # broadcast-layer failure (the driver
                # already swallows engine.step errors symmetrically)
                logger.exception("lockstep step failed")
                self._fail_clears(clears, e)
                # This round's admissions were popped from the driver's
                # pending queue before the broadcast died — they reached
                # neither the engine nor the followers. Fail them (only
                # the ones actually submitted; refused multimodal ones
                # already got their error); their clients would otherwise
                # wait forever.
                for rid in submitted:
                    self._post(rid, {"error": f"lockstep step failed: {e}"})
                    self._post(rid, None)
                drv.submit_errors.clear()
                continue
            for rid, err in drv.submit_errors:
                self._post(rid, {"error": err})
                self._post(rid, None)
            drv.submit_errors.clear()
            for fut in clears:
                self._loop.call_soon_threadsafe(
                    lambda f=fut, n=drv.last_cleared: f.done()
                    or f.set_result(n)
                )
            self._emit(outputs)
        # release the followers' serve() loops, then fail any flush
        # still waiting (it would otherwise await forever)
        try:
            drv.shutdown()
        except Exception:  # noqa: BLE001 — best-effort during teardown
            logger.warning("lockstep shutdown broadcast failed", exc_info=True)
        with self._lock:
            leftovers, self._clears = self._clears, []
        self._fail_clears(
            leftovers, RuntimeError("engine runner stopped")
        )

    def _fail_clears(self, clears, exc: Exception) -> None:
        for fut in clears:
            self._loop.call_soon_threadsafe(
                lambda f=fut, e=exc: f.done() or f.set_exception(e)
            )


def fake_embedding(tokens, dim: int = 32):
    """Deterministic stand-in embedding for echo/mock engines: a hashed
    bag-of-tokens projection, L2-normalized. Lets the /v1/embeddings path
    be exercised end-to-end with no model."""
    import numpy as np
    import xxhash

    vec = np.zeros(dim, np.float32)
    for pos, tok in enumerate(tokens):
        h = xxhash.xxh64_intdigest(f"{tok}".encode(), seed=7)
        vec[h % dim] += 1.0 + 0.01 * (pos % 7)
    norm = float(np.linalg.norm(vec))
    return vec / norm if norm > 0 else vec


class EchoEngine:
    """Echoes the prompt tokens back, one per step (engines.rs EchoCore)."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay

    async def generate(self, context, request: PreprocessedRequest):
        n = min(len(request.token_ids), request.max_tokens)
        for i, tok in enumerate(request.token_ids[:n]):
            if context.cancelled:
                return
            if self.delay:
                await asyncio.sleep(self.delay)
            yield {
                "token_ids": [tok],
                "finish_reason": "stop" if i == n - 1 else None,
            }

    async def embed(self, prompts, normalize: bool = True):
        import numpy as np

        return np.stack([fake_embedding(p) for p in prompts])

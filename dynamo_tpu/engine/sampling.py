"""On-device batched sampling: temperature / top-k / top-p / greedy.

One jitted function handles a heterogeneous batch (per-row params) so decode
stays a single XLA program: greedy rows take argmax, sampling rows take a
Gumbel draw over the top-k/top-p-masked, temperature-scaled distribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def sample(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,  # [B] f32 (<=0 => greedy)
    top_p: jax.Array,  # [B] f32 in (0, 1]
    top_k: jax.Array,  # [B] i32 (0 => disabled)
    seeds: jax.Array,  # [B] u32 per-request seed
    counters: jax.Array,  # [B] i32 per-request draw counter (token position)
) -> jax.Array:  # [B] i32 sampled token ids
    """Per-row PRNG: each request draws from key(seed) folded with its own
    token counter, so a (prompt, seed) pair reproduces exactly regardless of
    what else shares the batch or how steps interleave."""
    b, v = logits.shape
    greedy = temperature <= 0.0
    safe_t = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))
    scaled = logits / safe_t[:, None]

    # Work in sorted space: one descending sort serves both k and p masks.
    sort_idx = jnp.argsort(-scaled, axis=-1)  # [B, V]
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    ranks = jnp.arange(v)[None, :]
    # top-p: keep tokens whose preceding mass is < p (first always kept)
    keep_p = (cum - probs) < top_p[:, None]
    # top-k: keep the first k ranks (k == 0 disables)
    keep_k = jnp.where(top_k[:, None] > 0, ranks < top_k[:, None], True)
    masked = jnp.where(keep_p & keep_k, sorted_logits, _NEG_INF)

    def row_gumbel(seed, counter):
        key = jax.random.fold_in(jax.random.key(seed), counter)
        return jax.random.gumbel(key, (v,), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, counters)  # [B, V]
    sampled_rank = jnp.argmax(masked + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(sort_idx, sampled_rank[:, None], axis=-1)[:, 0]
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)

"""On-device batched sampling: temperature / top-k / top-p / greedy.

One jitted function handles a heterogeneous batch (per-row params) so decode
stays a single XLA program: greedy rows take argmax, sampling rows take a
Gumbel draw over the top-k/top-p-masked, temperature-scaled distribution.

TPU note: a full-vocab argsort is a bitonic network over 128k lanes and
costs tens of milliseconds — it would dominate the whole decode step. The
sampler instead takes the top `k_cap` candidates with lax.top_k (already
sorted) and computes their *true* probabilities under the full distribution
via one logsumexp over the vocab. Sampling is thus truncated to the k_cap
most likely tokens (requested top_k values above k_cap are clamped); top-p
mass is exact w.r.t. the full softmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

#: static candidate-set bound; per-request top_k is clamped to this
DEFAULT_K_CAP = 64


def build_output_counts(
    out_tokens: jax.Array,  # [B, O] i32 output-token history (padded)
    out_valid: jax.Array,  # [B, O] bool
    vocab: int,
) -> jax.Array:  # [B, V] f32 per-token output frequency
    """Scatter the output-token history into a per-vocab count table (the
    state the OpenAI frequency/presence penalties are defined over; output
    tokens only, matching the common engine interpretation)."""
    b = out_tokens.shape[0]
    counts = jnp.zeros((b, vocab), jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], out_tokens.shape)
    return counts.at[rows, out_tokens].add(out_valid.astype(jnp.float32))


def apply_penalties(
    logits: jax.Array,  # [B, V] f32
    counts: jax.Array,  # [B, V] f32 output-token frequency
    freq_pen: jax.Array,  # [B] f32
    pres_pen: jax.Array,  # [B] f32
    rep_pen: jax.Array | None = None,  # [B] f32 (1 = off)
) -> jax.Array:
    """OpenAI penalty rule: logit -= freq_pen * count + pres_pen * (count>0),
    applied to the raw logits before temperature scaling. `rep_pen` is a
    multiplicative repetition penalty (the reference exposes one via
    nvext — protocols/openai/nvext.rs repetition_penalty): seen tokens'
    logits divide by r when positive and multiply when negative, applied
    before the additive penalties. Like frequency/presence here, "seen"
    means GENERATED tokens only — prompt tokens are not penalized (HF's
    generate also walks the prompt; penalizing it would grow the history
    bucket to the full context length for every penalized step)."""
    if rep_pen is not None:
        seen = counts > 0
        r = rep_pen[:, None]
        adjusted = jnp.where(logits > 0, logits / r, logits * r)
        logits = jnp.where(seen, adjusted, logits)
    return (
        logits
        - freq_pen[:, None] * counts
        - pres_pen[:, None] * (counts > 0).astype(logits.dtype)
    )


#: static per-row sparse logit-bias slots (OpenAI logit_bias entries +
#: min_tokens eos/stop bans share them); requests needing more are
#: rejected at the API boundary
BIAS_SLOTS = 16


def apply_logit_bias(
    logits: jax.Array,  # [B, V] f32
    bias_ids: jax.Array,  # [B, K] i32 token ids (0-padded)
    bias_vals: jax.Array,  # [B, K] f32 additive biases (0 = no-op)
    bias_gated: jax.Array,  # [B, K] bool — active only before min_tokens
    counters: jax.Array,  # [B] i32 output-token counter
    min_toks: jax.Array,  # [B] i32 min_tokens per request
) -> jax.Array:
    """Sparse additive logit bias (OpenAI `logit_bias`), with slots that
    can be GATED on the output count — min_tokens is implemented as
    gated -inf entries on the eos/stop ids, lifted once `counters`
    reaches the request's minimum. Zero-valued padding slots scatter-add
    nothing, so bias-free rows are exact no-ops."""
    active = (~bias_gated) | (counters < min_toks)[:, None]
    vals = jnp.where(active, bias_vals, 0.0)
    rows = jnp.arange(logits.shape[0])[:, None]
    return logits.at[rows, bias_ids].add(vals)


#: static per-row stop-token slots for the on-device K-step decode
#: window (EngineConfig.decode_kstep): each row's eos ∪ stop_token_ids
#: set is packed into this many −1-padded slots; requests needing more
#: fall back to the host-side finish scan (mirrors BIAS_SLOTS)
STOP_SLOTS = 8


def stop_mask(
    ids: jax.Array,  # [B] i32 sampled token per row
    stop_slots: jax.Array,  # [B, S] i32 stop-token ids (−1-padded)
) -> jax.Array:  # [B] bool — this row's token is one of its stop tokens
    """On-device stop-condition check for the fused K-step decode window:
    a row whose sampled token matches any of its packed stop slots is
    frozen for the rest of the window (the stop token itself IS emitted
    first — `_finish_reason_for` appends it host-side too, so the device
    freeze decision and the host finish scan agree position-for-
    position). Padding slots are −1 and can never match a sampled id."""
    return jnp.any(
        (ids[:, None] == stop_slots) & (stop_slots >= 0), axis=1
    )


def sample(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,  # [B] f32 (<=0 => greedy)
    top_p: jax.Array,  # [B] f32 in (0, 1]
    top_k: jax.Array,  # [B] i32 (0 => disabled)
    seeds: jax.Array,  # [B] u32 per-request seed
    counters: jax.Array,  # [B] i32 per-request draw counter (token position)
    k_cap: int = DEFAULT_K_CAP,
) -> jax.Array:  # [B] i32 sampled token ids
    """Per-row PRNG: each request draws from key(seed) folded with its own
    token counter, so a (prompt, seed) pair reproduces exactly regardless of
    what else shares the batch or how steps interleave."""
    b, v = logits.shape
    k_cap = min(k_cap, v)
    greedy = temperature <= 0.0
    safe_t = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))
    scaled = logits / safe_t[:, None]

    # Top-k_cap candidates, descending — the only vocab-wide work besides
    # one reduction for the softmax denominator.
    cand_logits, cand_idx = jax.lax.top_k(scaled, k_cap)  # [B, K]
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(cand_logits - lse)  # true full-softmax mass of candidates
    cum = jnp.cumsum(probs, axis=-1)
    ranks = jnp.arange(k_cap)[None, :]
    # top-p: keep tokens whose preceding mass is < p (first always kept)
    keep_p = (cum - probs) < top_p[:, None]
    # top-k: keep the first k ranks (k == 0 disables => k_cap)
    eff_k = jnp.where(top_k > 0, jnp.minimum(top_k, k_cap), k_cap)
    keep = keep_p & (ranks < eff_k[:, None])
    masked = jnp.where(keep, cand_logits, _NEG_INF)

    def row_gumbel(seed, counter):
        key = jax.random.fold_in(jax.random.key(seed), counter)
        return jax.random.gumbel(key, (k_cap,), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, counters)  # [B, K]
    sampled_rank = jnp.argmax(masked + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(cand_idx, sampled_rank[:, None], axis=-1)[:, 0]
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)


def spec_accept_step(
    logits: jax.Array,  # [B, V] f32 raw (penalty/bias-adjusted) target logits
    draft: jax.Array,  # [B] i32 proposed token (ignored when has_draft=False)
    has_draft: bool,  # static: False for the bonus position (fresh draw)
    temperature: jax.Array,  # [B] f32 (<=0 => greedy row)
    top_p: jax.Array,  # [B] f32
    top_k: jax.Array,  # [B] i32
    seeds: jax.Array,  # [B] u32
    counters: jax.Array,  # [B] i32 draw counter for THIS position
    k_cap: int = DEFAULT_K_CAP,
) -> tuple[jax.Array, jax.Array]:  # (chosen [B] i32, accept [B] bool)
    """One position of speculative rejection sampling (Leviathan et al.
    2023 / Chen et al. 2023), specialized to a DETERMINISTIC draft: the
    proposal q is a point mass at the draft token, so accept it with
    probability p_eff(draft) and otherwise resample from the residual —
    p_eff with the draft zeroed, renormalized. The marginal of the
    emitted token is exactly p_eff for every position: p_eff(draft) from
    acceptance plus (1-p_eff(draft)) * p_eff(y)/(1-p_eff(draft))
    elsewhere.

    p_eff here is the PRECISE distribution `sample()` draws from —
    temperature-scaled logits truncated to the top-k_cap candidates,
    top-p/top-k masked, softmax over the surviving candidates — so
    spec-on sampling is distributionally identical to spec-off sampling
    (pinned by tests/test_spec_draft.py). Greedy rows (temperature<=0)
    take the argmax and accept iff it equals the draft — the bit-exact
    greedy path. The bonus position (has_draft=False) draws with the
    SAME fold_in(key(seed), counter) gumbel stream as `sample()`, so a
    bonus token is bit-identical to what the plain sampler would have
    drawn at that counter.
    """
    b, v = logits.shape
    k_cap = min(k_cap, v)
    greedy = temperature <= 0.0
    safe_t = jnp.where(greedy, 1.0, jnp.maximum(temperature, 1e-6))
    scaled = logits / safe_t[:, None]
    cand_logits, cand_idx = jax.lax.top_k(scaled, k_cap)  # [B, K]
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(cand_logits - lse)
    cum = jnp.cumsum(probs, axis=-1)
    ranks = jnp.arange(k_cap)[None, :]
    keep_p = (cum - probs) < top_p[:, None]
    eff_k = jnp.where(top_k > 0, jnp.minimum(top_k, k_cap), k_cap)
    keep = keep_p & (ranks < eff_k[:, None])
    masked = jnp.where(keep, cand_logits, _NEG_INF)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def row_gumbel(seed, counter):
        key = jax.random.fold_in(jax.random.key(seed), counter)
        return jax.random.gumbel(key, (k_cap,), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, counters)  # [B, K]

    if not has_draft:
        rank = jnp.argmax(masked + gumbel, axis=-1)
        samp_tok = jnp.take_along_axis(cand_idx, rank[:, None], axis=-1)[:, 0]
        chosen = jnp.where(greedy, greedy_tok, samp_tok).astype(jnp.int32)
        return chosen, jnp.ones((b,), bool)

    # p_eff(draft): the draft's true mass under the kept-candidate softmax
    kept_lse = jax.scipy.special.logsumexp(masked, axis=-1, keepdims=True)
    is_draft = cand_idx == draft[:, None]
    p_draft = jnp.sum(
        jnp.where(is_draft & keep, jnp.exp(masked - kept_lse), 0.0), axis=-1
    )

    def row_u(seed, counter):
        # accept-uniform: an extra fold keeps it independent of the
        # gumbel stream that shares (seed, counter)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), counter), 0x5BEC
        )
        return jax.random.uniform(key, ())

    u = jax.vmap(row_u)(seeds, counters)
    # residual resample: p_eff restricted to kept candidates minus the
    # draft (gumbel-argmax over masked logits == softmax-renormalized)
    masked_excl = jnp.where(is_draft, _NEG_INF, masked)
    has_alt = jnp.any(keep & ~is_draft, axis=-1)
    rank = jnp.argmax(masked_excl + gumbel, axis=-1)
    resampled = jnp.take_along_axis(cand_idx, rank[:, None], axis=-1)[:, 0]
    accept_s = (u < p_draft) | ~has_alt
    chosen_s = jnp.where(accept_s, draft, resampled)
    chosen = jnp.where(greedy, greedy_tok, chosen_s).astype(jnp.int32)
    accept = jnp.where(greedy, greedy_tok == draft, accept_s)
    return chosen, accept


def sample_greedy(logits: jax.Array) -> jax.Array:
    """Argmax-only fast path: when every request in the batch is greedy the
    engine compiles this instead of the sampling pipeline."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def token_logprobs(
    logits: jax.Array,  # [B, V] f32
    ids: jax.Array,  # [B] i32 chosen token per row
    k: int,  # top-k alternatives to report (0 => chosen only)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Log-probabilities under the UNSCALED distribution (OpenAI semantics:
    logprobs describe the model, not the sampling temperature).

    Returns (chosen_lp [B], top_ids [B, max(k,1)], top_lps [B, max(k,1)]);
    with k == 0 the top arrays are computed for 1 candidate and ignored by
    the caller (keeps one jaxpr shape per k)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B]
    chosen = jnp.take_along_axis(logits, ids[:, None].astype(jnp.int32), axis=-1)[
        :, 0
    ]
    kk = max(k, 1)
    top_vals, top_idx = jax.lax.top_k(logits, kk)  # [B, kk]
    return chosen - lse, top_idx.astype(jnp.int32), top_vals - lse[:, None]

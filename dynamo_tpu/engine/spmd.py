"""Multi-controller lockstep serving over a cross-host mesh.

The TPU-native answer to the reference's multi-node serving
(MultiNodeConfig, engines.rs:43-50 + leader_worker_barrier.rs:26-121):
instead of a head node RPC-ing shards of work to workers, every host runs
an IDENTICAL engine replica over one global `jax.sharding.Mesh`, and only
the tiny admission stream is coordinated:

  1. the leader (process 0) queues submit/abort events from its frontend;
  2. each round it broadcasts the event log to all hosts (two device
     collectives: a length then a payload — `broadcast_one_to_all` rides
     the same ICI/DCN fabric as the model's collectives, no side channel);
  3. every host applies the events to its own deterministic scheduler
     replica and calls `engine.step()`. Identical scheduler state means
     identical batch arrays, so all hosts enter the SAME jit dispatch in
     lockstep — XLA's compiled collectives do the cross-host math;
  4. sampled ids come back fully replicated (engine._get_step_fn's
     `rep`), so every replica advances identically. No output shipping.

Determinism contract (what makes replicated scheduling sound):
- Scheduler decisions depend only on config + the event stream (FIFO
  admission, page accounting; `arrival_time` is metadata).
- Sampling seeds derive from request ids (engine._request_seed), draw
  counters from per-request emit counts.
- Host tiering is refused under a multi-process mesh (engine.__init__);
  spec-decode drafts derive from token history only.

Bring-up rendezvous (coordinator address, mesh shape agreement) is the
fabric-store barrier, runtime/barrier.py.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Optional

import numpy as np

from dynamo_tpu.engine.engine import JaxEngine, StepOutput
from dynamo_tpu.engine.request import SamplingParams

logger = logging.getLogger("dynamo_tpu.spmd")

__all__ = ["SpmdDriver"]


def _bucket_len(n: int) -> int:
    """Next power of two (min 1 KiB): broadcast_one_to_all compiles one
    program PER ARRAY SHAPE, and event-log byte lengths are effectively
    unique per round — unpadded payloads leaked a compiled executable
    per distinct length on EVERY host (~300 MB / 15 min in the SPMD
    soak). Bucketing keeps the program family logarithmic."""
    b = 1024
    while b < n:
        b *= 2
    return b


def _broadcast_bytes(payload: Optional[bytes], is_leader: bool) -> bytes:
    """Leader ships `payload` to every process; followers pass None.
    Two collectives: a fixed-shape length, then the bucket-padded
    payload (sliced back to the exact length on receipt).

    The payload rides as ONE BYTE PER int32 ELEMENT, not uint8: some
    jaxlib CPU/gloo builds corrupt uint8 broadcasts by widening the
    buffer to int32 in the collective and handing back the widened
    bytes reinterpreted as uint8 (every payload byte followed by three
    NULs, tail truncated) — on both the source and the receivers. The
    4x wire size is irrelevant for KB-scale event logs; int32 is the
    one element type every gloo reduction path handles."""
    from jax.experimental import multihost_utils

    if is_leader:
        n = np.asarray(len(payload), np.int32)
    else:
        n = np.asarray(0, np.int32)
    n = int(multihost_utils.broadcast_one_to_all(n, is_source=is_leader))
    if n == 0:
        return b""
    b = _bucket_len(n)
    data = np.zeros(b, np.int32)
    if is_leader:
        data[:n] = np.frombuffer(payload, np.uint8)
    out = multihost_utils.broadcast_one_to_all(data, is_source=is_leader)
    return np.asarray(out)[:n].astype(np.uint8).tobytes()


class SpmdDriver:
    """Drives one JaxEngine replica in lockstep with its peers.

    Leader usage (process 0 — owns the frontend):
        drv = SpmdDriver(engine)
        drv.submit(rid, tokens, SamplingParams(...))
        outs = drv.step()          # broadcast + step, every host
        ...
        drv.shutdown()             # releases the followers' loops

    Follower usage (every other process):
        SpmdDriver(engine).serve() # blocks until the leader's shutdown
    """

    def __init__(self, engine: JaxEngine, is_leader: Optional[bool] = None):
        import jax

        if not engine._multiproc:
            raise ValueError(
                "SpmdDriver needs an engine on a multi-process mesh; "
                "single-process engines are driven directly"
            )
        self.engine = engine
        self.is_leader = (
            jax.process_index() == 0 if is_leader is None else is_leader
        )
        self._pending: list[dict] = []
        self._stopped = False
        #: liveness guard: consecutive failed rounds (a dead collective
        #: plane burns a full transport timeout PER ROUND — looping on
        #: it forever would wedge run_to_completion)
        self._failed_rounds = 0
        #: (request_id, error message) for submits that failed to admit
        #: this round — drained by the serving layer to answer clients.
        #: Every replica records the same failures; only the leader reads.
        self.submit_errors: list[tuple[str, str]] = []
        #: result of the last clear_cache op (leader reads after step)
        self.last_cleared: Optional[int] = None

    # -- leader-side admission --------------------------------------------

    def submit(
        self,
        request_id: str,
        prompt_tokens: list[int],
        sampling: SamplingParams,
    ) -> None:
        assert self.is_leader, "only the leader admits requests"
        self._pending.append(
            {
                "op": "submit",
                "rid": request_id,
                "tokens": [int(t) for t in prompt_tokens],
                "sampling": dataclasses.asdict(sampling),
            }
        )

    def abort(self, request_id: str) -> None:
        assert self.is_leader, "only the leader aborts requests"
        self._pending.append({"op": "abort", "rid": request_id})

    def clear_cache(self) -> None:
        """Queue a prefix-cache clear; replicated so every host's
        allocator stays identical. Result lands in last_cleared."""
        assert self.is_leader
        self._pending.append({"op": "clear_cache"})

    # -- lockstep rounds ---------------------------------------------------

    def _apply(self, events: list[dict]) -> None:
        for ev in events:
            op = ev["op"]
            if op == "submit":
                s = ev["sampling"]
                s["stop_token_ids"] = tuple(s.get("stop_token_ids", ()))
                try:
                    self.engine.add_request(
                        ev["rid"], ev["tokens"], SamplingParams(**s)
                    )
                except Exception as e:  # noqa: BLE001 — deterministic:
                    # every replica rejects the same bad request the same
                    # way; only the leader reports it to a client (a
                    # follower recording too would just leak memory)
                    if self.is_leader:
                        self.submit_errors.append((ev["rid"], str(e)))
            elif op == "abort":
                self.engine.abort_request(ev["rid"])
            elif op == "clear_cache":
                self.last_cleared = self.engine.allocator.clear_cache()
            elif op == "stop":
                self._stopped = True
            else:  # pragma: no cover — version-skew guard
                raise RuntimeError(f"unknown lockstep event {op!r}")

    def _round(self, events: list[dict]) -> list[StepOutput]:
        payload = json.dumps(events).encode() if self.is_leader else None
        raw = _broadcast_bytes(payload, self.is_leader)
        if not self.is_leader:
            try:
                events = json.loads(raw.decode()) if raw else []
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                # a mangled event log means the collective plane is
                # corrupting payloads — surface WHAT arrived, the next
                # broadcast would wedge anyway
                raise RuntimeError(
                    "lockstep event broadcast corrupt "
                    f"({len(raw)} bytes, head={raw[:32]!r}): {e}"
                ) from e
        self._apply(events)
        if self._stopped:
            return []
        try:
            outs = self.engine.step()
            self._failed_rounds = 0
            return outs
        except Exception as e:  # noqa: BLE001 — MUST be symmetric: a
            # deterministic step failure raises on every replica; if a
            # follower died on it while the leader caught-and-continued,
            # the leader's next broadcast would block forever on the
            # missing participant. Both sides log and stay in lockstep.
            logger.exception("lockstep engine step failed")
            # ... EXCEPT when the collective plane itself is dead: every
            # replica observes the same transport failure (symmetric by
            # construction), retrying burns a full transport timeout per
            # round, and no future round can succeed — raise instead of
            # wedging run_to_completion. Same for any failure streak long
            # enough that "deterministic one-off" is no longer credible.
            self._failed_rounds += 1
            msg = str(e).lower()
            # transport-specific markers only — a generic XLA status
            # token (FAILED_PRECONDITION alone) must not be mistaken
            # for a plane outage on its first occurrence
            dead_plane = any(
                s in msg
                for s in ("gloo", "deadline_exceeded", "getkeyvalue")
            )
            if dead_plane or self._failed_rounds >= 8:
                raise RuntimeError(
                    "lockstep collective plane failed "
                    f"({self._failed_rounds} consecutive failed rounds): "
                    f"{e}"
                ) from e
            return []

    def step(self) -> list[StepOutput]:
        """One lockstep round: broadcast queued events, step every
        replica. Leader-only (followers sit in serve())."""
        events, self._pending = self._pending, []
        return self._round(events)

    def run_to_completion(self) -> dict[str, list[int]]:
        """Leader: drain all admitted work across the fleet."""
        done: dict[str, list[int]] = {}
        while self._pending or self.engine.has_work:
            for out in self.step():
                done.setdefault(out.request_id, []).extend(
                    out.new_token_ids
                )
        return done

    def shutdown(self) -> None:
        """Leader: release every follower's serve() loop."""
        if self.is_leader and not self._stopped:
            self._round([{"op": "stop"}])

    def serve(self) -> None:
        """Follower loop: block on the leader's broadcasts, mirror every
        step, exit on the stop event."""
        assert not self.is_leader
        while not self._stopped:
            self._round([])

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import KvEvent, PageAllocator, PrefixCacheStats
from dynamo_tpu.engine.request import (
    FinishReason,
    Request,
    SamplingParams,
    StepOutput,
)
from dynamo_tpu.engine.scheduler import Scheduler, ScheduledBatch

__all__ = [
    "EngineConfig",
    "KvEvent",
    "PageAllocator",
    "PrefixCacheStats",
    "FinishReason",
    "Request",
    "SamplingParams",
    "StepOutput",
    "Scheduler",
    "ScheduledBatch",
]

"""Kubernetes API clients for the operator.

Two backends behind one duck-typed interface (get/list/create/replace/
delete/patch_status):

- `InMemoryKube` — a faithful in-memory object store for tests (the
  reference operator uses envtest, deploy/cloud/operator suite_test.go;
  same idea without a control-plane binary).
- `InClusterKube` — speaks the REST API over HTTPS using the pod's
  service-account credentials (/var/run/secrets/kubernetes.io/...).
  stdlib-only (urllib): the environment bakes no kubernetes client.

Objects are plain dicts in k8s wire shape. List filtering supports the
label selectors the reconciler uses (equality only)."""

from __future__ import annotations

import json
import logging
import os
import ssl
import urllib.error
import urllib.request
from typing import Any, Optional

logger = logging.getLogger(__name__)

#: group/version/plural for each kind the operator touches
_API = {
    "Deployment": ("apis/apps/v1", "deployments"),
    "Service": ("api/v1", "services"),
    "DynamoGraphDeployment": ("apis/dynamo.tpu/v1alpha1", "dynamographdeployments"),
    "DynamoComponentDeployment": (
        "apis/dynamo.tpu/v1alpha1",
        "dynamocomponentdeployments",
    ),
}


def _match_labels(obj: dict, selector: Optional[dict]) -> bool:
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryKube:
    """Dict-backed kube API server double."""

    def __init__(self):
        #: (kind, namespace, name) -> object
        self._objs: dict[tuple[str, str, str], dict] = {}
        self.actions: list[tuple[str, str, str]] = []  # (verb, kind, name)

    def get(self, kind: str, namespace: str, name: str) -> Optional[dict]:
        obj = self._objs.get((kind, namespace, name))
        # Deep-copy like a real API server: callers mutate what they GET
        # and write back via replace; aliasing the stored object would make
        # read-modify-write races invisible to tests.
        return json.loads(json.dumps(obj)) if obj is not None else None

    def list(
        self, kind: str, namespace: str, selector: Optional[dict] = None
    ) -> list[dict]:
        return [
            json.loads(json.dumps(o))
            for (k, ns, _), o in sorted(self._objs.items())
            if k == kind and ns == namespace and _match_labels(o, selector)
        ]

    def create(self, kind: str, namespace: str, obj: dict) -> dict:
        name = obj["metadata"]["name"]
        key = (kind, namespace, name)
        if key in self._objs:
            raise RuntimeError(f"{kind} {namespace}/{name} already exists")
        obj.setdefault("metadata", {}).setdefault("namespace", namespace)
        self._objs[key] = json.loads(json.dumps(obj))
        self.actions.append(("create", kind, name))
        return self._objs[key]

    def replace(self, kind: str, namespace: str, name: str, obj: dict) -> dict:
        key = (kind, namespace, name)
        if key not in self._objs:
            raise RuntimeError(f"{kind} {namespace}/{name} not found")
        self._objs[key] = json.loads(json.dumps(obj))
        self.actions.append(("replace", kind, name))
        return self._objs[key]

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        existed = self._objs.pop((kind, namespace, name), None) is not None
        if existed:
            self.actions.append(("delete", kind, name))
        return existed

    def patch_status(self, kind: str, namespace: str, name: str, status: dict) -> None:
        obj = self._objs.get((kind, namespace, name))
        if obj is not None:
            obj["status"] = json.loads(json.dumps(status))
            self.actions.append(("status", kind, name))

    def patch_scale(
        self, kind: str, namespace: str, name: str, replicas: int
    ) -> Optional[dict]:
        """The /scale subresource: set spec.replicas WITHOUT a full-object
        write — no read-modify-write race with the reconciler, like HPA."""
        obj = self._objs.get((kind, namespace, name))
        if obj is None:
            return None
        obj.setdefault("spec", {})["replicas"] = int(replicas)
        self.actions.append(("scale", kind, name))
        return json.loads(json.dumps(obj))


class InClusterKube:
    """REST client using the pod's mounted service-account credentials."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, base_url: Optional[str] = None):
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = base_url or f"https://{host}:{port}"
        self._token_path = os.path.join(self.SA_DIR, "token")
        # Fail fast at boot on a missing token (misconfigured pod); later
        # refreshes tolerate transient stat errors.
        with open(self._token_path) as f:
            self._token = f.read().strip()
        self._token_mtime = os.stat(self._token_path).st_mtime
        ca = os.path.join(self.SA_DIR, "ca.crt")
        self._ctx = ssl.create_default_context(
            cafile=ca if os.path.exists(ca) else None
        )

    def _url(self, kind: str, namespace: str, name: str = "", sub: str = "") -> str:
        api, plural = _API[kind]
        url = f"{self.base_url}/{api}/namespaces/{namespace}/{plural}"
        if name:
            url += f"/{name}"
        if sub:
            url += f"/{sub}"
        return url

    def _refresh_token(self, force: bool = False) -> None:
        # Bound SA tokens are rotated by the kubelet (~1h); re-read when the
        # projected file changes rather than caching the boot-time value.
        try:
            mtime = os.stat(self._token_path).st_mtime
        except OSError:
            return
        if force or mtime != self._token_mtime:
            with open(self._token_path) as f:
                self._token = f.read().strip()
            self._token_mtime = mtime

    def _request(
        self, method: str, url: str, body: Optional[dict] = None,
        content_type: str = "application/json",
    ) -> Optional[dict]:
        data = json.dumps(body).encode() if body is not None else None
        self._refresh_token()
        for attempt in (0, 1):
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Authorization", f"Bearer {self._token}")
            req.add_header("Accept", "application/json")
            if data is not None:
                req.add_header("Content-Type", content_type)
            try:
                with urllib.request.urlopen(req, context=self._ctx, timeout=30) as r:
                    return json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None
                if e.code == 401 and attempt == 0:
                    self._refresh_token(force=True)
                    continue
                raise
        raise AssertionError("unreachable: loop returns or raises")

    def get(self, kind: str, namespace: str, name: str) -> Optional[dict]:
        return self._request("GET", self._url(kind, namespace, name))

    def list(
        self, kind: str, namespace: str, selector: Optional[dict] = None
    ) -> list[dict]:
        url = self._url(kind, namespace)
        if selector:
            sel = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
            url += f"?labelSelector={urllib.request.quote(sel)}"
        out = self._request("GET", url)
        return (out or {}).get("items", [])

    def create(self, kind: str, namespace: str, obj: dict) -> Optional[dict]:
        return self._request("POST", self._url(kind, namespace), obj)

    def replace(self, kind: str, namespace: str, name: str, obj: dict) -> Optional[dict]:
        return self._request("PUT", self._url(kind, namespace, name), obj)

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        return self._request("DELETE", self._url(kind, namespace, name)) is not None

    def patch_scale(
        self, kind: str, namespace: str, name: str, replicas: int
    ) -> Optional[dict]:
        """PATCH the /scale subresource (the CRD declares it —
        deploy/k8s/crds.yaml): the API server updates only
        spec.replicas, so planner scaling never conflicts with the
        reconciler's status writes or a concurrent spec edit."""
        return self._request(
            "PATCH",
            self._url(kind, namespace, name, sub="scale"),
            {"spec": {"replicas": int(replicas)}},
            content_type="application/merge-patch+json",
        )

    def patch_status(self, kind: str, namespace: str, name: str, status: dict) -> None:
        self._request(
            "PATCH",
            self._url(kind, namespace, name, sub="status"),
            {"status": status},
            content_type="application/merge-patch+json",
        )

"""Reconcile one DynamoGraphDeployment into Deployments + Services.

The CR's spec carries a frozen build manifest (`dynamo-tpu build` output —
sdk/build.py): image + the service list with replicas/config. Desired
child objects come from the same renderer the `deploy` command uses
(sdk/build.render_k8s), stamped with ownership labels; reconciliation is
a three-way sweep — create missing, replace drifted, delete orphaned —
exactly the reference operator's loop (deploy/cloud/operator
internal/controller/dynamographdeployment_controller.go) without the
controller-runtime machinery.

Drift detection compares the desired spec against the observed object's
spec (fields we own); unknown server-set fields are ignored, so the loop
is idempotent against defaulting."""

from __future__ import annotations

import logging
from typing import Any

from dynamo_tpu.sdk.build import render_k8s

logger = logging.getLogger(__name__)

MANAGED_BY = "dynamo-tpu-operator"
LABEL_MANAGED = "app.kubernetes.io/managed-by"
LABEL_OWNER = "dynamo.tpu/deployment"


def desired_objects(cr: dict) -> list[dict]:
    """Render the CR's child objects, labeled for ownership sweeps."""
    spec = cr.get("spec", {})
    # Hand-written CRs may omit fields the CRD marks optional; default them
    # before rendering (render_k8s indexes replicas/config directly).
    services = [
        {
            "name": s["name"],
            "class": s["class"],
            "replicas": s.get("replicas", 1),
            "endpoints": s.get("endpoints", []),
            "depends": s.get("depends", []),
            "config": s.get("config", {}) or {},
            "k8s": s.get("k8s", {}) or {},
        }
        for s in spec.get("services", [])
    ]
    manifest = {
        "image": spec.get("image", "dynamo-tpu:latest"),
        "services": services,
    }
    owner = cr["metadata"]["name"]
    namespace = cr["metadata"].get("namespace", "default")
    # fabricExternal: the platform (helm chart) owns a persistent fabric;
    # the graph's services rendezvous there instead of the operator
    # rendering a per-graph fabric. An external fabric with no address
    # would silently point pods at a nonexistent Service — fail loudly.
    external = spec.get("fabricExternal", False)
    if external and not spec.get("fabricHost"):
        raise ValueError(
            f"CR {owner}: fabricExternal requires fabricHost (the address "
            "of the platform-managed fabric Service)"
        )
    objs = render_k8s(
        manifest,
        fabric_host=spec.get("fabricHost", f"{owner}-fabric"),
        include_fabric=not external,
        fabric_port=int(spec.get("fabricPort", 4222)),
    )
    for obj in objs:
        meta = obj.setdefault("metadata", {})
        meta["namespace"] = namespace
        labels = meta.setdefault("labels", {})
        labels[LABEL_MANAGED] = MANAGED_BY
        labels[LABEL_OWNER] = owner
        # Propagate ownership labels onto pod templates so `kubectl get
        # pods -l dynamo.tpu/deployment=<name>` works.
        if obj["kind"] == "Deployment":
            tmeta = obj["spec"]["template"].setdefault("metadata", {})
            tlabels = tmeta.setdefault("labels", {})
            tlabels[LABEL_OWNER] = owner
    return objs


def _subset(want: Any, have: Any) -> bool:
    """True when `want` is structurally contained in `have`: every field we
    set must match, fields the API server defaulted (strategy,
    imagePullPolicy, ports[].protocol, ...) are ignored. Lists compare
    positionally with the same containment rule."""
    if isinstance(want, dict):
        if not isinstance(have, dict):
            return False
        return all(_subset(v, have.get(k)) for k, v in want.items())
    if isinstance(want, list):
        if not isinstance(have, list) or len(want) != len(have):
            return False
        return all(_subset(w, h) for w, h in zip(want, have))
    return want == have


def _spec_drifted(desired: dict, observed: dict) -> bool:
    """Compare only fields we own (our spec subset + our labels)."""
    if not _subset(desired.get("spec"), observed.get("spec")):
        return True
    want = desired["metadata"].get("labels", {})
    have = observed.get("metadata", {}).get("labels", {}) or {}
    return any(have.get(k) != v for k, v in want.items())


def reconcile(kube: Any, cr: dict) -> dict:
    """One reconcile pass. Returns a status patch for the CR."""
    namespace = cr["metadata"].get("namespace", "default")
    owner = cr["metadata"]["name"]
    desired = desired_objects(cr)
    created = replaced = deleted = 0

    want_names: dict[str, set[str]] = {"Deployment": set(), "Service": set()}
    for obj in desired:
        kind, name = obj["kind"], obj["metadata"]["name"]
        want_names[kind].add(name)
        observed = kube.get(kind, namespace, name)
        if observed is None:
            kube.create(kind, namespace, obj)
            created += 1
        elif _spec_drifted(obj, observed):
            merged = dict(observed)
            merged["spec"] = obj["spec"]
            labels = dict(observed.get("metadata", {}).get("labels", {}) or {})
            labels.update(obj["metadata"]["labels"])
            merged.setdefault("metadata", {})["labels"] = labels
            kube.replace(kind, namespace, name, merged)
            replaced += 1

    # Ownership sweep: anything we manage for this CR that is no longer
    # desired (service removed from the graph, port dropped) gets deleted.
    selector = {LABEL_MANAGED: MANAGED_BY, LABEL_OWNER: owner}
    for kind in ("Deployment", "Service"):
        for obj in kube.list(kind, namespace, selector):
            name = obj["metadata"]["name"]
            if name not in want_names[kind]:
                kube.delete(kind, namespace, name)
                deleted += 1

    if created or replaced or deleted:
        logger.info(
            "reconciled %s/%s: +%d ~%d -%d",
            namespace, owner, created, replaced, deleted,
        )
    return {
        "observedGeneration": cr["metadata"].get("generation", 0),
        "conditions": [
            {
                "type": "Ready",
                "status": "True",
                "reason": "Reconciled",
                "message": (
                    f"{len(want_names['Deployment'])} deployments, "
                    f"{len(want_names['Service'])} services"
                ),
            }
        ],
        "lastAction": {
            "created": created, "replaced": replaced, "deleted": deleted,
        },
    }


def garbage_collect(kube: Any, namespace: str, live_owners: set[str]) -> int:
    """Delete objects owned by CRs that no longer exist (explicit-label GC —
    the ownerReference cascade without relying on the API server)."""
    n = 0
    for kind in ("Deployment", "Service"):
        for obj in kube.list(kind, namespace, {LABEL_MANAGED: MANAGED_BY}):
            owner = (obj["metadata"].get("labels") or {}).get(LABEL_OWNER)
            if owner and owner not in live_owners:
                kube.delete(kind, namespace, obj["metadata"]["name"])
                n += 1
    return n

"""Reconcile DynamoGraphDeployments through DynamoComponentDeployments
into Deployments + Services.

Two controllers, like the reference's (deploy/cloud/operator
internal/controller/{dynamographdeployment,dynamocomponentdeployment}
_controller.go), without the controller-runtime machinery:

1. **Graph level** (`reconcile`): the CR's spec carries a frozen build
   manifest (`dynamo-tpu build` output — sdk/build.py). Each service
   becomes one DynamoComponentDeployment child CR; the shared fabric's
   Deployment+Service are reconciled directly (they belong to the graph,
   not any one component).
2. **Component level** (`reconcile_component`): one DCD renders into its
   Deployment (+Service when it exposes a port) via the same renderer
   the `deploy` command uses (sdk/build.render_k8s).

Both levels are a three-way sweep — create missing, replace drifted,
delete orphaned. Drift detection compares only fields we own; unknown
server-set fields are ignored, so the loops are idempotent against
defaulting.

**Replica ownership**: a DCD's `spec.replicas` is scalable via the
/scale subresource (planner KubeConnector, HPA — the reference's
dynamocomponentdeployment_types.go scale path). The graph CR's
per-service `replicas` is the *initial* value and keeps propagating
only when the graph author CHANGES it — the DCD's
`dynamo.tpu/graph-replicas` annotation records the last value the graph
stated, so a planner scale-up is not clobbered by the next no-op graph
reconcile, while an explicit graph edit still wins."""

from __future__ import annotations

import json
import logging
from typing import Any

from dynamo_tpu.sdk.build import render_k8s

logger = logging.getLogger(__name__)

MANAGED_BY = "dynamo-tpu-operator"
LABEL_MANAGED = "app.kubernetes.io/managed-by"
LABEL_OWNER = "dynamo.tpu/deployment"
LABEL_COMPONENT = "dynamo.tpu/component"
ANNO_GRAPH_REPLICAS = "dynamo.tpu/graph-replicas"


def component_name(owner: str, service: str) -> str:
    return f"{owner}-{service.lower()}"


def _norm_service(s: dict) -> dict:
    """Hand-written CRs may omit fields the CRD marks optional; default
    them before rendering (render_k8s indexes replicas/config directly)."""
    return {
        "name": s["name"],
        "class": s["class"],
        "replicas": s.get("replicas", 1),
        "endpoints": s.get("endpoints", []),
        "depends": s.get("depends", []),
        "config": s.get("config", {}) or {},
        "k8s": s.get("k8s", {}) or {},
    }


def _validate_fabric(spec: dict, owner: str) -> None:
    # fabricExternal: the platform (helm chart) owns a persistent fabric;
    # an external fabric with no address would silently point pods at a
    # nonexistent Service — fail loudly.
    if spec.get("fabricExternal", False) and not spec.get("fabricHost"):
        raise ValueError(
            f"CR {owner}: fabricExternal requires fabricHost (the address "
            "of the platform-managed fabric Service)"
        )


def desired_objects(cr: dict) -> list[dict]:
    """Render the CR's child objects, labeled for ownership sweeps."""
    spec = cr.get("spec", {})
    services = [_norm_service(s) for s in spec.get("services", [])]
    manifest = {
        "image": spec.get("image", "dynamo-tpu:latest"),
        "services": services,
    }
    owner = cr["metadata"]["name"]
    namespace = cr["metadata"].get("namespace", "default")
    external = spec.get("fabricExternal", False)
    _validate_fabric(spec, owner)
    objs = render_k8s(
        manifest,
        fabric_host=spec.get("fabricHost", f"{owner}-fabric"),
        include_fabric=not external,
        fabric_port=int(spec.get("fabricPort", 4222)),
    )
    for obj in objs:
        meta = obj.setdefault("metadata", {})
        meta["namespace"] = namespace
        labels = meta.setdefault("labels", {})
        labels[LABEL_MANAGED] = MANAGED_BY
        labels[LABEL_OWNER] = owner
        # Propagate ownership labels onto pod templates so `kubectl get
        # pods -l dynamo.tpu/deployment=<name>` works.
        if obj["kind"] == "Deployment":
            tmeta = obj["spec"]["template"].setdefault("metadata", {})
            tlabels = tmeta.setdefault("labels", {})
            tlabels[LABEL_OWNER] = owner
    return objs


def _subset(want: Any, have: Any) -> bool:
    """True when `want` is structurally contained in `have`: every field we
    set must match, fields the API server defaulted (strategy,
    imagePullPolicy, ports[].protocol, ...) are ignored. Lists compare
    positionally with the same containment rule."""
    if isinstance(want, dict):
        if not isinstance(have, dict):
            return False
        return all(_subset(v, have.get(k)) for k, v in want.items())
    if isinstance(want, list):
        if not isinstance(have, list) or len(want) != len(have):
            return False
        return all(_subset(w, h) for w, h in zip(want, have))
    return want == have


def _spec_drifted(desired: dict, observed: dict) -> bool:
    """Compare only fields we own (our spec subset + our labels)."""
    if not _subset(desired.get("spec"), observed.get("spec")):
        return True
    want = desired["metadata"].get("labels", {})
    have = observed.get("metadata", {}).get("labels", {}) or {}
    return any(have.get(k) != v for k, v in want.items())


def desired_components(cr: dict) -> list[dict]:
    """One DynamoComponentDeployment per graph service."""
    spec = cr.get("spec", {})
    owner = cr["metadata"]["name"]
    namespace = cr["metadata"].get("namespace", "default")
    _validate_fabric(spec, owner)
    out = []
    for s in map(_norm_service, spec.get("services", [])):
        replicas = s["replicas"]
        out.append(
            {
                "apiVersion": "dynamo.tpu/v1alpha1",
                "kind": "DynamoComponentDeployment",
                "metadata": {
                    "name": component_name(owner, s["name"]),
                    "namespace": namespace,
                    "labels": {
                        LABEL_MANAGED: MANAGED_BY,
                        LABEL_OWNER: owner,
                    },
                    "annotations": {ANNO_GRAPH_REPLICAS: str(replicas)},
                },
                "spec": {
                    "image": spec.get("image", "dynamo-tpu:latest"),
                    "fabricHost": spec.get("fabricHost", f"{owner}-fabric"),
                    "fabricPort": int(spec.get("fabricPort", 4222)),
                    "replicas": replicas,
                    "service": {
                        k: v for k, v in s.items() if k != "replicas"
                    },
                },
            }
        )
    return out


def _sweep(
    kube: Any, namespace: str, desired: list[dict], kinds: tuple,
    selector: dict, keep_fields=(),
) -> tuple[int, int, int]:
    """Three-way convergence: create missing, replace drifted, delete
    owned-but-undesired. `keep_fields` names observed top-level spec
    fields another plane owns (e.g. replicas via /scale) — they are
    carried into the desired spec before the drift compare/write."""
    created = replaced = deleted = 0
    want: dict[str, set[str]] = {k: set() for k in kinds}
    for obj in desired:
        kind, name = obj["kind"], obj["metadata"]["name"]
        want[kind].add(name)
        observed = kube.get(kind, namespace, name)
        if observed is None:
            kube.create(kind, namespace, obj)
            created += 1
            continue
        obj = json.loads(json.dumps(obj))
        anno_stale = False
        for field in keep_fields:
            if field in (observed.get("spec") or {}):
                anno = (
                    observed.get("metadata", {}).get("annotations", {}) or {}
                )
                stated = obj["metadata"].get("annotations", {}).get(
                    ANNO_GRAPH_REPLICAS
                )
                if (
                    field == "replicas"
                    and stated is not None
                    and anno.get(ANNO_GRAPH_REPLICAS) != stated
                ):
                    # the graph author changed it: propagate, and make
                    # sure the annotation WRITE happens even when the new
                    # value already matches (e.g. the author aligned the
                    # manifest with a planner scale) — a stale annotation
                    # would clobber every later scale
                    anno_stale = True
                    continue
                obj["spec"][field] = observed["spec"][field]
        if anno_stale or _spec_drifted(obj, observed):
            merged = dict(observed)
            merged["spec"] = obj["spec"]
            labels = dict(observed.get("metadata", {}).get("labels", {}) or {})
            labels.update(obj["metadata"]["labels"])
            merged.setdefault("metadata", {})["labels"] = labels
            annos = dict(
                observed.get("metadata", {}).get("annotations", {}) or {}
            )
            annos.update(obj["metadata"].get("annotations", {}))
            if annos:
                merged["metadata"]["annotations"] = annos
            kube.replace(kind, namespace, name, merged)
            replaced += 1
    for kind in kinds:
        for obj in kube.list(kind, namespace, selector):
            name = obj["metadata"]["name"]
            if name not in want[kind]:
                kube.delete(kind, namespace, name)
                deleted += 1
    return created, replaced, deleted


def reconcile(kube: Any, cr: dict, converge_components: bool = True) -> dict:
    """Graph-level pass: converge the component CRs + the shared fabric,
    then (by default) converge every desired component's children so one
    call fully converges a graph. The Controller passes
    converge_components=False — its own component pass immediately
    follows, and doing the work twice per tick doubles the API load.
    Returns a status patch for the CR."""
    namespace = cr["metadata"].get("namespace", "default")
    owner = cr["metadata"]["name"]
    spec = cr.get("spec", {})
    comps = desired_components(cr)

    selector = {LABEL_MANAGED: MANAGED_BY, LABEL_OWNER: owner}
    created, replaced, deleted = _sweep(
        kube, namespace, comps, ("DynamoComponentDeployment",),
        selector, keep_fields=("replicas",),
    )

    # the shared fabric belongs to the graph, not any one component
    fabric_objs = []
    if not spec.get("fabricExternal", False):
        fabric_objs = render_k8s(
            {"image": spec.get("image", "dynamo-tpu:latest"), "services": []},
            fabric_host=spec.get("fabricHost", f"{owner}-fabric"),
            include_fabric=True,
            fabric_port=int(spec.get("fabricPort", 4222)),
        )
        for obj in fabric_objs:
            meta = obj.setdefault("metadata", {})
            meta["namespace"] = namespace
            meta.setdefault("labels", {}).update(selector)
            if obj["kind"] == "Deployment":
                # keep `kubectl get pods -l dynamo.tpu/deployment=<name>`
                # covering the fabric pod too
                tmeta = obj["spec"]["template"].setdefault("metadata", {})
                tmeta.setdefault("labels", {})[LABEL_OWNER] = owner
    fabric_selector = dict(selector, **{LABEL_COMPONENT: "fabric"})
    for obj in fabric_objs:
        obj["metadata"]["labels"][LABEL_COMPONENT] = "fabric"
    c2, r2, d2 = _sweep(
        kube, namespace, fabric_objs, ("Deployment", "Service"),
        fabric_selector,
    )
    created, replaced, deleted = created + c2, replaced + r2, deleted + d2

    # component-level convergence (the controller instead runs its own
    # per-DCD pass each tick, catching /scale changes between graph edits)
    if converge_components:
        for comp in comps:
            observed = kube.get(
                "DynamoComponentDeployment", namespace,
                comp["metadata"]["name"],
            )
            if observed is not None:
                c3, r3, d3 = reconcile_component_counts(kube, observed)
                created, replaced, deleted = (
                    created + c3, replaced + r3, deleted + d3,
                )

    # children of components that no longer exist (service removed from
    # the graph): their DCD was swept above, so nothing reconciles them —
    # delete by exclusion on the component label
    live_comps = {c["metadata"]["name"] for c in comps} | {"fabric"}
    for kind in ("Deployment", "Service"):
        for obj in kube.list(kind, namespace, selector):
            comp = (obj["metadata"].get("labels") or {}).get(LABEL_COMPONENT)
            # no component label = a stray we own anyway (pre-component
            # operator versions, manual edits): sweep it with the rest
            if comp not in live_comps:
                kube.delete(kind, namespace, obj["metadata"]["name"])
                deleted += 1

    if created or replaced or deleted:
        logger.info(
            "reconciled %s/%s: +%d ~%d -%d",
            namespace, owner, created, replaced, deleted,
        )
    return {
        "observedGeneration": cr["metadata"].get("generation", 0),
        "conditions": [
            {
                "type": "Ready",
                "status": "True",
                "reason": "Reconciled",
                "message": f"{len(comps)} components",
            }
        ],
        "lastAction": {
            "created": created, "replaced": replaced, "deleted": deleted,
        },
    }


def component_objects(dcd: dict) -> list[dict]:
    """Render one component CR's children (Deployment + Service when it
    exposes a port) with graph + component ownership labels."""
    spec = dcd.get("spec", {})
    svc = dict(spec.get("service", {}))
    svc["replicas"] = spec.get("replicas", 1)
    objs = render_k8s(
        {"image": spec.get("image", "dynamo-tpu:latest"), "services": [svc]},
        fabric_host=spec.get("fabricHost", "dynamo-fabric"),
        include_fabric=False,
        fabric_port=int(spec.get("fabricPort", 4222)),
    )
    namespace = dcd["metadata"].get("namespace", "default")
    owner = dcd["metadata"].get("labels", {}).get(
        LABEL_OWNER, dcd["metadata"]["name"]
    )
    comp = dcd["metadata"]["name"]
    for obj in objs:
        meta = obj.setdefault("metadata", {})
        meta["namespace"] = namespace
        labels = meta.setdefault("labels", {})
        labels[LABEL_MANAGED] = MANAGED_BY
        labels[LABEL_OWNER] = owner
        labels[LABEL_COMPONENT] = comp
        if obj["kind"] == "Deployment":
            tmeta = obj["spec"]["template"].setdefault("metadata", {})
            tlabels = tmeta.setdefault("labels", {})
            tlabels[LABEL_OWNER] = owner
    return objs


def reconcile_component_counts(kube: Any, dcd: dict) -> tuple[int, int, int]:
    namespace = dcd["metadata"].get("namespace", "default")
    comp = dcd["metadata"]["name"]
    objs = component_objects(dcd)
    selector = {LABEL_MANAGED: MANAGED_BY, LABEL_COMPONENT: comp}
    return _sweep(
        kube, namespace, objs, ("Deployment", "Service"), selector
    )


def reconcile_component(kube: Any, dcd: dict) -> dict:
    """Component-level pass. Returns a status patch for the DCD."""
    created, replaced, deleted = reconcile_component_counts(kube, dcd)
    replicas = dcd.get("spec", {}).get("replicas", 1)
    return {
        "observedGeneration": dcd["metadata"].get("generation", 0),
        "replicas": replicas,  # statusReplicasPath for the /scale read
        "conditions": [
            {
                "type": "Ready",
                "status": "True",
                "reason": "Reconciled",
                "message": f"replicas={replicas}",
            }
        ],
        "lastAction": {
            "created": created, "replaced": replaced, "deleted": deleted,
        },
    }


def garbage_collect(kube: Any, namespace: str, live_owners: set[str]) -> int:
    """Delete objects owned by CRs that no longer exist (explicit-label GC —
    the ownerReference cascade without relying on the API server)."""
    n = 0
    # children of STANDALONE component CRs (user-created, no graph) carry
    # the DCD's own name as owner — they are live as long as their DCD is
    live = set(live_owners) | {
        d["metadata"]["name"]
        for d in kube.list("DynamoComponentDeployment", namespace)
    }
    for obj in kube.list(
        "DynamoComponentDeployment", namespace, {LABEL_MANAGED: MANAGED_BY}
    ):
        owner = (obj["metadata"].get("labels") or {}).get(LABEL_OWNER)
        if owner and owner not in live_owners:
            kube.delete(
                "DynamoComponentDeployment", namespace,
                obj["metadata"]["name"],
            )
            live.discard(obj["metadata"]["name"])
            n += 1
    for kind in ("Deployment", "Service"):
        for obj in kube.list(kind, namespace, {LABEL_MANAGED: MANAGED_BY}):
            owner = (obj["metadata"].get("labels") or {}).get(LABEL_OWNER)
            if owner and owner not in live:
                kube.delete(kind, namespace, obj["metadata"]["name"])
                n += 1
    return n

"""Kubernetes operator: reconciles DynamoGraphDeployment custom resources
into Deployments/Services (reference parity: the Go operator at
/root/reference deploy/cloud/operator — CRDs DynamoGraphDeployment /
DynamoComponentDeployment, api/v1alpha1/dynamographdeployment_types.go:33-41,
reconcilers in internal/controller/).

Python-native here: the reconcile core is a pure diff over desired vs
observed objects (testable with the in-memory kube backend — the envtest
analog), the kube client speaks the REST API directly from in-cluster
credentials, and the controller is a poll loop (no informer machinery
needed at this scale)."""

from dynamo_tpu.operator.controller import Controller
from dynamo_tpu.operator.kube import InMemoryKube
from dynamo_tpu.operator.reconciler import reconcile, reconcile_component

__all__ = ["Controller", "InMemoryKube", "reconcile", "reconcile_component"]

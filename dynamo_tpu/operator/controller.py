"""Operator controller: a poll-reconcile loop over DynamoGraphDeployments.

The reference operator is informer/watch-driven (controller-runtime); at
this scale a bounded poll interval gives the same convergence with far
less machinery, and the reconcile core stays a pure function. Each pass:

1. list CRs in the watched namespace
2. reconcile each (create/replace/delete children, patch status)
3. garbage-collect children whose CR is gone

Errors on one CR don't block the others; the loop continues."""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

from dynamo_tpu.operator.reconciler import (
    garbage_collect,
    reconcile,
    reconcile_component,
)

logger = logging.getLogger(__name__)


class Controller:
    def __init__(self, kube: Any, namespace: str = "default", interval_s: float = 5.0):
        self.kube = kube
        self.namespace = namespace
        self.interval_s = interval_s
        self._stop = threading.Event()
        self.passes = 0

    def reconcile_once(self) -> dict[str, dict]:
        """One full pass; returns status patches by CR name."""
        statuses: dict[str, dict] = {}
        crs = self.kube.list("DynamoGraphDeployment", self.namespace)
        live = set()
        for cr in crs:
            name = cr["metadata"]["name"]
            live.add(name)
            try:
                # component convergence happens in our own pass below
                status = reconcile(self.kube, cr, converge_components=False)
                self.kube.patch_status(
                    "DynamoGraphDeployment", self.namespace, name, status
                )
                statuses[name] = status
            except Exception:
                logger.exception("reconcile failed for %s", name)
                statuses[name] = {
                    "conditions": [
                        {"type": "Ready", "status": "False", "reason": "Error"}
                    ]
                }
        # Component pass: converge every DCD and record its status —
        # this is what picks up /scale subresource changes (planner,
        # HPA) between graph edits.
        for dcd in self.kube.list(
            "DynamoComponentDeployment", self.namespace
        ):
            name = dcd["metadata"]["name"]
            try:
                status = reconcile_component(self.kube, dcd)
                self.kube.patch_status(
                    "DynamoComponentDeployment", self.namespace, name, status
                )
            except Exception:
                logger.exception("component reconcile failed for %s", name)
        gc = garbage_collect(self.kube, self.namespace, live)
        if gc:
            logger.info("garbage-collected %d orphaned objects", gc)
        self.passes += 1
        return statuses

    def run(self, max_passes: Optional[int] = None) -> None:
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:
                # A transient apiserver error on list()/garbage_collect()
                # must not kill the operator; retry on the next interval.
                logger.exception("reconcile pass failed; retrying")
                self.passes += 1
            if max_passes is not None and self.passes >= max_passes:
                return
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()


def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    p = argparse.ArgumentParser("dynamo-tpu-operator")
    p.add_argument("--namespace", default="default")
    p.add_argument("--interval", type=float, default=5.0)
    args = p.parse_args(argv)

    from dynamo_tpu.operator.kube import InClusterKube

    kube = InClusterKube()
    Controller(kube, namespace=args.namespace, interval_s=args.interval).run()


if __name__ == "__main__":
    main()

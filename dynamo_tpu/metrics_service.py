"""Standalone metrics service: fleet observability -> Prometheus.

Capability parity with the reference's metrics component
(/root/reference components/metrics/src/main.rs: scrape endpoint stats,
aggregate LLMWorkerLoadCapacityConfig, serve Prometheus, subscribe
KVHitRateEvent on `kv-hit-rate`). Here the worker metrics plane is
push-based (worker.py _publish_loop), so the service subscribes instead of
scraping, converts the latest per-worker snapshots plus cumulative
router hit-rate counters into Prometheus text format, and serves
/metrics + /health over HTTP.

Run: `dynamo-tpu metrics --fabric host:port --port 9091`.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web

from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.subjects import KV_HIT_RATE_SUBJECT

logger = logging.getLogger(__name__)

PREFIX = "dynamo_tpu"

#: worker snapshot fields -> (prometheus suffix, type). Counters whose
#: field name lacks the `_total` suffix gain it in the EXPOSED name
#: (Prometheus naming convention, enforced by telemetry/promlint.py in
#: tests) — e.g. snapshot field `steps` serves as
#: dynamo_tpu_worker_steps_total. See docs/migrating.md.
_WORKER_FIELDS = (
    ("kv_usage", "gauge"),
    ("kv_active_pages", "gauge"),
    ("kv_free_pages", "gauge"),
    ("kv_total_pages", "gauge"),
    # KV-pool byte gauges (EngineConfig.kv_quantize): actual device bytes
    # (quantized pages + scale planes) vs the model-dtype equivalent —
    # their ratio is the effective cache-capacity multiplier
    ("kv_pool_bytes", "gauge"),
    ("kv_pool_bytes_dense_equiv", "gauge"),
    ("num_waiting", "gauge"),
    ("num_running", "gauge"),
    ("prefix_hit_rate", "gauge"),
    ("steps", "counter"),
    ("generated_tokens", "counter"),
    ("requests_received", "counter"),
    # disagg KV transfer planes (absent on non-disagg workers)
    ("kv_transfer_device_total", "counter"),
    ("kv_transfer_shm_total", "counter"),
    ("kv_transfer_bulk_total", "counter"),
    ("kv_transfer_host_total", "counter"),
    ("remote_prefills_total", "counter"),
    # step-phase wall time (EngineMetrics.time_*_ms — host-loop
    # observability; ratios against dispatch counters give ms/dispatch)
    ("time_schedule_ms", "counter"),
    ("time_prefill_ms", "counter"),
    ("time_decode_ms", "counter"),
    # mixed prefill+decode steps (EngineConfig.mixed_steps): one fused
    # dispatch carrying a prefill chunk AND the decode batch — the
    # stall-free path (docs/engine.md "Mixed steps")
    ("time_mixed_ms", "counter"),
    # decode's phase split (dispatch/sync/postprocess) + the overlapped-
    # decode pipeline counters — sync collapsing toward zero is the
    # overlap working (docs/engine.md "The decode loop")
    ("time_decode_dispatch_ms", "counter"),
    ("time_decode_sync_ms", "counter"),
    ("time_decode_host_ms", "counter"),
    ("prefill_dispatches", "counter"),
    ("decode_dispatches", "counter"),
    ("mixed_dispatches", "counter"),
    ("overlap_dispatches", "counter"),
    ("overlap_hits", "counter"),
    ("overlap_rollbacks", "counter"),
    # subprocess external-engine harness (absent on native workers):
    # supervisor lifecycle for foreign engines (docs/external_engines.md
    # "Level 2") — restarts climbing or ready=0 is a crash-looping child
    ("ext_ready", "gauge"),
    ("ext_broken", "gauge"),
    ("ext_restarts_total", "counter"),
    ("ext_consecutive_failures", "gauge"),
)


class MetricsService:
    def __init__(
        self,
        fabric,
        component: str = "backend",
        host: str = "127.0.0.1",
        port: int = 9091,
        fabric_stats_interval: float = 2.0,
    ):
        self.fabric = fabric
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = MetricsAggregator(fabric, component)
        # cumulative router-decision counters (KVHitRateEvent stream)
        self.hit_events = 0
        self.isl_tokens_total = 0
        self.overlap_tokens_total = 0
        #: latest broker self-metrics snapshot (fabric `stats` op) —
        #: empty when the fabric backend doesn't expose stats
        self.fabric_stats: dict = {}
        self.fabric_stats_interval = fabric_stats_interval
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.aggregator.start()
        self._sub = await self.fabric.subscribe(KV_HIT_RATE_SUBJECT)
        self._task = asyncio.get_running_loop().create_task(self._pump())
        if hasattr(self.fabric, "stats"):
            self._stats_task = asyncio.get_running_loop().create_task(
                self._poll_fabric_stats()
            )
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        app.router.add_get("/v1/traces", self._traces)
        app.router.add_get("/v1/traces/{trace_id}", self._trace)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        logger.info("metrics service on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()
        if self._stats_task is not None:
            self._stats_task.cancel()
        await self.aggregator.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                h = msg.header or {}
                isl = int(h.get("isl_tokens", 0))
                overlap = int(h.get("overlap_tokens", 0))
            except (TypeError, ValueError, AttributeError):
                # One malformed publish must not kill the consumer task and
                # freeze the counters for every later legitimate event.
                logger.warning("malformed kv-hit-rate event: %r", msg.header)
                continue
            self.hit_events += 1
            self.isl_tokens_total += isl
            self.overlap_tokens_total += overlap

    async def _poll_fabric_stats(self) -> None:
        """Broker self-metrics: poll the fabric's `stats` op (RemoteFabric
        issues the wire request; LocalFabric answers in-process). A
        broker outage blanks the snapshot instead of serving stale
        numbers."""
        while True:
            try:
                res = self.fabric.stats()
                if asyncio.iscoroutine(res):
                    res = await res
                self.fabric_stats = res or {}
            except asyncio.CancelledError:
                raise
            except Exception:
                self.fabric_stats = {}
            await asyncio.sleep(self.fabric_stats_interval)

    # -- exposition --------------------------------------------------------

    def _fabric_lines(self) -> list[str]:
        lines = []
        for key, val in sorted(self.fabric_stats.items()):
            if key == "queues":
                name = f"{PREFIX}_fabric_queue_depth"
                lines.append(f"# TYPE {name} gauge")
                for qname, depth in sorted(val.items()):
                    lines.append(f'{name}{{queue="{qname}"}} {depth}')
                continue
            if not isinstance(val, (int, float)):
                continue
            ptype = "counter" if key.endswith("_total") else "gauge"
            name = f"{PREFIX}_fabric_{key}"
            lines.append(f"# TYPE {name} {ptype}")
            lines.append(f"{name} {val}")
        return lines

    def expose(self) -> str:
        snap = self.aggregator.snapshot()
        lines = [
            f"# TYPE {PREFIX}_live_workers gauge",
            f'{PREFIX}_live_workers{{component="{self.component}"}} {len(snap)}',
        ]
        for field, ptype in _WORKER_FIELDS:
            name = f"{PREFIX}_worker_{field}"
            if ptype == "counter" and not field.endswith("_total"):
                name += "_total"
            lines.append(f"# TYPE {name} {ptype}")
            for iid, m in sorted(snap.items()):
                if field in m:
                    lines.append(
                        f'{name}{{component="{self.component}",'
                        f'instance="{iid}"}} {m[field]}'
                    )
        lines += [
            f"# TYPE {PREFIX}_kv_hit_rate_events_total counter",
            f"{PREFIX}_kv_hit_rate_events_total {self.hit_events}",
            f"# TYPE {PREFIX}_kv_hit_rate_isl_tokens_total counter",
            f"{PREFIX}_kv_hit_rate_isl_tokens_total {self.isl_tokens_total}",
            f"# TYPE {PREFIX}_kv_hit_rate_overlap_tokens_total counter",
            f"{PREFIX}_kv_hit_rate_overlap_tokens_total {self.overlap_tokens_total}",
            f"# TYPE {PREFIX}_kv_hit_rate gauge",
            f"{PREFIX}_kv_hit_rate "
            f"{self.overlap_tokens_total / self.isl_tokens_total if self.isl_tokens_total else 0.0}",
        ]
        lines += self._fabric_lines()
        # per-phase latency histograms (telemetry plane, process-global)
        from dynamo_tpu.telemetry import phases

        lines += phases.expose_lines()
        return "\n".join(lines) + "\n"

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=self.expose(), content_type="text/plain", charset="utf-8"
        )

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "workers": len(self.aggregator.snapshot())}
        )

    async def _traces(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.http_api import traces_payload

        body, status = traces_payload(request.query.get("limit"))
        return web.json_response(body, status=status)

    async def _trace(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.http_api import trace_payload

        body, status = trace_payload(
            request.match_info["trace_id"], request.query.get("format")
        )
        return web.json_response(body, status=status)

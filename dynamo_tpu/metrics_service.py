"""Standalone metrics service: fleet observability -> Prometheus.

Capability parity with the reference's metrics component
(/root/reference components/metrics/src/main.rs: scrape endpoint stats,
aggregate LLMWorkerLoadCapacityConfig, serve Prometheus, subscribe
KVHitRateEvent on `kv-hit-rate`). Here the worker metrics plane is
push-based (worker.py _publish_loop), so the service subscribes instead of
scraping, converts the latest per-worker snapshots plus cumulative
router hit-rate counters into Prometheus text format, and serves
/metrics + /health over HTTP.

Run: `dynamo-tpu metrics --fabric host:port --port 9091`.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from aiohttp import web

from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.subjects import (
    FLEET_EVENTS_SUBJECT,
    KV_HIT_RATE_SUBJECT,
    KV_INDEX_SUBJECT,
    PLANNER_SUBJECT,
    TRACE_SPANS_SUBJECT,
)
from dynamo_tpu.telemetry.events import EventRing
from dynamo_tpu.telemetry.traceplane import TailSampler, TraceAssembler

logger = logging.getLogger(__name__)

PREFIX = "dynamo_tpu"

#: worker snapshot fields -> (prometheus suffix, type). Counters whose
#: field name lacks the `_total` suffix gain it in the EXPOSED name
#: (Prometheus naming convention, enforced by telemetry/promlint.py in
#: tests) — e.g. snapshot field `steps` serves as
#: dynamo_tpu_worker_steps_total. See docs/migrating.md.
_WORKER_FIELDS = (
    ("kv_usage", "gauge"),
    ("kv_active_pages", "gauge"),
    ("kv_free_pages", "gauge"),
    ("kv_total_pages", "gauge"),
    # KV-pool byte gauges (EngineConfig.kv_quantize): actual device bytes
    # (quantized pages + scale planes) vs the model-dtype equivalent —
    # their ratio is the effective cache-capacity multiplier
    ("kv_pool_bytes", "gauge"),
    ("kv_pool_bytes_dense_equiv", "gauge"),
    ("num_waiting", "gauge"),
    ("num_running", "gauge"),
    ("prefix_hit_rate", "gauge"),
    ("steps", "counter"),
    ("generated_tokens", "counter"),
    ("requests_received", "counter"),
    # disagg KV transfer planes (absent on non-disagg workers)
    ("kv_transfer_device_total", "counter"),
    ("kv_transfer_shm_total", "counter"),
    ("kv_transfer_bulk_total", "counter"),
    ("kv_transfer_host_total", "counter"),
    ("remote_prefills_total", "counter"),
    # step-phase wall time (EngineMetrics.time_*_ms — host-loop
    # observability; ratios against dispatch counters give ms/dispatch)
    ("time_schedule_ms", "counter"),
    ("time_prefill_ms", "counter"),
    ("time_decode_ms", "counter"),
    # mixed prefill+decode steps (EngineConfig.mixed_steps): one fused
    # dispatch carrying a prefill chunk AND the decode batch — the
    # stall-free path (docs/engine.md "Mixed steps")
    ("time_mixed_ms", "counter"),
    # decode's phase split (dispatch/sync/postprocess) + the overlapped-
    # decode pipeline counters — sync collapsing toward zero is the
    # overlap working (docs/engine.md "The decode loop")
    ("time_decode_dispatch_ms", "counter"),
    ("time_decode_sync_ms", "counter"),
    ("time_decode_host_ms", "counter"),
    ("prefill_dispatches", "counter"),
    ("decode_dispatches", "counter"),
    ("mixed_dispatches", "counter"),
    ("overlap_dispatches", "counter"),
    ("overlap_hits", "counter"),
    ("overlap_rollbacks", "counter"),
    # on-device K-step decode windows (EngineConfig.decode_kstep):
    # steps/windows is the realized fusion depth, window_size the live
    # target after clamps, fallbacks the per-dispatch eligibility misses
    # (logprobs rows, oversized stop sets); time/windows is the
    # decode_kstep family's measured ms per window
    ("kstep_windows", "counter"),
    ("kstep_steps", "counter"),
    ("kstep_fallbacks", "counter"),
    ("kstep_window_size", "gauge"),
    ("time_kstep_ms", "counter"),
    # speculative decoding (spec_ngram / spec_draft_model): drafts
    # proposed vs accepted — their ratio times S is the extra tokens per
    # verify dispatch; the skip counters say WHY speculation sat out
    # (ineligible batch vs acceptance cooldown). spec_accept_rate is the
    # engine's live ~60 s window, not the lifetime ratio.
    ("spec_drafted", "counter"),
    ("spec_accepted", "counter"),
    ("spec_skipped_ineligible", "counter"),
    ("spec_skipped_cooldown", "counter"),
    ("spec_accept_rate", "gauge"),
    ("spec_window_drafted", "gauge"),
    # subprocess external-engine harness (absent on native workers):
    # supervisor lifecycle for foreign engines (docs/external_engines.md
    # "Level 2") — restarts climbing or ready=0 is a crash-looping child
    ("ext_ready", "gauge"),
    ("ext_broken", "gauge"),
    ("ext_restarts_total", "counter"),
    ("ext_consecutive_failures", "gauge"),
    # engine-internals plane (fleet telemetry): jit-cache misses + their
    # cumulative wall cost, page-pool pressure (high-watermark +
    # preemption-by-recompute), and the live utilization gauges
    ("compiles", "counter"),
    ("compile_ms", "counter"),
    ("kv_pages_watermark", "gauge"),
    ("preemptions", "counter"),
    ("tokens_per_s", "gauge"),
    ("mfu", "gauge"),
    # stall watchdog (telemetry/watchdog.py): stalls diagnosed on this
    # worker — climbing means streams are wedging (the per-cause split
    # is in the worker's own dynamo_tpu_stalls_total{cause} and in the
    # /v1/fleet snapshot's stalls_by_cause)
    ("stalls_total", "counter"),
    # overload plane (docs/operations.md "Overload & draining"): bounded-
    # admission rejects (EngineConfig.max_waiting) and deadline-expired
    # error finishes — climbing rejects = shedding (raise capacity);
    # deep num_waiting with zero rejects = queue unbounded (enable caps)
    ("overload_rejects", "counter"),
    ("deadline_expired", "counter"),
    # role flips this worker performed (closed-loop planner actuation —
    # docs/operations.md "Closed-loop autoscaling & role flips")
    ("flips_total", "counter"),
    # worker handover (docs/operations.md "Rolling upgrades & worker
    # handover"): completed handovers vs drain fallbacks on the retiring
    # side, KV bytes/blocks migrated out, blocks adopted as a successor,
    # and transfer frames the codec checksum rejected (wire corruption
    # never lands)
    ("handovers_total", "counter"),
    ("handover_fallbacks_total", "counter"),
    ("handover_bytes_total", "counter"),
    ("handover_blocks_total", "counter"),
    ("handovers_adopted_total", "counter"),
    ("kv_transfer_corrupt_total", "counter"),
    # control-plane HA (docs/operations.md "Control-plane HA"): the
    # worker's broker-connection view — degraded is live only while the
    # worker can still publish (partial partitions); the counters carry
    # the post-recovery accounting of full outages
    ("degraded", "gauge"),
    ("degraded_entries_total", "counter"),
    ("kv_events_dropped_total", "counter"),
    ("kv_events_pending", "gauge"),
    # KV economy (docs/operations.md "The KV economy"): source-side
    # per-prefix migration counters + KVBM tier residency/traffic — the
    # Grafana "KV economy" row and the doctor's migration-storm /
    # tier-pressure rules read these
    ("kv_migrations_total", "counter"),
    ("kv_migration_fallbacks_total", "counter"),
    ("kv_migration_bytes_total", "counter"),
    ("kv_migration_blocks_total", "counter"),
    ("kvbm_host_blocks", "gauge"),
    ("kvbm_disk_blocks", "gauge"),
    ("kvbm_demotions_total", "counter"),
    ("kvbm_promotions_total", "counter"),
    ("kvbm_host_hits_total", "counter"),
    ("kvbm_disk_hits_total", "counter"),
    # HBM accounting plane (docs/observability.md "Reading the perf
    # plane"): per-worker byte totals summed over the worker's local
    # devices — weights (param-tree shards), KV pool, compiled-program
    # scratch estimate, free and peak. On CPU the engine falls back to
    # accounted sums (source="accounted" in the /v1/debug/memory doc);
    # the per-device split rides the frames' "memory" report
    ("hbm_weights_bytes", "gauge"),
    ("hbm_kv_pool_bytes", "gauge"),
    ("hbm_scratch_bytes", "gauge"),
    ("hbm_free_bytes", "gauge"),
    ("hbm_peak_bytes", "gauge"),
    # multi-host SPMD introspection: jax.process_index() of the worker
    # plus its flight-window dispatch p95 — the fleet host-skew family
    # (dynamo_tpu_fleet_host_dispatch_p95_ms{host}) and the doctor's
    # host-skew rule are derived from these two
    ("host", "gauge"),
    ("dispatch_p95_ms", "gauge"),
)

#: numeric per-worker fields copied verbatim into the /v1/fleet snapshot
_FLEET_WORKER_FIELDS = (
    "kv_usage", "kv_free_pages", "kv_active_pages", "kv_total_pages",
    "kv_pages_watermark", "preemptions", "num_running", "num_waiting",
    "steps", "generated_tokens", "requests_received", "compiles",
    "compile_ms", "tokens_per_s", "mfu", "prefix_hit_rate",
    "stalls_total", "overload_rejects", "deadline_expired", "flips_total",
    "spec_drafted", "spec_accepted", "spec_skipped_ineligible",
    "spec_skipped_cooldown", "spec_accept_rate", "spec_window_drafted",
    "kstep_windows", "kstep_steps", "kstep_window_size",
    "handovers_total", "handover_fallbacks_total", "handover_bytes_total",
    "handover_blocks_total", "handovers_adopted_total",
    "kv_transfer_corrupt_total",
    "degraded", "degraded_entries_total", "kv_events_dropped_total",
    "kv_events_pending",
    "kv_migrations_total", "kv_migration_fallbacks_total",
    "kv_migration_bytes_total", "kv_migration_blocks_total",
    "kvbm_host_blocks", "kvbm_disk_blocks", "kvbm_demotions_total",
    "kvbm_promotions_total", "kvbm_host_hits_total",
    "kvbm_disk_hits_total",
    "hbm_weights_bytes", "hbm_kv_pool_bytes", "hbm_scratch_bytes",
    "hbm_free_bytes", "hbm_peak_bytes", "host", "dispatch_p95_ms",
)


class MetricsService:
    def __init__(
        self,
        fabric,
        component: str = "backend",
        host: str = "127.0.0.1",
        port: int = 9091,
        fabric_stats_interval: float = 2.0,
        extra_components: tuple = ("prefill",),
        trace_sample_rate: Optional[int] = None,
        trace_window_s: float = 2.0,
        trace_keep: int = 512,
        trace_sample_seed: int = 0,
    ):
        self.fabric = fabric
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = MetricsAggregator(fabric, component)
        #: fleet trace plane (docs/observability.md "Fleet traces &
        #: event timeline"): assemble every process's shipped spans into
        #: cross-process traces behind the tail sampler. "Slow" tracks
        #: the LIVE fleet SLO p95s via _slo_p95s (cached ~5 s).
        import os as _os

        rate = (
            trace_sample_rate
            if trace_sample_rate is not None
            else int(_os.environ.get("DYNTPU_TRACE_SAMPLE_RATE", "10") or 10)
        )
        self.trace_sampler = TailSampler(
            healthy_rate=rate,
            seed=trace_sample_seed,
            slo_p95s=self._slo_p95s,
        )
        self.traces = TraceAssembler(
            sampler=self.trace_sampler,
            window_s=trace_window_s,
            keep=trace_keep,
        )
        self._slo_p95_cache: tuple[float, dict] = (0.0, {})
        #: fleet event timeline: bounded ring of control-plane events
        #: (flips, handovers, shed episodes, planner decisions, replays,
        #: resyncs, worker losses) served at GET /v1/fleet/events and
        #: exposed for the Grafana annotation layer
        self.events = EventRing()
        #: fleet view spans every serving role: one aggregator per
        #: component's subject space (decode pool + disagg prefill pool
        #: by default). The primary keeps its name for back-compat.
        self.aggregators = [self.aggregator] + [
            MetricsAggregator(fabric, c)
            for c in extra_components
            if c and c != component
        ]
        #: per-instance (requests_received, generated_tokens, monotonic)
        #: baselines for the fleet snapshot's req/s + tok/s rates
        self._rate_state: dict[str, tuple[int, int, float]] = {}
        #: counter-churn bookkeeping for the `dynamo_tpu_fleet_*_total`
        #: families: last-seen counter contributions per live worker, and
        #: per-role monotonic bases holding the contributions of departed
        #: or restarted workers (see _fold_departed)
        self._live_contrib: dict[str, tuple[str, dict]] = {}
        self._retired_counters: dict[str, dict] = {}
        #: contributions folded for AGED-OUT workers, kept so a worker
        #: that returns with its counters intact (a transient publish
        #: gap — partition, fabric outage — not a restart) can be
        #: UN-folded instead of double-counted (see _fold_departed)
        self._ghost_contrib: dict[str, tuple[str, dict]] = {}
        #: last advertised state per worker (serving/draining/handover)
        #: — a departure that ANNOUNCED itself (drain, handover: it
        #: already put its own event on the timeline) must not also
        #: fire a worker_lost warning when its frames age out
        self._last_state: dict[str, str] = {}
        # cumulative router-decision counters (KVHitRateEvent stream)
        self.hit_events = 0
        self.isl_tokens_total = 0
        self.overlap_tokens_total = 0
        #: latest broker self-metrics snapshot (fabric `stats` op) —
        #: empty when the fabric backend doesn't expose stats
        self.fabric_stats: dict = {}
        self.fabric_stats_interval = fabric_stats_interval
        #: latest closed-loop planner status frame (ControlRunner.status
        #: over PLANNER_SUBJECT) + when it arrived — serves the
        #: dynamo_tpu_planner_* families and the /v1/fleet `planner`
        #: section doctor's planner rules read
        self.planner_status: Optional[dict] = None
        self.planner_status_age: float = 0.0
        #: latest KV index-health frame per (component, router id)
        #: (KvRouter publishes its indexer's stats over
        #: KV_INDEX_SUBJECT) — serves the
        #: dynamo_tpu_router_kv_index_*{component,router} families and
        #: the /v1/fleet `kv_index` section doctor's kv-index-drift
        #: rule reads
        self.kv_index_status: dict[str, dict] = {}
        self.kv_index_status_age: dict[str, float] = {}
        self._sub = None
        self._planner_sub = None
        self._kv_index_sub = None
        self._trace_sub = None
        self._events_sub = None
        self._task: Optional[asyncio.Task] = None
        self._kv_index_task: Optional[asyncio.Task] = None
        self._planner_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._trace_task: Optional[asyncio.Task] = None
        self._events_task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        self._runner: Optional[web.AppRunner] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        for agg in self.aggregators:
            await agg.start()
        self._sub = await self.fabric.subscribe(KV_HIT_RATE_SUBJECT)
        self._task = asyncio.get_running_loop().create_task(self._pump())
        self._planner_sub = await self.fabric.subscribe(PLANNER_SUBJECT)
        self._planner_task = asyncio.get_running_loop().create_task(
            self._planner_pump()
        )
        self._kv_index_sub = await self.fabric.subscribe(KV_INDEX_SUBJECT)
        self._kv_index_task = asyncio.get_running_loop().create_task(
            self._kv_index_pump()
        )
        self._trace_sub = await self.fabric.subscribe(TRACE_SPANS_SUBJECT)
        self._trace_task = asyncio.get_running_loop().create_task(
            self._trace_pump()
        )
        self._events_sub = await self.fabric.subscribe(FLEET_EVENTS_SUBJECT)
        self._events_task = asyncio.get_running_loop().create_task(
            self._events_pump()
        )
        self._sweep_task = asyncio.get_running_loop().create_task(
            self._trace_sweep_loop()
        )
        if hasattr(self.fabric, "stats"):
            self._stats_task = asyncio.get_running_loop().create_task(
                self._poll_fabric_stats()
            )
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        app.router.add_get("/v1/fleet", self._fleet)
        app.router.add_get("/v1/fleet/events", self._fleet_events)
        app.router.add_get("/v1/traces", self._traces)
        app.router.add_get("/v1/traces/{trace_id}", self._trace)
        app.router.add_get("/v1/debug/flight", self._debug_flight)
        app.router.add_get("/v1/debug/programs", self._debug_programs)
        app.router.add_get("/v1/debug/memory", self._debug_memory)
        app.router.add_get("/v1/debug/mesh", self._debug_mesh)
        app.router.add_post("/v1/debug/profile", self._debug_profile)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        logger.info("metrics service on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()
        if self._planner_sub is not None:
            self._planner_sub.close()
        if self._planner_task is not None:
            self._planner_task.cancel()
        if self._kv_index_sub is not None:
            self._kv_index_sub.close()
        if self._kv_index_task is not None:
            self._kv_index_task.cancel()
        if self._trace_sub is not None:
            self._trace_sub.close()
        if self._trace_task is not None:
            self._trace_task.cancel()
        if self._events_sub is not None:
            self._events_sub.close()
        if self._events_task is not None:
            self._events_task.cancel()
        if self._sweep_task is not None:
            self._sweep_task.cancel()
        if self._stats_task is not None:
            self._stats_task.cancel()
        for agg in self.aggregators:
            await agg.stop()
        if self._runner is not None:
            await self._runner.cleanup()

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                h = msg.header or {}
                isl = int(h.get("isl_tokens", 0))
                overlap = int(h.get("overlap_tokens", 0))
            except (TypeError, ValueError, AttributeError):
                # One malformed publish must not kill the consumer task and
                # freeze the counters for every later legitimate event.
                logger.warning("malformed kv-hit-rate event: %r", msg.header)
                continue
            self.hit_events += 1
            self.isl_tokens_total += isl
            self.overlap_tokens_total += overlap

    async def _trace_pump(self) -> None:
        """Consume shipped span batches into the assembler. A malformed
        batch is logged and skipped — one garbage publisher must not
        sever the whole trace plane."""
        import msgpack

        while True:
            msg = await self._trace_sub.next()
            if msg is None:
                return
            try:
                spans = msgpack.unpackb(msg.payload, raw=False)
                if not isinstance(spans, list):
                    raise TypeError(f"span batch is {type(spans).__name__}")
            except Exception:
                logger.warning("malformed trace.spans batch", exc_info=True)
                continue
            try:
                self.traces.add_spans(spans)
            except Exception:
                logger.warning("trace assembly failed", exc_info=True)

    async def _events_pump(self) -> None:
        """Consume fleet-event batch frames into the bounded ring
        (garbage batches/frames are dropped — by the unpack guard and
        EventRing.add respectively — and never kill the pump)."""
        import msgpack

        while True:
            msg = await self._events_sub.next()
            if msg is None:
                return
            try:
                batch = msgpack.unpackb(msg.payload, raw=False)
                if not isinstance(batch, list):
                    raise TypeError(
                        f"event batch is {type(batch).__name__}"
                    )
            except Exception:
                logger.warning(
                    "malformed fleet.events batch", exc_info=True
                )
                continue
            for ev in batch:
                self.events.add(ev)

    async def _trace_sweep_loop(self) -> None:
        """Finalize trace assemblies that went quiet past the window
        (the tail-sampling decision point)."""
        interval = max(0.25, self.traces.window_s / 4.0)
        while True:
            await asyncio.sleep(interval)
            try:
                self.traces.sweep()
            except Exception:
                logger.warning("trace sweep failed", exc_info=True)

    def _slo_p95s(self) -> dict:
        """Live fleet TTFT/e2e p95s for the tail sampler's "slow"
        thresholds, merged from the workers' SLO wires and cached ~5 s
        (the sampler calls this per finalized trace). Sketches with too
        few observations return nothing — a cold fleet must not flag
        every trace slow off three data points."""
        import time as _time

        from dynamo_tpu.telemetry import slo as slo_mod

        now = _time.monotonic()
        cached_at, cached = self._slo_p95_cache
        if now - cached_at < 5.0:
            return cached
        wires = []
        for iid, (m, age, comp) in self._snapshot_all().items():
            wire = m.get("slo")
            if isinstance(wire, dict):
                wires.append(wire)
        out: dict = {}
        try:
            merged = slo_mod.merge_trackers(wires)
            for metric in ("ttft_ms", "e2e_ms"):
                sk = merged.sketches.get(metric)
                if sk is not None and sk.count >= 50:
                    q = sk.quantile(0.95)
                    if q is not None:
                        out[metric] = float(q)
        except Exception:
            logger.warning("slo p95 merge failed", exc_info=True)
            out = {}
        self._slo_p95_cache = (now, out)
        return out

    async def _planner_pump(self) -> None:
        """Latest-wins consumer of the planner's status frames. A
        malformed frame is logged and skipped — the planner section
        degrades to its previous value, never kills the pump."""
        import time as _time

        while True:
            msg = await self._planner_sub.next()
            if msg is None:
                return
            frame = getattr(msg, "header", None)
            if not isinstance(frame, dict):
                logger.warning("malformed planner frame: %r", frame)
                continue
            self.planner_status = frame
            self.planner_status_age = _time.monotonic()

    async def _kv_index_pump(self) -> None:
        """Latest-wins consumer of router index-health frames, keyed by
        (component, router id) — two routers serving the SAME component
        (e.g. two frontends) must not overwrite each other into a
        counter sawtooth; the exposition emits per-key samples and the
        fleet doc sums them. A malformed frame is logged and skipped,
        never kills the pump. Frames from dead routers age out."""
        import time as _time

        while True:
            msg = await self._kv_index_sub.next()
            if msg is None:
                return
            frame = getattr(msg, "header", None)
            if not isinstance(frame, dict):
                logger.warning("malformed kv_index frame: %r", frame)
                continue
            comp = str(frame.get("component") or "backend")
            key = f"{comp}|{frame.get('router') or ''}"
            now = _time.monotonic()
            self.kv_index_status[key] = frame
            self.kv_index_status_age[key] = now
            # a restarted router gets a fresh router id: prune entries
            # that stopped refreshing so its old counters don't linger
            for k in list(self.kv_index_status):
                if now - self.kv_index_status_age.get(k, now) > 15.0:
                    del self.kv_index_status[k]
                    self.kv_index_status_age.pop(k, None)

    def _kv_index_doc(self) -> Optional[dict]:
        """The /v1/fleet `kv_index` section: SUMMED counters across
        every live router frame at the top level (one stale subtree
        anywhere must surface there) plus the per-(component, router)
        frames underneath."""
        import time as _time

        if not self.kv_index_status:
            return None
        now = _time.monotonic()
        doc: dict = {"components": {}}
        totals = {
            k: 0
            for k in (
                "gaps_total", "resyncs_total", "resync_failures_total",
                "drift_blocks_total", "digest_mismatches_total",
                "stale_workers",
            )
        }
        for key, frame in sorted(self.kv_index_status.items()):
            doc["components"][key] = {
                **frame,
                "last_seen_s": round(
                    now - self.kv_index_status_age.get(key, now), 3
                ),
            }
            for k in totals:
                try:
                    totals[k] += int(frame.get(k) or 0)
                except (TypeError, ValueError):
                    pass
        doc.update(totals)
        return doc

    def _kv_index_lines(self) -> list[str]:
        """`dynamo_tpu_router_kv_index_*{component,router}` — the
        fleet-side view of router-published index health, one sample
        per live router frame (dashboards sum over them; the routers'
        own processes expose the unlabeled dynamo_tpu_kv_index_*
        families via debug.kv_index_lines)."""
        if not self.kv_index_status:
            return []
        lines: list[str] = []
        fields = (
            ("gaps_total", "counter"),
            ("resyncs_total", "counter"),
            ("resync_failures_total", "counter"),
            ("drift_blocks_total", "counter"),
            ("digest_mismatches_total", "counter"),
            ("stale_workers", "gauge"),
        )
        for fieldname, ptype in fields:
            samples = []
            for key, frame in sorted(self.kv_index_status.items()):
                v = frame.get(fieldname)
                if isinstance(v, (int, float)):
                    comp = str(frame.get("component") or "backend")
                    router = str(frame.get("router") or "")
                    samples.append((comp, router, v))
            if not samples:
                continue
            name = f"{PREFIX}_router_kv_index_{fieldname}"
            lines.append(f"# TYPE {name} {ptype}")
            for comp, router, v in samples:
                lines.append(
                    f'{name}{{component="{comp}",router="{router}"}} {v}'
                )
        return lines

    def _planner_doc(self) -> Optional[dict]:
        import time as _time

        if self.planner_status is None:
            return None
        return {
            **self.planner_status,
            "last_seen_s": round(
                _time.monotonic() - self.planner_status_age, 3
            ),
        }

    def _planner_lines(self) -> list[str]:
        """`dynamo_tpu_planner_*`: the closed-loop autoscaler's own
        exposition — pool targets vs observed, decision counters, flip
        count, SLO signals vs setpoint (the Grafana "Planner" row)."""
        p = self.planner_status
        if not isinstance(p, dict):
            return []
        lines: list[str] = []

        def fam(name: str, ptype: str, samples: list) -> None:
            samples = [(lab, v) for lab, v in samples if v is not None]
            if not samples:
                return
            lines.append(f"# TYPE {PREFIX}_planner_{name} {ptype}")
            for lab, v in samples:
                label = f"{{{lab}}}" if lab else ""
                lines.append(f"{PREFIX}_planner_{name}{label} {v}")

        targets = p.get("targets") or {}
        observed = p.get("observed") or {}
        fam("pool_target", "gauge", [
            (f'role="{r}"', targets.get(r)) for r in sorted(targets)
        ])
        fam("pool_observed", "gauge", [
            (f'role="{r}"', observed.get(r)) for r in sorted(observed)
        ])
        decisions = p.get("decisions_total") or {}
        fam("decisions_total", "counter", [
            (f'action="{a}"', decisions.get(a)) for a in sorted(decisions)
        ])
        fam("flips_total", "counter", [("", p.get("flips_total", 0))])
        fam("actions_clamped_total", "counter",
            [("", p.get("actions_clamped_total", 0))])
        fam("cooldown_holds_total", "counter",
            [("", p.get("cooldown_holds_total", 0))])
        signals = p.get("signals") or {}
        setpoint = p.get("setpoint") or {}
        fam("sla_attainment", "gauge",
            [("", signals.get("sla_attainment"))])
        fam("burn_rate", "gauge", [("", signals.get("burn_rate"))])
        fam("attainment_setpoint", "gauge",
            [("", setpoint.get("attainment"))])
        fam("burn_high_ticks", "gauge", [("", p.get("burn_high_ticks"))])
        fam("at_max", "gauge", [("", int(bool(p.get("at_max"))))])
        return lines

    def _control_plane_doc(self) -> dict:
        """The /v1/fleet `control_plane` section doctor's
        control-plane-degraded and replication-lag rules read: this
        process's own broker-connection state plus the latest broker
        self-metrics (replication lag, fence, orphaned leases)."""
        fab = self.fabric
        doc = {
            "degraded": bool(getattr(fab, "degraded", False)),
            "disconnected_s": round(
                float(getattr(fab, "disconnected_s", 0.0) or 0.0), 2
            ),
            "degraded_total": int(getattr(fab, "degraded_total", 0) or 0),
            "failovers_total": int(
                getattr(fab, "failovers_total", 0) or 0
            ),
            "addresses": list(getattr(fab, "addresses", []) or []),
        }
        st = self.fabric_stats
        if st:
            doc["broker"] = {
                k: st[k]
                for k in (
                    "is_primary", "fence", "repl_subscribers",
                    "repl_lag_records", "promotions_total",
                    "demotions_total", "orphaned_leases", "active_leases",
                )
                if k in st
            }
        return doc

    async def _poll_fabric_stats(self) -> None:
        """Broker self-metrics: poll the fabric's `stats` op (RemoteFabric
        issues the wire request; LocalFabric answers in-process). A
        broker outage blanks the snapshot instead of serving stale
        numbers."""
        while True:
            try:
                res = self.fabric.stats()
                if asyncio.iscoroutine(res):
                    res = await res
                self.fabric_stats = res or {}
            except asyncio.CancelledError:
                raise
            except Exception:
                self.fabric_stats = {}
            await asyncio.sleep(self.fabric_stats_interval)

    # -- exposition --------------------------------------------------------

    def _fabric_lines(self) -> list[str]:
        lines = []
        for key, val in sorted(self.fabric_stats.items()):
            if key == "queues":
                name = f"{PREFIX}_fabric_queue_depth"
                lines.append(f"# TYPE {name} gauge")
                for qname, depth in sorted(val.items()):
                    lines.append(f'{name}{{queue="{qname}"}} {depth}')
                continue
            if not isinstance(val, (int, float)):
                continue
            ptype = "counter" if key.endswith("_total") else "gauge"
            name = f"{PREFIX}_fabric_{key}"
            lines.append(f"# TYPE {name} {ptype}")
            lines.append(f"{name} {val}")
        return lines

    def _snapshot_all(self) -> dict[str, tuple[dict, float, str]]:
        """instance_id → (frame, age_s, component) across every
        aggregated component (decode + prefill pools)."""
        out: dict[str, tuple[dict, float, str]] = {}
        for agg in self.aggregators:
            for iid, (m, age) in agg.snapshot_with_age().items():
                comp = m.get("component") or agg.component
                out[iid] = (m, age, str(comp))
        return out

    # -- fleet view (docs/observability.md "Fleet view & SLO accounting") --

    def _assemble_fleet(self, snap=None):
        """One pass over the live frames -> (snapshot doc, per-role
        MergedSlo). A worker publishing garbage is logged and skipped —
        the fleet view degrades by one worker, never dies (and never
        kills the serving pump; see tests/test_fleet_telemetry.py)."""
        import time as _time

        from dynamo_tpu.telemetry import slo as slo_mod

        if snap is None:
            snap = self._snapshot_all()
        now = _time.monotonic()
        workers: dict[str, dict] = {}
        wires_by_role: dict[str, list[dict]] = {}
        role_stats: dict[str, dict] = {}
        contribs: dict[str, tuple[str, dict]] = {}
        for iid, (m, age, comp) in sorted(snap.items()):
            try:
                role = str(
                    m.get("role")
                    or ("prefill" if "prefill" in comp else "decode")
                )
                w: dict = {
                    "role": role,
                    "component": comp,
                    "model": m.get("model"),
                    "last_seen_s": round(age, 3),
                }
                state = m.get("state")
                if isinstance(state, str):
                    # serving | draining | handover — doctor's draining-
                    # worker / handover-stuck rules and fleet_top key
                    # off this
                    w["state"] = state
                    self._last_state[iid] = state
                phase = m.get("handover_phase")
                if isinstance(phase, str):
                    w["handover_phase"] = phase
                for f in _FLEET_WORKER_FIELDS:
                    v = m.get(f)
                    if isinstance(v, (int, float)):
                        w[f] = v
                # req/s + tok/s from per-instance counter deltas (>=1 s
                # between baselines so rapid /v1/fleet polls don't alias)
                rr = int(m.get("requests_received", 0) or 0)
                gt = int(m.get("generated_tokens", 0) or 0)
                prev = self._rate_state.get(iid)
                if prev is not None and now - prev[2] >= 1.0:
                    dt = now - prev[2]
                    prev = (
                        rr, gt, now,
                        round(max(0, rr - prev[0]) / dt, 3),
                        round(max(0, gt - prev[1]) / dt, 2),
                    )
                    self._rate_state[iid] = prev
                elif prev is None:
                    prev = (rr, gt, now, 0.0, 0.0)
                    self._rate_state[iid] = prev
                w["req_s"], w["tok_s"] = prev[3], prev[4]
                cbk = m.get("compiles_by_kind")
                if isinstance(cbk, dict):
                    w["compiles_by_kind"] = {
                        str(k): int(v)
                        for k, v in cbk.items()
                        if isinstance(v, int)
                    }
                sbc = m.get("stalls_by_cause")
                if isinstance(sbc, dict):
                    w["stalls_by_cause"] = {
                        str(k): int(v)
                        for k, v in sbc.items()
                        if isinstance(v, int)
                    }
                st = role_stats.setdefault(
                    role,
                    {"workers": 0, "kv_usage": [], "mfu": [],
                     "tokens_per_s": 0.0, "preemptions": 0,
                     "spec_drafted": 0, "spec_accepted": 0,
                     "spec_rate_num": 0.0, "spec_rate_den": 0,
                     "compiles_by_kind": {}},
                )
                st["workers"] += 1
                if "kv_usage" in w:
                    st["kv_usage"].append(float(w["kv_usage"]))
                if "mfu" in w:
                    st["mfu"].append(float(w["mfu"]))
                st["tokens_per_s"] += float(w.get("tokens_per_s", 0.0))
                st["preemptions"] += int(w.get("preemptions", 0))
                st["spec_drafted"] += int(w.get("spec_drafted", 0))
                st["spec_accepted"] += int(w.get("spec_accepted", 0))
                # the LIVE per-role rate is the drafted-weighted mean of
                # the workers' ~60 s windowed rates (== the true windowed
                # accepted/drafted ratio), NOT the lifetime ratio — a
                # draft that degrades must move this gauge within the
                # window, and an actively-failing draft (rate 0, window
                # drafted > 0) must weigh it down rather than vanish
                wd = int(w.get("spec_window_drafted", 0) or 0)
                if wd > 0:
                    st["spec_rate_num"] += (
                        float(w.get("spec_accept_rate", 0.0) or 0.0) * wd
                    )
                    st["spec_rate_den"] += wd
                for k, v in w.get("compiles_by_kind", {}).items():
                    st["compiles_by_kind"][k] = (
                        st["compiles_by_kind"].get(k, 0) + v
                    )
                # None marks a family ABSENT from this frame (the worker
                # drops a key it failed to build, a garbage wire merges
                # to zero sources) — _fold_departed must tell that apart
                # from a genuine counter reset, or the fold+restore cycle
                # double-counts the monotonic fleet families
                slo_counts = None
                wire = m.get("slo")
                if isinstance(wire, dict):
                    one = slo_mod.merge_trackers([wire])
                    if one.sources:
                        w["slo"] = one.to_snapshot()
                        wires_by_role.setdefault(role, []).append(wire)
                        slo_counts = (
                            one.requests_total, one.within_sla_total,
                            one.tokens_total, one.goodput_tokens_total,
                        )
                contribs[iid] = (
                    role,
                    {
                        "preemptions": (
                            None if m.get("preemptions") is None
                            else int(w.get("preemptions", 0) or 0)
                        ),
                        "spec": (
                            None if m.get("spec_drafted") is None
                            else (
                                int(w.get("spec_drafted", 0) or 0),
                                int(w.get("spec_accepted", 0) or 0),
                            )
                        ),
                        "compiles": (
                            dict(w["compiles_by_kind"])
                            if isinstance(w.get("compiles_by_kind"), dict)
                            else None
                        ),
                        "slo": slo_counts,
                    },
                )
                workers[iid] = w
            except Exception:
                logger.warning(
                    "skipping malformed worker frame from %s", iid,
                    exc_info=True,
                )
        self._fold_departed(snap, contribs)
        role_merged = {
            role: slo_mod.merge_trackers(wires)
            for role, wires in wires_by_role.items()
        }
        all_wires = [w for ws in wires_by_role.values() for w in ws]
        roles: dict[str, dict] = {}
        for role, st in sorted(role_stats.items()):
            roles[role] = {
                "workers": st["workers"],
                "kv_usage": (
                    round(sum(st["kv_usage"]) / len(st["kv_usage"]), 4)
                    if st["kv_usage"]
                    else None
                ),
                "mfu": (
                    round(sum(st["mfu"]) / len(st["mfu"]), 6)
                    if st["mfu"]
                    else None
                ),
                "tokens_per_s": round(st["tokens_per_s"], 2),
                "preemptions": st["preemptions"],
                "spec_drafted": st["spec_drafted"],
                "spec_accepted": st["spec_accepted"],
                "spec_accept_rate": (
                    round(st["spec_rate_num"] / st["spec_rate_den"], 4)
                    if st["spec_rate_den"]
                    else 0.0
                ),
                "compiles_by_kind": st["compiles_by_kind"],
            }
            merged = role_merged.get(role)
            if merged is not None and merged.sources:
                roles[role]["slo"] = merged.to_snapshot()
        fleet = slo_mod.merge_trackers(all_wires)
        doc = {
            "workers": workers,
            "roles": roles,
            "fleet": {
                "workers": len(workers),
                **(
                    {"slo": fleet.to_snapshot()} if fleet.sources else {}
                ),
            },
        }
        doc["control_plane"] = self._control_plane_doc()
        planner = self._planner_doc()
        if planner is not None:
            doc["planner"] = planner
        kv_index = self._kv_index_doc()
        if kv_index is not None:
            doc["kv_index"] = kv_index
        return doc, role_merged, role_stats

    def _fold_departed(self, snap: dict, contribs: dict) -> None:
        """Counter-churn bookkeeping for the fleet exposition. The
        `dynamo_tpu_fleet_*_total` families are sums over live worker
        frames — a worker aging out (or restarting with fresh counters)
        would make them DROP, which Prometheus rate()/increase() reads
        as a counter reset and turns into a phantom spike equal to the
        whole new sum. So: when a worker departs or its counters
        regress, its last-seen contribution moves into a per-role
        monotonic base that _fleet_lines adds back. Also prunes the
        req/s-tok/s rate baselines of departed workers (unbounded
        growth under churn otherwise)."""
        for iid in list(self._rate_state):
            if iid not in snap:
                del self._rate_state[iid]
        # a worker RETURNING after aging out: if its counters carried on
        # from where the fold left them (>= the folded contribution in
        # every present family), the gap was a transient publish outage,
        # not a restart — un-fold the ghost so the monotonic fleet
        # families don't count its history twice. A genuinely regressed
        # family means a restart: the fold stays (the new counters are a
        # fresh life).
        for iid in list(self._ghost_contrib):
            cur = contribs.get(iid)
            if cur is None:
                continue
            role, ghost = self._ghost_contrib.pop(iid)
            c = cur[1]
            unfold = {
                "preemptions": 0, "spec": None, "compiles": {}, "slo": None,
            }
            if ghost.get("preemptions") is not None and (
                c.get("preemptions") or 0
            ) >= ghost["preemptions"]:
                unfold["preemptions"] = ghost["preemptions"]
            if ghost.get("spec") is not None and all(
                x >= p
                for x, p in zip(c.get("spec") or (0, 0), ghost["spec"])
            ):
                unfold["spec"] = ghost["spec"]
            if ghost.get("compiles") is not None and all(
                (c.get("compiles") or {}).get(k, 0) >= v
                for k, v in ghost["compiles"].items()
            ):
                unfold["compiles"] = ghost["compiles"]
            if ghost.get("slo") is not None and all(
                x >= p
                for x, p in zip(c.get("slo") or (0, 0, 0, 0), ghost["slo"])
            ):
                unfold["slo"] = ghost["slo"]
            self._unfold_retired(role, unfold)
        for iid, (role, prev) in list(self._live_contrib.items()):
            cur = contribs.get(iid)
            if cur is None:
                # malformed-this-pass frames (iid still in snap) keep
                # their old contribution until they truly age out
                if iid not in snap:
                    self._fold_retired(role, prev)
                    # fleet event timeline: an UNANNOUNCED disappearance
                    # is exactly what an incident reconstruction looks
                    # for. A worker whose last frame said draining/
                    # handover already put its own event on the timeline
                    # — a planned wind-down must not cry worker_lost.
                    last_state = self._last_state.pop(iid, "serving")
                    if last_state not in ("draining", "handover"):
                        self.events.add({
                            "type": "worker_lost", "severity": "warning",
                            "source": iid, "attrs": {"role": role},
                        })
                    self._ghost_contrib[iid] = (role, prev)
                    while len(self._ghost_contrib) > 1024:
                        self._ghost_contrib.pop(
                            next(iter(self._ghost_contrib))
                        )
                    del self._live_contrib[iid]
                continue
            c = cur[1]
            # a family ABSENT from this frame (None) keeps its previous
            # contribution — absence is a dropped key on the worker or a
            # garbage wire, not a counter reset; treating it as zero
            # would fold prev now and re-add it from the next healthy
            # frame, permanently double-counting the monotonic families
            for fam in ("preemptions", "spec", "compiles", "slo"):
                if c.get(fam) is None:
                    c[fam] = prev.get(fam)
            # fold ONLY the families that actually regressed (reset on a
            # worker restart) — a regression in one never implies the
            # others reset too
            folded = {
                "preemptions": 0, "spec": None, "compiles": {},
                "slo": None,
            }
            any_folded = False
            if (
                prev["preemptions"] is not None
                and (c["preemptions"] or 0) < prev["preemptions"]
            ):
                folded["preemptions"] = prev["preemptions"]
                any_folded = True
            if prev.get("spec") is not None and any(
                x < p
                for x, p in zip(c.get("spec") or (0, 0), prev["spec"])
            ):
                folded["spec"] = prev["spec"]
                any_folded = True
            if prev["compiles"] is not None and any(
                (c["compiles"] or {}).get(k, 0) < v
                for k, v in prev["compiles"].items()
            ):
                folded["compiles"] = prev["compiles"]
                any_folded = True
            if prev["slo"] is not None and any(
                x < p for x, p in zip(c["slo"] or (0, 0, 0, 0), prev["slo"])
            ):
                folded["slo"] = prev["slo"]
                any_folded = True
            if any_folded:
                self._fold_retired(role, folded)
        self._live_contrib.update(contribs)

    def _unfold_retired(self, role: str, contrib: dict) -> None:
        """Subtract a returned worker's folded contribution back out of
        the per-role monotonic base (floored at 0: the base must never
        make a fleet counter regress)."""
        base = self._retired_counters.get(role)
        if base is None:
            return
        base["preemptions"] = max(
            0, base["preemptions"] - (contrib.get("preemptions") or 0)
        )
        base["spec"] = [
            max(0, a - b)
            for a, b in zip(
                base.get("spec", [0, 0]), contrib.get("spec") or (0, 0)
            )
        ]
        for k, v in (contrib.get("compiles") or {}).items():
            if k in base["compiles"]:
                base["compiles"][k] = max(0, base["compiles"][k] - v)
        base["slo"] = [
            max(0, a - b)
            for a, b in zip(base["slo"], contrib.get("slo") or (0, 0, 0, 0))
        ]

    def _fold_retired(self, role: str, contrib: dict) -> None:
        base = self._retired_counters.setdefault(
            role,
            {"preemptions": 0, "spec": [0, 0], "compiles": {},
             "slo": [0, 0, 0, 0]},
        )
        base["preemptions"] += contrib["preemptions"] or 0
        base["spec"] = [
            a + b
            for a, b in zip(
                base.get("spec", [0, 0]), contrib.get("spec") or (0, 0)
            )
        ]
        for k, v in (contrib["compiles"] or {}).items():
            base["compiles"][k] = base["compiles"].get(k, 0) + v
        base["slo"] = [
            a + b
            for a, b in zip(base["slo"], contrib["slo"] or (0, 0, 0, 0))
        ]

    def fleet_snapshot(self) -> dict:
        return self._assemble_fleet()[0]

    def _fleet_lines(self, assembled=None) -> list[str]:
        """`dynamo_tpu_fleet_*{role=...}` exposition: per-role worker
        counts, merged SLO percentiles / attainment / burn rates /
        goodput, mean utilization, and folded engine-internals counters.
        Counter families include the retired-worker bases so they stay
        monotonic across worker churn (the /v1/fleet JSON deliberately
        does not — it describes the live fleet at this instant)."""
        import dataclasses

        from dynamo_tpu.telemetry import slo as slo_mod

        _, role_merged, role_stats = assembled or self._assemble_fleet()
        retired = self._retired_counters
        lines: list[str] = []
        if role_stats:
            lines.append(f"# TYPE {PREFIX}_fleet_workers gauge")
            for role, st in sorted(role_stats.items()):
                lines.append(
                    f'{PREFIX}_fleet_workers{{role="{role}"}} '
                    f'{st["workers"]}'
                )
            for field, ptype, pick in (
                ("kv_usage", "gauge",
                 lambda role, st: (
                     sum(st["kv_usage"]) / len(st["kv_usage"])
                     if st["kv_usage"] else None
                 )),
                ("mfu", "gauge",
                 lambda role, st: (
                     sum(st["mfu"]) / len(st["mfu"])
                     if st["mfu"] else None
                 )),
                ("tokens_per_s", "gauge",
                 lambda role, st: st["tokens_per_s"]),
                ("preemptions_total", "counter",
                 lambda role, st: (
                     st["preemptions"]
                     + retired.get(role, {}).get("preemptions", 0)
                 )),
                # speculation: drafted/accepted counters stay monotonic
                # across worker churn like preemptions; the rate gauge
                # is the LIVE fleet ratio (live workers only)
                ("spec_drafted_total", "counter",
                 lambda role, st: (
                     st.get("spec_drafted", 0)
                     + retired.get(role, {}).get("spec", [0, 0])[0]
                 )),
                ("spec_accepted_total", "counter",
                 lambda role, st: (
                     st.get("spec_accepted", 0)
                     + retired.get(role, {}).get("spec", [0, 0])[1]
                 )),
                # windowed drafted-weighted mean, NOT the lifetime ratio
                # (which would stop moving after hours of serving)
                ("spec_accept_rate", "gauge",
                 lambda role, st: (
                     st["spec_rate_num"] / st["spec_rate_den"]
                     if st.get("spec_rate_den")
                     else 0.0
                 )),
            ):
                vals = [
                    (role, pick(role, st))
                    for role, st in sorted(role_stats.items())
                ]
                vals = [(r, v) for r, v in vals if v is not None]
                if not vals:
                    continue
                lines.append(f"# TYPE {PREFIX}_fleet_{field} {ptype}")
                for role, v in vals:
                    lines.append(
                        f'{PREFIX}_fleet_{field}{{role="{role}"}} '
                        f"{round(v, 6)}"
                    )
            kind_totals: dict[str, dict] = {}
            for role, st in role_stats.items():
                kt = dict(st["compiles_by_kind"])
                for k, v in retired.get(role, {}).get("compiles", {}).items():
                    kt[k] = kt.get(k, 0) + v
                kind_totals[role] = kt
            kind_samples = [
                (role, k, v)
                for role in sorted(role_stats)
                for k, v in sorted(kind_totals[role].items())
            ]
            if kind_samples:
                lines.append(f"# TYPE {PREFIX}_fleet_compile_total counter")
                for role, k, v in kind_samples:
                    lines.append(
                        f'{PREFIX}_fleet_compile_total{{role="{role}",'
                        f'kind="{k}"}} {v}'
                    )
        scopes = []
        for role, merged in sorted(role_merged.items()):
            b = retired.get(role, {}).get("slo")
            if b and any(b):
                merged = dataclasses.replace(
                    merged,
                    requests_total=merged.requests_total + b[0],
                    within_sla_total=merged.within_sla_total + b[1],
                    tokens_total=merged.tokens_total + b[2],
                    goodput_tokens_total=merged.goodput_tokens_total + b[3],
                )
            scopes.append((f'role="{role}"', merged))
        lines += slo_mod.expose_lines(f"{PREFIX}_fleet", scopes)
        return lines

    def expose(self, openmetrics: bool = False) -> str:
        """Classic Prometheus text by default; `openmetrics=True` is
        the negotiated rendering (OpenMetrics counter naming, `# EOF`,
        phase-histogram exemplars — classic parsers reject exemplar
        syntax, so it never rides the text/plain surface)."""
        snap3 = self._snapshot_all()
        assembled = self._assemble_fleet(snap3)
        counts: dict[str, int] = {self.component: 0}
        for _, (_, _, comp) in snap3.items():
            counts[comp] = counts.get(comp, 0) + 1
        lines = [f"# TYPE {PREFIX}_live_workers gauge"]
        for comp, n in sorted(counts.items()):
            lines.append(
                f'{PREFIX}_live_workers{{component="{comp}"}} {n}'
            )
        for field, ptype in _WORKER_FIELDS:
            name = f"{PREFIX}_worker_{field}"
            if ptype == "counter" and not field.endswith("_total"):
                name += "_total"
            lines.append(f"# TYPE {name} {ptype}")
            for iid, (m, _, comp) in sorted(snap3.items()):
                if field in m and isinstance(m[field], (int, float)):
                    lines.append(
                        f'{name}{{component="{comp}",'
                        f'instance="{iid}"}} {m[field]}'
                    )
        lines += [
            f"# TYPE {PREFIX}_kv_hit_rate_events_total counter",
            f"{PREFIX}_kv_hit_rate_events_total {self.hit_events}",
            f"# TYPE {PREFIX}_kv_hit_rate_isl_tokens_total counter",
            f"{PREFIX}_kv_hit_rate_isl_tokens_total {self.isl_tokens_total}",
            f"# TYPE {PREFIX}_kv_hit_rate_overlap_tokens_total counter",
            f"{PREFIX}_kv_hit_rate_overlap_tokens_total {self.overlap_tokens_total}",
            f"# TYPE {PREFIX}_kv_hit_rate gauge",
            f"{PREFIX}_kv_hit_rate "
            f"{self.overlap_tokens_total / self.isl_tokens_total if self.isl_tokens_total else 0.0}",
        ]
        lines += self._fabric_lines()
        lines += self._fleet_lines(assembled)
        lines += self._planner_lines()
        lines += self._kv_index_lines()
        # fleet trace plane: assembly/sampling counters + the event-
        # timeline counter family the Grafana annotation layer queries
        lines += self.traces.expose_lines(PREFIX)
        lines += self.events.expose_lines(PREFIX)
        # process-global speculation counters (in-process engines; the
        # per-worker fleet view is dynamo_tpu_worker_spec_* above) —
        # the same families FrontendMetrics exposes, both surfaces
        from dynamo_tpu.telemetry import debug as _debug

        lines += _debug.spec_lines(PREFIX)
        # on-device K-step decode windows — same both-surfaces contract
        lines += _debug.kstep_lines(PREFIX)
        # data-integrity rejections (disk-tier checksum misses, corrupt
        # transfer frames) — same both-surfaces contract as spec_lines
        lines += _debug.integrity_lines(PREFIX)
        # control-plane HA: this process's broker-connection state
        # (degraded gauge, outage counters, client-observed failovers)
        # — docs/operations.md "Control-plane HA"
        lines += _debug.control_plane_lines(PREFIX)
        # process-global KV index health (zeros here — this process hosts
        # no router; the per-component fleet view is
        # dynamo_tpu_router_kv_index_* above) — both-surfaces contract
        lines += _debug.kv_index_lines(PREFIX)
        # process-global HBM accounting (zeros here — no engine in this
        # process; the per-worker fleet view is the
        # dynamo_tpu_worker_hbm_* families above) — both-surfaces
        # contract
        lines += _debug.hbm_lines(PREFIX)
        # host-skew straggler gauge: per-host max of the workers'
        # flight-window dispatch p95, grouped by the frames' `host`
        # (jax.process_index()). Under lockstep SPMD one slow host drags
        # every dispatch — this family makes WHICH host visible. The
        # zeroed {host="0"} default keeps the family present for the
        # Grafana panel-vs-emitted-names gate
        skew: dict[str, float] = {}
        for _, (m, _, _) in sorted(snap3.items()):
            p95 = m.get("dispatch_p95_ms")
            if not isinstance(p95, (int, float)):
                continue
            h = str(int(m.get("host", 0) or 0))
            skew[h] = max(skew.get(h, 0.0), float(p95))
        lines.append(f"# TYPE {PREFIX}_fleet_host_dispatch_p95_ms gauge")
        for h, v in sorted(skew.items()) or [("0", 0.0)]:
            lines.append(
                f'{PREFIX}_fleet_host_dispatch_p95_ms{{host="{h}"}} {v}'
            )
        # per-phase latency histograms (telemetry plane, process-global)
        from dynamo_tpu.telemetry import phases

        lines += phases.expose_lines(exemplars=openmetrics)
        # stall-watchdog counters (process-global, usually empty here —
        # the per-worker view is dynamo_tpu_worker_stalls_total above)
        from dynamo_tpu.telemetry.watchdog import stall_counters

        lines += stall_counters.expose_lines()
        text = "\n".join(lines) + "\n"
        if openmetrics:
            from dynamo_tpu.telemetry.openmetrics import to_openmetrics

            return to_openmetrics(text)
        return text

    async def _metrics(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry import openmetrics

        if openmetrics.negotiate(request.headers.get("Accept")):
            return web.Response(
                text=self.expose(openmetrics=True),
                content_type=openmetrics.CONTENT_TYPE,
                charset="utf-8",
            )
        return web.Response(
            text=self.expose(), content_type="text/plain", charset="utf-8"
        )

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "workers": len(self.aggregator.snapshot())}
        )

    async def _fleet(self, request: web.Request) -> web.Response:
        """The queryable fleet snapshot: per-worker role / rates /
        engine internals / SLO percentiles + per-role and fleet-wide
        merged views (scripts/fleet_top.py renders this)."""
        return web.json_response(self.fleet_snapshot())

    async def _traces(self, request: web.Request) -> web.Response:
        """GET /v1/traces — the fleet trace SEARCH API over assembled,
        tail-sampled traces: ?min_ms= &status= &worker= &endpoint=
        &since= &sort=recent|duration &limit=N. (The per-process rings
        still serve the same path on each frontend/worker; this surface
        is the cross-process one.)"""
        q = request.query
        try:
            kwargs = {
                "min_ms": float(q["min_ms"]) if "min_ms" in q else None,
                "status": q.get("status"),
                "worker": q.get("worker"),
                "endpoint": q.get("endpoint"),
                "since": float(q["since"]) if "since" in q else None,
                "sort": q.get("sort", "recent"),
                "limit": int(q.get("limit", "50")),
            }
        except (TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"bad query parameter: {e}"}, status=400
            )
        if kwargs["sort"] not in ("recent", "duration"):
            return web.json_response(
                {"error": "sort must be recent|duration"}, status=400
            )
        return web.json_response(
            {
                "traces": self.traces.search(**kwargs),
                "stats": self.traces.stats(),
                "sample_rate": self.trace_sampler.healthy_rate,
            }
        )

    async def _trace(self, request: web.Request) -> web.Response:
        """GET /v1/traces/{id}[?format=chrome] — one ASSEMBLED trace:
        spans from every process, the timeline breakdown, and the fleet
        events that overlapped its window."""
        tid = request.match_info["trace_id"]
        doc = self.traces.get(tid)
        if doc is None:
            return web.json_response(
                {"error": f"trace {tid!r} not found"}, status=404
            )
        if request.query.get("format") == "chrome":
            from dynamo_tpu.telemetry.chrome_export import to_chrome_trace

            return web.json_response(to_chrome_trace(doc["spans"]))
        summary = doc["summary"]
        t0 = float(summary.get("start_ts") or 0.0)
        dur_ms = float(summary.get("duration_ms") or 0.0)
        doc["events"] = self.events.overlapping(t0, t0 + dur_ms / 1000.0)
        doc["breakdown"] = (summary or {}).get("breakdown")
        return web.json_response(doc)

    async def _fleet_events(self, request: web.Request) -> web.Response:
        """GET /v1/fleet/events — the fleet event timeline:
        ?since=<id> &since_ts=<epoch> &type= &severity= &source=
        &limit=N (newest last; `id` is monotonic, tail with since=)."""
        q = request.query
        try:
            kwargs = {
                "since_id": int(q["since"]) if "since" in q else None,
                "since_ts": (
                    float(q["since_ts"]) if "since_ts" in q else None
                ),
                "etype": q.get("type"),
                "severity": q.get("severity"),
                "source": q.get("source"),
                "limit": int(q.get("limit", "200")),
            }
        except (TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"bad query parameter: {e}"}, status=400
            )
        return web.json_response({"events": self.events.query(**kwargs)})

    # -- debug plane: fleet-wide flight windows + program cost tables ------
    # (the per-worker data rides the metrics frames; docs/observability.md
    # "Debugging a slow or stuck worker")

    async def _debug_flight(self, request: web.Request) -> web.Response:
        from dynamo_tpu.telemetry.debug import parse_window
        from dynamo_tpu.telemetry.flight import tail

        n, err = parse_window(request.query.get("n"))
        if err is not None:
            return web.json_response(err, status=400)

        workers = {}
        for iid, (m, age, comp) in sorted(self._snapshot_all().items()):
            fl = m.get("flight")
            if not isinstance(fl, list):
                continue
            fl = tail(fl, n)
            workers[iid] = {
                "component": comp,
                "last_seen_s": round(age, 3),
                "records": fl,
            }
        return web.json_response({"workers": workers})

    async def _debug_programs(self, request: web.Request) -> web.Response:
        workers = {}
        for iid, (m, age, comp) in sorted(self._snapshot_all().items()):
            pk = m.get("programs_by_kind")
            if not isinstance(pk, dict):
                continue
            workers[iid] = {
                "component": comp,
                "last_seen_s": round(age, 3),
                "kinds": pk,
            }
        return web.json_response({"workers": workers})

    async def _debug_memory(self, request: web.Request) -> web.Response:
        """Fleet-wide HBM accounting: each worker's per-device
        weights/kv_pool/scratch/free/peak byte breakdown, as published
        in its frames (engine.memory_report())."""
        workers = {}
        for iid, (m, age, comp) in sorted(self._snapshot_all().items()):
            mem = m.get("memory")
            if not isinstance(mem, dict):
                continue
            workers[iid] = {
                "component": comp,
                "last_seen_s": round(age, 3),
                **mem,
            }
        return web.json_response({"workers": workers})

    async def _debug_mesh(self, request: web.Request) -> web.Response:
        """Fleet-wide mesh/sharding introspection: each worker's mesh
        shape, per-param-group sharding specs, process_index and
        dispatch timing, as published in its frames
        (engine.mesh_report())."""
        workers = {}
        for iid, (m, age, comp) in sorted(self._snapshot_all().items()):
            mesh = m.get("mesh")
            if not isinstance(mesh, dict):
                continue
            workers[iid] = {
                "component": comp,
                "last_seen_s": round(age, 3),
                **mesh,
            }
        return web.json_response({"workers": workers})

    async def _debug_profile(self, request: web.Request) -> web.Response:
        # the metrics service hosts no engine; the payload layer answers
        # the honest 501 (profile captures must be triggered on the
        # process that owns the device)
        from dynamo_tpu.telemetry.debug import profile_payload

        try:
            body = await request.json()
        except Exception:
            body = {}
        payload, status = profile_payload(body)
        return web.json_response(payload, status=status)

"""Pallas TPU paged-attention decode kernel.

Decode (T=1) attention over the paged KV history. The XLA fallback path
(models/llama.py:paged_attention) gathers the full per-sequence KV history
into a dense [B, K, Hkv, D] array in HBM before the matmuls — 2× the HBM
traffic (read pages, write gather, read gather) plus O(B·MP·S) memory. This
kernel instead walks each sequence's page table and streams pages HBM→VMEM
with double-buffered async DMA, accumulating a flash-style online softmax.
KV bytes are read exactly once, nothing is materialized.

Cache layout is [L, P, S, Hkv, D] (models/llama.py KVPages): one (layer,
page) slice is a contiguous [S, Hkv, D] block, so a single DMA per page
feeds the compute for EVERY kv head — the grid is (B,), one cell per
sequence, with the (small) per-head dots unrolled inside the cell. D is
lane-padded to a 128 multiple (LlamaConfig.kv_head_dim): Mosaic DMA slices
must be 128-aligned in the minor dimension.

The kernel reads HISTORY ONLY (tokens already written to pages — the
current token's KV is staged and written once per step by ops/kv_update).
It returns the UNNORMALIZED accumulator plus the softmax running max and
denominator (m, l), and the caller folds the current token in exactly:

    out = (e^{m-m*}·acc + e^{s_self-m*}·v_cur) / (e^{m-m*}·l + e^{s_self-m*})

Parity: replaces the paged-attention kernels the reference gets from vLLM /
TRT-LLM (engine-delegated, SURVEY.md §2.9); on TPU the engine is first-class
so the kernel lives here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32 — layer of the stacked cache to read
    pt_ref,  # [B, MP] int32 page tables (SMEM)
    len_ref,  # [B] int32 HISTORY lengths (tokens already in the cache)
    # inputs
    q_ref,  # [1, HQ, D] VMEM block (this sequence's queries, unscaled)
    k_ref,  # [L, P, S, Hkv, D] in HBM/ANY
    v_ref,  # [L, P, S, Hkv, D] in HBM/ANY
    # outputs
    acc_ref,  # [1, HQ, D] f32 — UNNORMALIZED flash accumulator
    m_ref,  # [1, HQ, 128] f32 — running max (lane-broadcast)
    l_ref,  # [1, HQ, 128] f32 — running denominator (lane-broadcast)
    # scratch
    k_scr,  # [2, S, Hkv, D] VMEM
    v_scr,  # [2, S, Hkv, D] VMEM
    sem,  # [2, 2] DMA semaphores: [k|v, slot]
    *,
    page_size: int,
    scale_dim: int,
    num_kv_heads: int,
):
    b = pl.program_id(0)
    li = layer_ref[0]
    hq, d = q_ref.shape[1], q_ref.shape[2]
    g = hq // num_kv_heads
    s = page_size
    hist = len_ref[b]
    used = pl.cdiv(hist, s)  # pages the history actually occupies

    def k_copy(slot, i):
        return pltpu.make_async_copy(
            k_ref.at[li, pt_ref[b, i]], k_scr.at[slot], sem.at[0, slot]
        )

    def v_copy(slot, i):
        return pltpu.make_async_copy(
            v_ref.at[li, pt_ref[b, i]], v_scr.at[slot], sem.at[1, slot]
        )

    @pl.when(used > 0)
    def _():
        k_copy(0, 0).start()
        v_copy(0, 0).start()

    # Scale after the f32 cast so bf16 q matches the XLA path bit-for-bit.
    # scale_dim is the model's true head_dim — d may be lane-padded.
    q = q_ref[0].astype(jnp.float32) * (1.0 / math.sqrt(scale_dim))  # [HQ, D]

    def body(i, carry):
        ms, ls, accs = carry  # per-head tuples: [G,1], [G,1], [G,D]
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < used)
        def _():
            k_copy(1 - slot, i + 1).start()
            v_copy(1 - slot, i + 1).start()

        k_copy(slot, i).wait()
        v_copy(slot, i).wait()

        kp = k_scr[slot].astype(jnp.float32)  # [S, Hkv, D]
        vp = v_scr[slot].astype(jnp.float32)
        key_pos = i * s + jax.lax.broadcasted_iota(jnp.int32, (g, s), 1)
        key_mask = key_pos < hist  # [G, S]

        # One DMA fed all heads; the per-head dots are small but the page
        # walk is DMA-bound, so their latency hides under the next copy.
        m_out, l_out, a_out = [], [], []
        for h in range(num_kv_heads):  # static unroll
            qh = q[h * g : (h + 1) * g]  # [G, D]
            scores = jax.lax.dot_general(
                qh, kp[:, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, S]
            scores = jnp.where(key_mask, scores, -1e30)
            m_new = jnp.maximum(ms[h], jnp.max(scores, axis=1, keepdims=True))
            p = jnp.exp(scores - m_new)
            corr = jnp.exp(ms[h] - m_new)
            l_new = ls[h] * corr + jnp.sum(p, axis=1, keepdims=True)
            a_new = accs[h] * corr + jax.lax.dot_general(
                p, vp[:, h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_out.append(m_new)
            l_out.append(l_new)
            a_out.append(a_new)
        return tuple(m_out), tuple(l_out), tuple(a_out)

    init = (
        tuple(
            jnp.full((g, 1), -jnp.inf, jnp.float32)
            for _ in range(num_kv_heads)
        ),
        tuple(jnp.zeros((g, 1), jnp.float32) for _ in range(num_kv_heads)),
        tuple(jnp.zeros((g, d), jnp.float32) for _ in range(num_kv_heads)),
    )
    ms, ls, accs = jax.lax.fori_loop(0, used, body, init)
    acc_ref[0] = jnp.concatenate(accs, axis=0)
    m_ref[0] = jnp.broadcast_to(jnp.concatenate(ms, axis=0), (hq, 128))
    l_ref[0] = jnp.broadcast_to(jnp.concatenate(ls, axis=0), (hq, 128))


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] post-rope decode queries (D may be padded)
    k_cache: jax.Array,  # [L, P, S, Hkv, D] — full stacked cache
    v_cache: jax.Array,  # [L, P, S, Hkv, D]
    layer: jax.Array,  # scalar int32 layer index
    page_tables: jax.Array,  # [B, MP] int32
    history_lens: jax.Array,  # [B] int32 — tokens already written to pages
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """History-only flash attention over the paged cache.

    Returns (acc [B, Hq, D] f32 unnormalized, m [B, Hq] f32, l [B, Hq] f32)
    for the caller to merge the current token (see module docstring).
    A sequence with history_lens == 0 yields acc=0, l=0, m=-inf — the merge
    then reduces to pure self-attention.

    `interpret` defaults to True off-TPU so tests run the same kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        # Heads are embarrassingly parallel: shard_map the kernel over tp
        # (q/outputs on the head axis, caches on the kv-head axis) — each
        # shard walks the same pages for its own heads, no collectives.
        from functools import partial

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        fn = shard_map(
            partial(
                paged_decode_attention,
                scale_dim=scale_dim,
                interpret=interpret,
                mesh=None,
            ),
            mesh=mesh,
            in_specs=(
                P(None, "tp", None),
                P(None, None, None, "tp", None),
                P(None, None, None, "tp", None),
                P(),
                P(),
                P(),
            ),
            out_specs=(P(None, "tp", None), P(None, "tp"), P(None, "tp")),
            check_vma=False,
        )
        return fn(q, k_cache, v_cache, layer, page_tables, history_lens)
    b, hq, d = q.shape
    hkv, s = k_cache.shape[3], k_cache.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hq, d), lambda bi, li, pt, ln: (bi, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, hq, d), lambda bi, li, pt, ln: (bi, 0, 0)),
            pl.BlockSpec((1, hq, 128), lambda bi, li, pt, ln: (bi, 0, 0)),
            pl.BlockSpec((1, hq, 128), lambda bi, li, pt, ln: (bi, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, s, hkv, d), k_cache.dtype),
            pltpu.VMEM((2, s, hkv, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    acc, m, l = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=s,
            scale_dim=scale_dim or d,
            num_kv_heads=hkv,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 128), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        page_tables.astype(jnp.int32),
        history_lens.astype(jnp.int32),
        q,
        k_cache,
        v_cache,
    )
    return acc, m[:, :, 0], l[:, :, 0]

"""Pallas TPU paged-attention decode kernel.

Decode (T=1) attention against the paged KV cache. The XLA fallback path
(models/llama.py:paged_attention) gathers the full per-sequence KV history
into a dense [B, K, Hkv, D] array in HBM before the matmuls — 2× the HBM
traffic (read pages, write gather, read gather) plus O(B·MP·S) memory. This
kernel instead walks each sequence's page table and streams pages HBM→VMEM
with double-buffered async DMA, accumulating a flash-style online softmax.
KV bytes are read exactly once, nothing is materialized.

Cache layout is [Hkv, P, S, D] per layer (models/llama.py KVPages), so one
(head, page) slice is a contiguous [S, D] block — a single dense DMA
descriptor per page.

Grid: (B, Hkv) — one cell per (sequence, kv-head); the q-head group G=Hq/Hkv
rides the sublane dim. Decode attention is HBM-bandwidth-bound, so the tiny
per-cell matmuls ([G,S]·[S,D]) are irrelevant; the DMA pipeline is the point.

Parity: replaces the paged-attention kernels the reference gets from vLLM /
TRT-LLM (engine-delegated, SURVEY.md §2.9); on TPU the engine is first-class
so the kernel lives here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32 — which layer of the stacked cache to read
    pt_ref,  # [B, MP] int32 page tables (SMEM)
    len_ref,  # [B] int32 kv lengths, incl. the token being decoded (SMEM)
    # inputs
    q_ref,  # [1, 1, G, D] VMEM block (this cell's q-head group, pre-scaled)
    k_ref,  # [L, Hkv, P, S, D] in HBM/ANY — the full stacked cache
    v_ref,  # [L, Hkv, P, S, D] in HBM/ANY
    # output
    o_ref,  # [1, 1, G, D] VMEM block
    # scratch
    k_scr,  # [2, S, D] VMEM
    v_scr,  # [2, S, D] VMEM
    sem,  # [2, 2] DMA semaphores: [k|v, slot]
    *,
    page_size: int,
    scale_dim: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    li = layer_ref[0]
    g, d = q_ref.shape[2], q_ref.shape[3]
    s = page_size
    seq_len = len_ref[b]
    used = pl.cdiv(seq_len, s)  # pages this sequence actually occupies

    def k_copy(slot, i):
        return pltpu.make_async_copy(
            k_ref.at[li, h, pt_ref[b, i]], k_scr.at[slot], sem.at[0, slot]
        )

    def v_copy(slot, i):
        return pltpu.make_async_copy(
            v_ref.at[li, h, pt_ref[b, i]], v_scr.at[slot], sem.at[1, slot]
        )

    # Warm up the pipeline (seq_len >= 1 always: the decoded token itself).
    k_copy(0, 0).start()
    v_copy(0, 0).start()

    # Scale after the f32 cast so bf16 q matches the XLA path bit-for-bit.
    # scale_dim is the model's true head_dim — d may be lane-padded.
    q = q_ref[0, 0].astype(jnp.float32) * (1.0 / math.sqrt(scale_dim))  # [G, D]

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < used)
        def _():
            k_copy(1 - slot, i + 1).start()
            v_copy(1 - slot, i + 1).start()

        k_copy(slot, i).wait()
        v_copy(slot, i).wait()

        k = k_scr[slot].astype(jnp.float32)  # [S, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [G, S]
        key_pos = i * s + jax.lax.broadcasted_iota(jnp.int32, (g, s), 1)
        scores = jnp.where(key_pos < seq_len, scores, -1e30)

        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)  # [G, S]
        corr = jnp.exp(m - m_new)  # [G, 1]
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_scr[slot].astype(jnp.float32)  # [S, D]
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    a0 = jnp.zeros((g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, used, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] post-rope decode queries
    k_cache: jax.Array,  # [L, Hkv, P, S, D] — full stacked cache
    v_cache: jax.Array,  # [L, Hkv, P, S, D]
    layer: jax.Array,  # scalar int32 layer index
    page_tables: jax.Array,  # [B, MP] int32
    seq_lens: jax.Array,  # [B] int32 — kv length incl. the decoded token
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Returns [B, Hq*D] attention output, matching the XLA paged path.

    Takes the full layer-stacked cache plus a (traced) layer index so the
    layer scan can carry the cache without slicing it — a dynamic slice of
    one layer would materialize a copy per layer per step; the kernel
    instead offsets its page DMAs by the prefetched index.

    `scale_dim` is the softmax scale's head_dim — pass the model's true
    head_dim when q/k/v are lane-padded to a 128 multiple (cfg.kv_head_dim).
    `interpret` defaults to True off-TPU so tests run the same kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, hq, d = q.shape
    hkv, s = k_cache.shape[1], k_cache.shape[3]
    g = hq // hkv
    qr = q.reshape(b, hkv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec(
                (1, 1, g, d), lambda bi, hi, li, pt, ln: (bi, hi, 0, 0)
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bi, hi, li, pt, ln: (bi, hi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, s, d), k_cache.dtype),
            pltpu.VMEM((2, s, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, page_size=s, scale_dim=scale_dim or d
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        page_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        qr, k_cache, v_cache,
    )
    return out.reshape(b, hq * d)

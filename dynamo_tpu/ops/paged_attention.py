"""Pallas TPU paged-attention decode kernel.

Decode (T=1) attention over the paged KV history. The XLA fallback path
(models/llama.py:paged_attention) gathers the full per-sequence KV history
into a dense [B, K, Hkv, D] array in HBM before the matmuls — 2× the HBM
traffic (read pages, write gather, read gather) plus O(B·MP·S) memory. This
kernel streams pages HBM→VMEM with multi-buffered async DMA, accumulating a
flash-style online softmax. KV bytes are read exactly once, nothing is
materialized.

The work list is FLATTENED: one kernel invocation (grid=(1,)) walks every
(sequence, page) pair of the batch back to back, so the DMA pipeline stays
full across the whole batch. The round-3 per-sequence-grid design drained
its 2-deep pipeline at every grid-cell boundary — at decode batch 128 that
is 128 pipeline restarts per layer per step, and DMA issue latency (not
bandwidth) dominated the measured 13 ms/token-row vs the ~4 ms HBM
roofline (artifacts/tpu/decode_profile.json). Per-page flash merges are
order-independent (max/rescale/add), so each page read-modify-writes its
sequence's running (m, l, acc) rows in the VMEM outputs directly — no
carried state, no sequence-boundary flushes.

Cache layout is [L, P, S, Hkv, D] (models/llama.py KVPages): one (layer,
page) slice is a contiguous [S, Hkv, D] block, so a single DMA per page
feeds the compute for EVERY kv head. D is lane-padded to a 128 multiple
(LlamaConfig.kv_head_dim): Mosaic DMA slices must be 128-aligned in the
minor dimension.

The kernel reads HISTORY ONLY (tokens already written to pages — the
current token's KV is staged and written once per step by ops/kv_update).
It returns the UNNORMALIZED accumulator plus the softmax running max and
denominator (m, l), and the caller folds the current token in exactly:

    out = (e^{m-m*}·acc + e^{s_self-m*}·v_cur) / (e^{m-m*}·l + e^{s_self-m*})

Parity: replaces the paged-attention kernels the reference gets from vLLM /
TRT-LLM (engine-delegated, SURVEY.md §2.9); on TPU the engine is first-class
so the kernel lives here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: DMA pipeline depth (slots per k/v scratch). 4 hides issue latency well
#: past the 2-deep minimum while costing only 2 extra [S, Hkv, D] buffers.
_DEPTH = 4


def _decode_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32 — layer of the stacked cache to read
    nwork_ref,  # [1] int32 — valid (sequence, page) work items
    order_ref,  # [B*MP] int32 — work item -> b*MP + page ordinal
    page_of_ref,  # [B*MP] int32 — work item -> physical page id
    len_ref,  # [B] int32 HISTORY lengths (tokens already in the cache)
    # then (positional, shape depends on `quantized`):
    #   q_ref,  # [B, HQ, D] VMEM (whole batch's queries, unscaled)
    #   k_ref,  # [L, P, S, Hkv, D] in HBM/ANY (narrow dtype when quantized)
    #   v_ref,
    #   [ks_ref, vs_ref]  # [L, P, S, Hkv] f32 scale planes (quantized)
    # outputs (whole batch resident in VMEM; read-modify-written per page):
    #   acc_ref,  # [B, HQ, D] f32 — UNNORMALIZED flash accumulator
    #   m_ref,  # [B, HQ, 128] f32 — running max (lane-broadcast)
    #   l_ref,  # [B, HQ, 128] f32 — running denominator (lane-broadcast)
    # scratch:
    #   k_scr,  # [DEPTH, S, Hkv, D] VMEM
    #   v_scr,
    #   [ks_scr, vs_scr]  # [DEPTH, S, Hkv] f32 VMEM (quantized)
    #   sem,  # [2 or 4, DEPTH] DMA semaphores: [plane, slot]
    *refs,
    page_size: int,
    scale_dim: int,
    num_kv_heads: int,
    max_pages: int,  # MP — decodes order_ref into (sequence, ordinal)
    quantized: bool,
):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, acc_ref, m_ref, l_ref,
         k_scr, v_scr, ks_scr, vs_scr, sem) = refs
    else:
        (q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
         k_scr, v_scr, sem) = refs
        ks_ref = vs_ref = ks_scr = vs_scr = None
    li = layer_ref[0]
    n = nwork_ref[0]
    hq, d = q_ref.shape[1], q_ref.shape[2]
    g = hq // num_kv_heads
    s = page_size
    inv_scale = 1.0 / math.sqrt(scale_dim)

    # Rows never visited (zero history) must read as the empty-history
    # state the caller's merge expects: acc=0, m=-inf, l=0.
    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
    l_ref[...] = jnp.zeros_like(l_ref)

    # one DMA plane per (cache/scale, slot); scale planes ride the same
    # pipeline as their pages — a page and its scales land together
    planes = [(k_ref, k_scr), (v_ref, v_scr)]
    if quantized:
        planes += [(ks_ref, ks_scr), (vs_ref, vs_scr)]

    def copies(slot, j):
        return tuple(
            pltpu.make_async_copy(
                src.at[li, page_of_ref[j]], dst.at[slot], sem.at[pi, slot]
            )
            for pi, (src, dst) in enumerate(planes)
        )

    # prime the pipeline: DEPTH-1 transfers in flight before compute starts
    for p in range(_DEPTH - 1):
        @pl.when(p < n)
        def _(p=p):
            for c in copies(p, p):
                c.start()

    def body(j, _):
        slot = jax.lax.rem(j, _DEPTH)

        @pl.when(j + _DEPTH - 1 < n)
        def _():
            nslot = jax.lax.rem(j + _DEPTH - 1, _DEPTH)
            for c in copies(nslot, j + _DEPTH - 1):
                c.start()

        for c in copies(slot, j):
            c.wait()

        oj = order_ref[j]
        bj = oj // max_pages
        hist = len_ref[bj]
        q = q_ref[bj].astype(jnp.float32) * inv_scale  # [HQ, D]
        kp = k_scr[slot].astype(jnp.float32)  # [S, Hkv, D]
        vp = v_scr[slot].astype(jnp.float32)
        if quantized:
            # dequantize in VMEM right after the DMA lands: the f32 rows
            # feed the flash merge directly, so the scale folds into the
            # per-page scores/weights and no fp page ever touches HBM
            kp = kp * ks_scr[slot][..., None]
            vp = vp * vs_scr[slot][..., None]
        key_pos = (oj % max_pages) * s + jax.lax.broadcasted_iota(
            jnp.int32, (g, s), 1
        )
        key_mask = key_pos < hist  # [G, S]

        m_old = m_ref[bj]  # [HQ, 128] (column 0 is the value)
        l_old = l_ref[bj]
        acc_old = acc_ref[bj]  # [HQ, D]

        # One DMA fed all heads; the per-head dots are small but the page
        # walk is DMA-bound, so their latency hides under the next copy.
        m_out, l_out, a_out = [], [], []
        for h in range(num_kv_heads):  # static unroll
            sl = slice(h * g, (h + 1) * g)
            qh = q[sl]  # [G, D]
            ms = m_old[sl, :1]  # [G, 1]
            ls = l_old[sl, :1]
            accs = acc_old[sl]  # [G, D]
            scores = jax.lax.dot_general(
                qh, kp[:, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, S]
            scores = jnp.where(key_mask, scores, -1e30)
            m_new = jnp.maximum(ms, jnp.max(scores, axis=1, keepdims=True))
            p = jnp.exp(scores - m_new)
            corr = jnp.exp(ms - m_new)
            l_new = ls * corr + jnp.sum(p, axis=1, keepdims=True)
            a_new = accs * corr + jax.lax.dot_general(
                p, vp[:, h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_out.append(m_new)
            l_out.append(l_new)
            a_out.append(a_new)
        acc_ref[bj] = jnp.concatenate(a_out, axis=0)
        m_ref[bj] = jnp.broadcast_to(
            jnp.concatenate(m_out, axis=0), (hq, 128)
        )
        l_ref[bj] = jnp.broadcast_to(
            jnp.concatenate(l_out, axis=0), (hq, 128)
        )
        return 0

    jax.lax.fori_loop(0, n, body, 0)


def decode_work_list(
    page_tables: jax.Array,  # [B, MP] int32
    history_lens: jax.Array,  # [B] int32
    page_size: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Compacted (sequence, page) work list for the decode kernel:
    (n_work [1], order [B*MP], page_of [B*MP]) with valid pairs first in
    (b, i) order. `order` encodes both coordinates — the kernel derives
    b = order//MP, i = order%MP with two scalar ops instead of carrying
    two more [B*MP] prefetch arrays through SMEM.

    LAYER-INVARIANT: build it once per decode step and pass it to every
    layer's paged_decode_attention — inside the per-layer scan body XLA
    is not guaranteed to hoist the sort, and re-sorting B*MP elements per
    layer re-adds fixed per-layer overhead the flattened walk exists to
    remove."""
    mp = page_tables.shape[1]
    hist = history_lens.astype(jnp.int32)
    used = -(-hist // page_size)  # cdiv
    valid = jnp.arange(mp, dtype=jnp.int32)[None, :] < used[:, None]
    flat_valid = valid.reshape(-1)
    order = jnp.argsort(~flat_valid, stable=True).astype(jnp.int32)
    page_of = page_tables.reshape(-1).astype(jnp.int32)[order]
    n_work = flat_valid.sum(dtype=jnp.int32).reshape(1)
    return n_work, order, page_of


def decode_vmem_bytes(
    b: int, hq: int, d: int, s: int, hkv: int, itemsize: int,
    quantized: bool = False,
) -> int:
    """Kernel VMEM footprint estimate: whole-batch q + f32 acc/m/l blocks
    plus the DMA scratch and the per-slot f32 k/v cast temporaries
    (`kp`/`vp` in the kernel body — one slot's pages live in f32 while
    its scores/weights compute). Quantized pools add the f32 scale-plane
    scratch (and `itemsize` is the narrow dtype's — the scratch shrinks).
    The caller routes to the XLA gather when this exceeds the budget
    instead of letting Mosaic fail allocation."""
    scale_scratch = 2 * _DEPTH * s * hkv * 4 if quantized else 0
    return (
        b * hq * d * itemsize  # q (itemsize of q ≈ cache dtype or wider)
        + b * hq * d * 4  # acc f32
        + 2 * b * hq * 128 * 4  # m, l f32 (lane-broadcast)
        + 2 * _DEPTH * s * hkv * d * itemsize  # k/v scratch
        + 2 * s * hkv * d * 4  # kp/vp f32 cast of the active slot
        + scale_scratch
    )


def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] post-rope decode queries (D may be padded)
    k_cache: jax.Array,  # [L, P, S, Hkv, D] — full stacked cache
    v_cache: jax.Array,  # [L, P, S, Hkv, D]
    layer: jax.Array,  # scalar int32 layer index
    page_tables: jax.Array,  # [B, MP] int32
    history_lens: jax.Array,  # [B] int32 — tokens already written to pages
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
    mesh=None,
    work_list=None,  # precomputed decode_work_list (layer-invariant)
    k_scale: jax.Array | None = None,  # [L, P, S, Hkv] f32 (quantized pools)
    v_scale: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """History-only flash attention over the paged cache.

    Returns (acc [B, Hq, D] f32 unnormalized, m [B, Hq] f32, l [B, Hq] f32)
    for the caller to merge the current token (see module docstring).
    A sequence with history_lens == 0 yields acc=0, l=0, m=-inf — the merge
    then reduces to pure self-attention.

    With `k_scale`/`v_scale` the cache holds quantized rows; each page's
    scale plane DMAs alongside it and the rows dequantize in VMEM before
    the flash merge.

    `interpret` defaults to True off-TPU so tests run the same kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    hkv, s = k_cache.shape[3], k_cache.shape[2]
    if work_list is None:
        work_list = decode_work_list(page_tables, history_lens, s)
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        # Heads are embarrassingly parallel: shard_map the kernel over tp
        # (q/outputs on the head axis, caches on the kv-head axis) — each
        # shard walks the same pages for its own heads, no collectives.
        # The (replicated) work list rides along so shards don't re-sort.
        from functools import partial

        from dynamo_tpu.platform import get_shard_map

        shard_map = get_shard_map()
        from jax.sharding import PartitionSpec as P

        def sharded(q_, k_, v_, layer_, pt_, hist_, n_, od_, pg_, *scales):
            return paged_decode_attention(
                q_, k_, v_, layer_, pt_, hist_,
                scale_dim=scale_dim, interpret=interpret, mesh=None,
                work_list=(n_, od_, pg_),
                k_scale=scales[0] if scales else None,
                v_scale=scales[1] if scales else None,
            )

        in_specs = [
            P(None, "tp", None),
            P(None, None, None, "tp", None),
            P(None, None, None, "tp", None),
            P(),
            P(),
            P(),
            P(),
            P(),
            P(),
        ]
        args = [q, k_cache, v_cache, layer, page_tables, history_lens,
                *work_list]
        if quantized:
            in_specs += [P(None, None, None, "tp"), P(None, None, None, "tp")]
            args += [k_scale, v_scale]
        fn = shard_map(
            sharded,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(P(None, "tp", None), P(None, "tp"), P(None, "tp")),
            check_vma=False,
        )
        return fn(*args)
    b, hq, d = q.shape
    mp = page_tables.shape[1]
    n_work, order, page_of = work_list

    in_specs = [
        pl.BlockSpec(
            (b, hq, d), lambda i, li, n, od, pg, ln: (0, 0, 0)
        ),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch_shapes = [
        pltpu.VMEM((_DEPTH, s, hkv, d), k_cache.dtype),
        pltpu.VMEM((_DEPTH, s, hkv, d), v_cache.dtype),
    ]
    operands = [q, k_cache, v_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        scratch_shapes += [
            pltpu.VMEM((_DEPTH, s, hkv), jnp.float32),
            pltpu.VMEM((_DEPTH, s, hkv), jnp.float32),
        ]
        operands += [k_scale, v_scale]
    scratch_shapes.append(
        pltpu.SemaphoreType.DMA((4 if quantized else 2, _DEPTH))
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(1,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (b, hq, d), lambda i, li, n, od, pg, ln: (0, 0, 0)
            ),
            pl.BlockSpec(
                (b, hq, 128), lambda i, li, n, od, pg, ln: (0, 0, 0)
            ),
            pl.BlockSpec(
                (b, hq, 128), lambda i, li, n, od, pg, ln: (0, 0, 0)
            ),
        ],
        scratch_shapes=scratch_shapes,
    )
    acc, m, l = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=s,
            scale_dim=scale_dim or d,
            num_kv_heads=hkv,
            max_pages=mp,
            quantized=quantized,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 128), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        n_work,
        order,
        page_of,
        history_lens.astype(jnp.int32),
        *operands,
    )
    return acc, m[:, :, 0], l[:, :, 0]

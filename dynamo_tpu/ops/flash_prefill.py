"""Pallas TPU flash attention for prefill chunks (causal, GQA).

The XLA first-chunk path (models/llama.py:_chunk_only_attention →
paged_attention) materializes fp32 scores [B, Hkv, g, T, T] in HBM — at
the north-star ISL (3000) that is hundreds of MB of score traffic per
layer. This kernel computes the same causal attention with an online
softmax: scores live in VMEM one [BQ·g, BK] tile at a time, K/V stream
through VMEM once, nothing is materialized.

Grid: (B, Hkv, T/BQ) — one cell per (sequence, kv head, query block); the
g query heads sharing a kv head fold into the tile's rows. The causal
frontier prunes key blocks strictly above the diagonal, and a per-sequence
`valid_len` (scalar-prefetched) masks the padding tail, matching the
fallback's semantics (invalid queries produce ignored rows).

Parity note: the reference gets its prefill kernels from vLLM/TRT-LLM
(engine-delegated, SURVEY.md §2.9); here the engine is first-class so the
kernel lives in-tree, next to the decode kernel (ops/paged_attention.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: q/k tile rows; T is padded to a multiple (masked out)
BLOCK = 128


def _prefill_kernel(
    # scalar prefetch
    len_ref,  # [B] int32 valid token counts
    # inputs (VMEM blocks)
    q_ref,  # [1, 1, G, BQ, D]
    k_ref,  # [1, 1, T, D]
    v_ref,  # [1, 1, T, D]
    # output
    o_ref,  # [1, 1, G, BQ, D]
    *,
    scale_dim: int,
    block: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    g, bq, d = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
    valid = len_ref[b]
    scale = 1.0 / math.sqrt(scale_dim)

    q = q_ref[0, 0].astype(jnp.float32).reshape(g * bq, d) * scale
    row_pos = jax.lax.broadcasted_iota(jnp.int32, (g, bq), 1).reshape(
        g * bq
    ) + qi * bq  # absolute query positions, per folded row

    acc0 = jnp.zeros((g * bq, d), jnp.float32)
    m0 = jnp.full((g * bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g * bq,), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        k_blk = jax.lax.dynamic_slice_in_dim(
            k_ref[0, 0], j * block, block, axis=0
        ).astype(jnp.float32)  # [BK, D]
        v_blk = jax.lax.dynamic_slice_in_dim(
            v_ref[0, 0], j * block, block, axis=0
        ).astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G*BQ, BK]
        col_pos = jax.lax.broadcasted_iota(jnp.int32, (1, block), 1) + j * block
        mask = (col_pos <= row_pos[:, None]) & (col_pos < valid)
        s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = alpha[:, None] * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # causal frontier: key blocks 0..qi inclusive (BQ == BK aligned)
    acc, m, l = jax.lax.fori_loop(0, qi + 1, body, (acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]  # masked rows stay finite
    o_ref[0, 0] = out.reshape(g, bq, d).astype(o_ref.dtype)


def flash_prefill_attention(
    q: jax.Array,  # [B, T, Hq, D] post-rope (D may be lane-padded)
    k: jax.Array,  # [B, T, Hkv, D] post-rope
    v: jax.Array,  # [B, T, Hkv, D]
    valid_len: jax.Array,  # [B] int32 — contiguous valid prefix length
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
    mesh=None,
) -> jax.Array:
    """Causal flash attention over one prefill chunk. Returns
    [B, T, Hq, D]; rows at positions >= valid_len are unspecified (the
    engine ignores them, same contract as the XLA fallback).

    `interpret` defaults to True off-TPU so tests run the kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        from functools import partial

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        fn = shard_map(
            partial(
                flash_prefill_attention,
                scale_dim=scale_dim, interpret=interpret, mesh=None,
            ),
            mesh=mesh,
            in_specs=(
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(),
            ),
            out_specs=P(None, None, "tp", None),
            check_vma=False,
        )
        return fn(q, k, v, valid_len)

    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    tp = -(-t // BLOCK) * BLOCK
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    # head-major layouts: q [B, Hkv, G, T, D] (the g heads of a kv group
    # are adjacent because Hq ordering is group-major), k/v [B, Hkv, T, D]
    qh = q.reshape(b, tp, hkv, g, d).transpose(0, 2, 3, 1, 4)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    grid = (b, hkv, tp // BLOCK)
    out = pl.pallas_call(
        functools.partial(
            _prefill_kernel, scale_dim=scale_dim or d, block=BLOCK
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, g, BLOCK, d),
                    lambda bi, hi, qi, ln: (bi, hi, 0, qi, 0),
                ),
                pl.BlockSpec(
                    (1, 1, tp, d), lambda bi, hi, qi, ln: (bi, hi, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, tp, d), lambda bi, hi, qi, ln: (bi, hi, 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, g, BLOCK, d),
                lambda bi, hi, qi, ln: (bi, hi, 0, qi, 0),
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, tp, d), q.dtype),
        interpret=interpret,
    )(valid_len.astype(jnp.int32), qh, kh, vh)
    # back to [B, T, Hq, D]
    out = out.transpose(0, 3, 1, 2, 4)
    return out.reshape(b, tp, hq, d)[:, :t]

"""Pallas TPU flash attention for prefill chunks (causal, GQA).

The XLA first-chunk path (models/llama.py:_chunk_only_attention →
paged_attention) materializes fp32 scores [B, Hkv, g, T, T] in HBM — at
the north-star ISL (3000) that is hundreds of MB of score traffic per
layer. This kernel computes the same causal attention with an online
softmax: scores live in VMEM one [BQ·g, BK] tile at a time, K/V stream
through VMEM once, nothing is materialized.

Grid: (B, Hkv, T/BQ) — one cell per (sequence, kv head, query block); the
g query heads sharing a kv head fold into the tile's rows. The causal
frontier prunes key blocks strictly above the diagonal, and a per-sequence
`valid_len` (scalar-prefetched) masks the padding tail, matching the
fallback's semantics (invalid queries produce ignored rows).

Parity note: the reference gets its prefill kernels from vLLM/TRT-LLM
(engine-delegated, SURVEY.md §2.9); here the engine is first-class so the
kernel lives in-tree, next to the decode kernel (ops/paged_attention.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.platform import tpu_compiler_params

#: q/k tile rows; T is padded to a multiple (masked out)
BLOCK = 128


def _prefill_kernel(
    # scalar prefetch
    len_ref,  # [B] int32 valid token counts
    # inputs (VMEM blocks)
    q_ref,  # [1, 1, G, BQ, D]
    k_ref,  # [1, 1, T, D]
    v_ref,  # [1, 1, T, D]
    # output
    o_ref,  # [1, 1, G, BQ, D]
    *,
    scale_dim: int,
    block: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    g, bq, d = q_ref.shape[2], q_ref.shape[3], q_ref.shape[4]
    valid = len_ref[b]
    scale = 1.0 / math.sqrt(scale_dim)

    q = q_ref[0, 0].astype(jnp.float32).reshape(g * bq, d) * scale
    # absolute query positions per folded row; built 2D via rem — Mosaic
    # cannot lower a (g, bq) -> (g*bq,) cross-lane reshape of an iota
    row_pos = (
        jax.lax.rem(jax.lax.broadcasted_iota(jnp.int32, (g * bq, 1), 0), bq)
        + qi * bq
    )  # [G*BQ, 1]

    acc0 = jnp.zeros((g * bq, d), jnp.float32)
    m0 = jnp.full((g * bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g * bq, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        # ref-sliced with pl.ds: Mosaic lowers dynamic indexing on refs,
        # not lax.dynamic_slice on loaded values
        k_blk = k_ref[0, 0, pl.ds(j * block, block), :].astype(
            jnp.float32
        )  # [BK, D]
        v_blk = v_ref[0, 0, pl.ds(j * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G*BQ, BK]
        col_pos = jax.lax.broadcasted_iota(jnp.int32, (1, block), 1) + j * block
        mask = (col_pos <= row_pos) & (col_pos < valid)
        s = jnp.where(mask, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # causal frontier: key blocks 0..qi inclusive (BQ == BK aligned)
    acc, m, l = jax.lax.fori_loop(0, qi + 1, body, (acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)  # masked rows stay finite
    o_ref[0, 0] = out.reshape(g, bq, d).astype(o_ref.dtype)


def _hist_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32
    pt_ref,  # [B, MP] int32 page tables (SMEM)
    hist_ref,  # [B] int32 — tokens already in the cache (chunk start)
    cur_ref,  # [B] int32 — valid tokens in THIS chunk
    # then (positional, extra scale refs only when `quantized`):
    #   q_ref,  # [1, BQ, HQ, D] VMEM (post-rope, unscaled)
    #   kcur_ref,  # [1, T, Hkv, D] VMEM — this chunk's keys (post-rope)
    #   vcur_ref,  # [1, T, Hkv, D] VMEM
    #   k_hbm,  # [L, P, S, Hkv, D] ANY (narrow dtype when quantized)
    #   v_hbm,
    #   [ks_hbm, vs_hbm]  # [L, P, S, Hkv] f32 scale planes (quantized)
    # output:
    #   o_ref,  # [1, BQ, HQ, D]
    # scratch:
    #   k_scr,  # [2, S, Hkv, D] VMEM
    #   v_scr,
    #   [ks_scr, vs_scr]  # [2, S, Hkv] f32 VMEM (quantized)
    #   sem,  # [2 or 4, 2] DMA semaphores
    *refs,
    page_size: int,
    scale_dim: int,
    num_kv_heads: int,
    quantized: bool,
):
    if quantized:
        (q_ref, kcur_ref, vcur_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         o_ref, k_scr, v_scr, ks_scr, vs_scr, sem) = refs
    else:
        (q_ref, kcur_ref, vcur_ref, k_hbm, v_hbm,
         o_ref, k_scr, v_scr, sem) = refs
        ks_hbm = vs_hbm = ks_scr = vs_scr = None
    b = pl.program_id(0)
    qi = pl.program_id(1)
    li = layer_ref[0]
    bq, hq, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    t = kcur_ref.shape[1]
    g = hq // num_kv_heads
    s = page_size
    hist = hist_ref[b]
    cur = cur_ref[b]
    used = pl.cdiv(hist, s)

    planes = [(k_hbm, k_scr), (v_hbm, v_scr)]
    if quantized:
        planes += [(ks_hbm, ks_scr), (vs_hbm, vs_scr)]

    def copies(slot, i):
        return tuple(
            pltpu.make_async_copy(
                src.at[li, pt_ref[b, i]], dst.at[slot], sem.at[pi, slot]
            )
            for pi, (src, dst) in enumerate(planes)
        )

    @pl.when(used > 0)
    def _():
        for c in copies(0, 0):
            c.start()

    scale = 1.0 / math.sqrt(scale_dim)
    # per-head query tiles [G·BQ, D], group-major like the cache layout
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, HQ, D]

    def qh_tile(h):
        return (
            q[:, h * g : (h + 1) * g]
            .transpose(1, 0, 2)
            .reshape(g * bq, d)
        )

    # -- history pages (every key position < hist: no causal test) --------
    def body(i, carry):
        ms, ls, accs = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < used)
        def _():
            for c in copies(1 - slot, i + 1):
                c.start()

        for c in copies(slot, i):
            c.wait()
        kp = k_scr[slot].astype(jnp.float32)  # [S, Hkv, D]
        vp = v_scr[slot].astype(jnp.float32)
        if quantized:
            # dequant in VMEM right after the page lands (scale folds
            # into this page's slice of the online softmax)
            kp = kp * ks_scr[slot][..., None]
            vp = vp * vs_scr[slot][..., None]
        key_pos = i * s + jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
        key_mask = key_pos < hist  # [1, S] — the last page may be partial

        m_out, l_out, a_out = [], [], []
        for h in range(num_kv_heads):  # static unroll
            scores = jax.lax.dot_general(
                qh_tile(h), kp[:, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G·BQ, S]
            scores = jnp.where(key_mask, scores, -1e30)
            m_new = jnp.maximum(
                ms[h], jnp.max(scores, axis=1, keepdims=True)
            )
            p = jnp.exp(scores - m_new)
            corr = jnp.exp(ms[h] - m_new)
            l_new = ls[h] * corr + jnp.sum(p, axis=1, keepdims=True)
            a_new = accs[h] * corr + jax.lax.dot_general(
                p, vp[:, h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_out.append(m_new)
            l_out.append(l_new)
            a_out.append(a_new)
        return tuple(m_out), tuple(l_out), tuple(a_out)

    init = (
        tuple(
            jnp.full((g * bq, 1), -jnp.inf, jnp.float32)
            for _ in range(num_kv_heads)
        ),
        tuple(jnp.zeros((g * bq, 1), jnp.float32) for _ in range(num_kv_heads)),
        tuple(jnp.zeros((g * bq, d), jnp.float32) for _ in range(num_kv_heads)),
    )
    ms, ls, accs = jax.lax.fori_loop(0, used, body, init)

    # -- the current chunk (causal within the chunk, padding masked) -------
    # Key blocks strictly above the causal diagonal are pruned: block j
    # only matters for q block qi when j <= qi (BQ-aligned), mirroring
    # _prefill_kernel's frontier loop.
    # [G*BQ, 1], built via rem (see _prefill_kernel's row_pos note)
    row_rel = qi * bq + jax.lax.rem(
        jax.lax.broadcasted_iota(jnp.int32, (g * bq, 1), 0), bq
    )

    def cur_body(j, carry):
        ms, ls, accs = carry
        col_rel = j * bq + jax.lax.broadcasted_iota(jnp.int32, (1, bq), 1)
        cmask = (col_rel <= row_rel) & (col_rel < cur)  # [G·BQ, BQ]
        m_out, l_out, a_out = [], [], []
        for h in range(num_kv_heads):
            kc = kcur_ref[0, pl.ds(j * bq, bq), h, :].astype(
                jnp.float32
            )  # [BQ, D]
            vc = vcur_ref[0, pl.ds(j * bq, bq), h, :].astype(jnp.float32)
            scores = jax.lax.dot_general(
                qh_tile(h), kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G·BQ, BQ]
            scores = jnp.where(cmask, scores, -1e30)
            m_new = jnp.maximum(
                ms[h], jnp.max(scores, axis=1, keepdims=True)
            )
            p = jnp.exp(scores - m_new)
            corr = jnp.exp(ms[h] - m_new)
            l_new = ls[h] * corr + jnp.sum(p, axis=1, keepdims=True)
            a_new = accs[h] * corr + jax.lax.dot_general(
                p, vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_out.append(m_new)
            l_out.append(l_new)
            a_out.append(a_new)
        return tuple(m_out), tuple(l_out), tuple(a_out)

    ms, ls, accs = jax.lax.fori_loop(0, qi + 1, cur_body, (ms, ls, accs))
    outs = []
    for h in range(num_kv_heads):
        out = accs[h] / jnp.maximum(ls[h], 1e-30)  # [G·BQ, D]
        outs.append(out.reshape(g, bq, d))
    # [HQ(group-major), BQ, D] -> [BQ, HQ, D]
    o_ref[0] = (
        jnp.concatenate(outs, axis=0).transpose(1, 0, 2).astype(o_ref.dtype)
    )


def paged_prefill_attention(
    q: jax.Array,  # [B, T, Hq, D] post-rope chunk queries (D lane-padded)
    k_cur: jax.Array,  # [B, T, Hkv, D] this chunk's keys (post-rope)
    v_cur: jax.Array,  # [B, T, Hkv, D]
    k_cache: jax.Array,  # [L, P, S, Hkv, D] stacked cache (history)
    v_cache: jax.Array,
    layer: jax.Array,  # scalar int32
    page_tables: jax.Array,  # [B, MP] int32
    hist_lens: jax.Array,  # [B] int32 — tokens already written to pages
    cur_lens: jax.Array,  # [B] int32 — valid tokens in this chunk
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
    mesh=None,
    k_scale: jax.Array | None = None,  # [L, P, S, Hkv] f32 (quantized pools)
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """History-chunk prefill attention: paged history walked with
    double-buffered DMA (read once per q block) + the in-register current
    chunk, one online softmax over both — replaces the XLA
    gather-then-attend path, which materializes the whole history densely
    in HBM before a single matmul touches it. With `k_scale`/`v_scale`
    the history pages are quantized; each page's scale plane rides its
    DMA pipeline and rows dequantize in VMEM.

    Returns [B, T, Hq, D]; rows past cur_lens are unspecified.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        from functools import partial

        from dynamo_tpu.platform import get_shard_map

        shard_map = get_shard_map()
        from jax.sharding import PartitionSpec as P

        def sharded(q_, kc_, vc_, k_, v_, layer_, pt_, hl_, cl_, *scales):
            return paged_prefill_attention(
                q_, kc_, vc_, k_, v_, layer_, pt_, hl_, cl_,
                scale_dim=scale_dim, interpret=interpret, mesh=None,
                k_scale=scales[0] if scales else None,
                v_scale=scales[1] if scales else None,
            )

        in_specs = [
            P(None, None, "tp", None),
            P(None, None, "tp", None),
            P(None, None, "tp", None),
            P(None, None, None, "tp", None),
            P(None, None, None, "tp", None),
            P(), P(), P(), P(),
        ]
        args = [q, k_cur, v_cur, k_cache, v_cache, layer, page_tables,
                hist_lens, cur_lens]
        if quantized:
            in_specs += [P(None, None, None, "tp"), P(None, None, None, "tp")]
            args += [k_scale, v_scale]
        fn = shard_map(
            sharded,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(None, None, "tp", None),
            check_vma=False,
        )
        return fn(*args)

    b, t, hq, d = q.shape
    hkv, s = k_cache.shape[3], k_cache.shape[2]
    bq = BLOCK
    tp = -(-t // bq) * bq
    if tp != t:
        qpad = ((0, 0), (0, tp - t), (0, 0), (0, 0))
        q = jnp.pad(q, qpad)
        k_cur = jnp.pad(k_cur, qpad)  # BQ-aligned key blocks for the
        v_cur = jnp.pad(v_cur, qpad)  # frontier loop (cur masks the tail)

    in_specs = [
        pl.BlockSpec(
            (1, bq, hq, d),
            lambda bi, qi, li, pt, hl, cl: (bi, qi, 0, 0),
        ),
        pl.BlockSpec(
            (1, tp, hkv, d),
            lambda bi, qi, li, pt, hl, cl: (bi, 0, 0, 0),
        ),
        pl.BlockSpec(
            (1, tp, hkv, d),
            lambda bi, qi, li, pt, hl, cl: (bi, 0, 0, 0),
        ),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch_shapes = [
        pltpu.VMEM((2, s, hkv, d), k_cache.dtype),
        pltpu.VMEM((2, s, hkv, d), v_cache.dtype),
    ]
    operands = [q, k_cur, v_cur, k_cache, v_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        scratch_shapes += [
            pltpu.VMEM((2, s, hkv), jnp.float32),
            pltpu.VMEM((2, s, hkv), jnp.float32),
        ]
        operands += [k_scale, v_scale]
    scratch_shapes.append(
        pltpu.SemaphoreType.DMA((4 if quantized else 2, 2))
    )

    grid = (b, tp // bq)
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel,
            page_size=s,
            scale_dim=scale_dim or d,
            num_kv_heads=hkv,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, bq, hq, d),
                lambda bi, qi, li, pt, hl, cl: (bi, qi, 0, 0),
            ),
            scratch_shapes=scratch_shapes,
        ),
        out_shape=jax.ShapeDtypeStruct((b, tp, hq, d), q.dtype),
        interpret=interpret,
        # the static kv-head unroll holds per-head f32 accumulators; at
        # llama3 shapes (Hkv=8, G=4, BQ=128, D=128) that is ~19MB of
        # scoped VMEM — above Mosaic's 16MB default, well under v5e's 128MB
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    )(
        jnp.asarray(layer, jnp.int32).reshape(1),
        page_tables.astype(jnp.int32),
        hist_lens.astype(jnp.int32),
        cur_lens.astype(jnp.int32),
        *operands,
    )
    return out[:, :t]


def flash_prefill_attention(
    q: jax.Array,  # [B, T, Hq, D] post-rope (D may be lane-padded)
    k: jax.Array,  # [B, T, Hkv, D] post-rope
    v: jax.Array,  # [B, T, Hkv, D]
    valid_len: jax.Array,  # [B] int32 — contiguous valid prefix length
    *,
    scale_dim: int | None = None,
    interpret: bool | None = None,
    mesh=None,
) -> jax.Array:
    """Causal flash attention over one prefill chunk. Returns
    [B, T, Hq, D]; rows at positions >= valid_len are unspecified (the
    engine ignores them, same contract as the XLA fallback).

    `interpret` defaults to True off-TPU so tests run the kernel on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        from functools import partial

        from dynamo_tpu.platform import get_shard_map

        shard_map = get_shard_map()
        from jax.sharding import PartitionSpec as P

        fn = shard_map(
            partial(
                flash_prefill_attention,
                scale_dim=scale_dim, interpret=interpret, mesh=None,
            ),
            mesh=mesh,
            in_specs=(
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(None, None, "tp", None),
                P(),
            ),
            out_specs=P(None, None, "tp", None),
            check_vma=False,
        )
        return fn(q, k, v, valid_len)

    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    tp = -(-t // BLOCK) * BLOCK
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    # head-major layouts: q [B, Hkv, G, T, D] (the g heads of a kv group
    # are adjacent because Hq ordering is group-major), k/v [B, Hkv, T, D]
    qh = q.reshape(b, tp, hkv, g, d).transpose(0, 2, 3, 1, 4)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    grid = (b, hkv, tp // BLOCK)
    out = pl.pallas_call(
        functools.partial(
            _prefill_kernel, scale_dim=scale_dim or d, block=BLOCK
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, g, BLOCK, d),
                    lambda bi, hi, qi, ln: (bi, hi, 0, qi, 0),
                ),
                pl.BlockSpec(
                    (1, 1, tp, d), lambda bi, hi, qi, ln: (bi, hi, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, tp, d), lambda bi, hi, qi, ln: (bi, hi, 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, g, BLOCK, d),
                lambda bi, hi, qi, ln: (bi, hi, 0, qi, 0),
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, tp, d), q.dtype),
        interpret=interpret,
    )(valid_len.astype(jnp.int32), qh, kh, vh)
    # back to [B, T, Hq, D]
    out = out.transpose(0, 3, 1, 2, 4)
    return out.reshape(b, tp, hq, d)[:, :t]

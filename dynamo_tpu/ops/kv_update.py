"""Pallas TPU in-place paged-KV writer.

The paged cache is [L, P, S, Hkv, D] (models/llama.py KVPages). The model's
layer scan STAGES each layer's newly-computed KV (a small [L, B, T, Hkv, D]
scan output) instead of scattering into the cache per layer — XLA lowers
those scatters at ~0.5 ms each on TPU, and 2×L of them dominated the decode
step. This kernel lands the whole step's writes afterwards in ONE launch:
for every (sequence, page-run) it issues a single strided DMA covering ALL
layers at once (the layer axis is the cache's major axis, so
cache[:, page, slot0:slot0+run] is one descriptor).

Run shape: decode writes runs of 1 slot; prefill chunks are page-aligned
(scheduler invariant) so runs are min(T, S) slots. A prompt-tail run may
carry garbage staging rows past the valid tokens — harmless, those slots
are beyond every sequence's readable history and are overwritten by decode
before they become readable. Invalid (padding) runs are redirected to the
null page 0.

input_output_aliasing keeps both caches in place. D must be a 128 multiple
on TPU (LlamaConfig.kv_head_dim) — Mosaic DMA minor-dim alignment.

Parity: the engine-side KV write the reference delegates to vLLM's
reshape_and_cache CUDA kernel (SURVEY.md §2.9); TPU-native equivalent as a
Pallas DMA kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _write_kernel(
    pages_ref,  # [NR] int32 target page per run (scalar prefetch)
    slots_ref,  # [NR] int32 first slot per run (scalar prefetch)
    k_src_ref,  # [L, NR, R, Hkv, D] ANY — staged K rows, run-major
    v_src_ref,  # [L, NR, R, Hkv, D] ANY
    k_in_ref,  # [L, P, S, Hkv, D] ANY (aliased with k_out)
    v_in_ref,
    k_out_ref,  # [L, P, S, Hkv, D] ANY
    v_out_ref,
    sem,  # DMA semaphore
    *,
    num_runs: int,
    run: int,
):
    del k_in_ref, v_in_ref  # aliased: writes land in place

    def copies(i):
        dst_k = k_out_ref.at[:, pages_ref[i], pl.ds(slots_ref[i], run)]
        dst_v = v_out_ref.at[:, pages_ref[i], pl.ds(slots_ref[i], run)]
        return (
            pltpu.make_async_copy(k_src_ref.at[:, i], dst_k, sem),
            pltpu.make_async_copy(v_src_ref.at[:, i], dst_v, sem),
        )

    def start(i, _):
        ck, cv = copies(i)
        ck.start()
        cv.start()
        return 0

    def drain(i, _):
        ck, cv = copies(i)
        ck.wait()
        cv.wait()
        return 0

    # All runs' DMAs go out before any wait: targets are disjoint (padding
    # runs all alias the null page, where content is irrelevant), so total
    # latency is one round, not NR of them.
    jax.lax.fori_loop(0, num_runs, start, 0)
    jax.lax.fori_loop(0, num_runs, drain, 0)


def paged_write(
    k_cache: jax.Array,  # [L, P, S, Hkv, D]
    v_cache: jax.Array,
    k_stage: jax.Array,  # [L, B, T, Hkv, D] — per-layer staged new KV
    v_stage: jax.Array,
    page_tables: jax.Array,  # [B, MP] int32
    positions: jax.Array,  # [B, T] int32 absolute positions
    valid: jax.Array,  # [B, T] bool
    *,
    use_kernel: bool | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Write one step's staged KV for all layers into the caches in place.

    Requires T == 1 (decode) or page-aligned chunk starts with T a multiple
    of min(T, S) (prefill — guaranteed by the scheduler's page-aligned
    chunking). `use_kernel` defaults to True on TPU. Under a tp mesh the
    kernel is shard_mapped: staging and cache both shard on the kv-head
    axis, every shard writes its own lanes of the same rows.
    """
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    if use_kernel and mesh is not None and mesh.shape.get("tp", 1) > 1:
        from functools import partial

        from dynamo_tpu.platform import get_shard_map

        shard_map = get_shard_map()
        from jax.sharding import PartitionSpec as P

        kv_spec = P(None, None, None, "tp", None)
        fn = shard_map(
            partial(paged_write, use_kernel=True, mesh=None),
            mesh=mesh,
            in_specs=(
                kv_spec, kv_spec, kv_spec, kv_spec,
                P(None, None), P(None, None), P(None, None),
            ),
            out_specs=(kv_spec, kv_spec),
            check_vma=False,
        )
        return fn(
            k_cache, v_cache, k_stage, v_stage, page_tables, positions, valid
        )
    L, b, t = k_stage.shape[0], k_stage.shape[1], k_stage.shape[2]
    s = k_cache.shape[2]

    if not use_kernel:
        # XLA scatter fallback (CPU, meshes): token-granular, one 5D
        # advanced-index scatter per cache.
        page_of = positions // s
        slot_of = positions % s
        page_ids = jnp.take_along_axis(page_tables, page_of, axis=1)
        page_ids = jnp.where(valid, page_ids, 0).reshape(-1)
        slot_of = jnp.where(valid, slot_of, 0).reshape(-1)
        ks = k_stage.reshape(L, b * t, *k_stage.shape[3:])
        vs = v_stage.reshape(L, b * t, *v_stage.shape[3:])
        k_cache = k_cache.at[:, page_ids, slot_of].set(
            ks.astype(k_cache.dtype), mode="drop"
        )
        v_cache = v_cache.at[:, page_ids, slot_of].set(
            vs.astype(v_cache.dtype), mode="drop"
        )
        return k_cache, v_cache

    run = min(t, s)
    assert t % run == 0, f"chunk T={t} must be a multiple of run={run}"
    runs_per_seq = t // run
    nr = b * runs_per_seq
    # First token of each run determines its page/slot; invalid -> null.
    first_pos = positions[:, ::run]  # [B, T//R]
    first_valid = valid[:, ::run]
    page_ids = jnp.take_along_axis(page_tables, first_pos // s, axis=1)
    page_ids = jnp.where(first_valid, page_ids, 0).reshape(-1)
    slots = jnp.where(first_valid, first_pos % s, 0).reshape(-1)

    shape_tail = k_stage.shape[3:]
    k_src = k_stage.reshape(L, nr, run, *shape_tail).astype(k_cache.dtype)
    v_src = v_stage.reshape(L, nr, run, *shape_tail).astype(v_cache.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    return pl.pallas_call(
        functools.partial(_write_kernel, num_runs=nr, run=run),
        out_shape=[
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        grid_spec=grid_spec,
        # operands: pages, slots, k_src, v_src, k_cache, v_cache
        input_output_aliases={4: 0, 5: 1},
        interpret=jax.default_backend() != "tpu",
    )(
        page_ids.astype(jnp.int32),
        slots.astype(jnp.int32),
        k_src,
        v_src,
        k_cache,
        v_cache,
    )

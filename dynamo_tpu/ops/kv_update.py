"""Pallas TPU in-place paged-KV writer.

The paged cache is [L, P, S, Hkv, D] (models/llama.py KVPages). The model's
layer scan STAGES each layer's newly-computed KV (a small [L, B, T, Hkv, D]
scan output) instead of scattering into the cache per layer — XLA lowers
those scatters at ~0.5 ms each on TPU, and 2×L of them dominated the decode
step. This kernel lands the whole step's writes afterwards in ONE launch:
for every (sequence, page-run) it issues a single strided DMA covering ALL
layers at once (the layer axis is the cache's major axis, so
cache[:, page, slot0:slot0+run] is one descriptor).

Run shape: decode writes runs of 1 slot; prefill chunks are page-aligned
(scheduler invariant) so runs are min(T, S) slots. A prompt-tail run may
carry garbage staging rows past the valid tokens — harmless, those slots
are beyond every sequence's readable history and are overwritten by decode
before they become readable. Invalid (padding) runs are redirected to the
null page 0.

Quantized pools (kv_quantize, models/llama.py): the staged model-dtype
rows are quantized HERE — per-token, per-kv-head symmetric amax scales —
and the kernel DMAs the narrow pages plus their [run, Hkv] f32 scale
planes in the same launch, so no fp copy of the cache ever exists in HBM
(the staged arrays are transient step-sized temporaries either way).

input_output_aliasing keeps both caches in place. D must be a 128 multiple
on TPU (LlamaConfig.kv_head_dim) — Mosaic DMA minor-dim alignment. The
scale-plane copies have a SUB-128 minor dim (Hkv) — interpret mode can't
prove Mosaic accepts that, so the queued on-chip stages
(scripts/tpu_pallas_check.py paged_write_int8 / paged_decode_int8) are
the lowering proof; if Mosaic rejects it, store the planes lane-padded
(or packed into spare page lanes) — the semantics here don't change.

Parity: the engine-side KV write the reference delegates to vLLM's
reshape_and_cache CUDA kernel (SURVEY.md §2.9); TPU-native equivalent as a
Pallas DMA kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _write_kernel(
    pages_ref,  # [NR] int32 target page per run (scalar prefetch)
    slots_ref,  # [NR] int32 first slot per run (scalar prefetch)
    *refs,  # srcs, aliased-ins, outs, sem — layout depends on `quantized`
    num_runs: int,
    run: int,
    quantized: bool,
):
    if quantized:
        (
            k_src_ref,  # [L, NR, R, Hkv, D] ANY — quantized staged rows
            v_src_ref,
            ks_src_ref,  # [L, NR, R, Hkv] ANY — f32 row scales
            vs_src_ref,
            k_in_ref, v_in_ref, ks_in_ref, vs_in_ref,  # aliased
            k_out_ref,  # [L, P, S, Hkv, D] ANY
            v_out_ref,
            ks_out_ref,  # [L, P, S, Hkv] ANY
            vs_out_ref,
            sem,
        ) = refs
        del k_in_ref, v_in_ref, ks_in_ref, vs_in_ref
        pairs = (
            (k_src_ref, k_out_ref),
            (v_src_ref, v_out_ref),
            (ks_src_ref, ks_out_ref),
            (vs_src_ref, vs_out_ref),
        )
    else:
        (
            k_src_ref,  # [L, NR, R, Hkv, D] ANY — staged K rows, run-major
            v_src_ref,
            k_in_ref, v_in_ref,  # aliased: writes land in place
            k_out_ref,  # [L, P, S, Hkv, D] ANY
            v_out_ref,
            sem,
        ) = refs
        del k_in_ref, v_in_ref
        pairs = ((k_src_ref, k_out_ref), (v_src_ref, v_out_ref))

    def copies(i):
        return tuple(
            pltpu.make_async_copy(
                src.at[:, i], dst.at[:, pages_ref[i], pl.ds(slots_ref[i], run)],
                sem,
            )
            for src, dst in pairs
        )

    def start(i, _):
        for c in copies(i):
            c.start()
        return 0

    def drain(i, _):
        for c in copies(i):
            c.wait()
        return 0

    # All runs' DMAs go out before any wait: targets are disjoint (padding
    # runs all alias the null page, where content is irrelevant), so total
    # latency is one round, not NR of them.
    jax.lax.fori_loop(0, num_runs, start, 0)
    jax.lax.fori_loop(0, num_runs, drain, 0)


def paged_write(
    k_cache: jax.Array,  # [L, P, S, Hkv, D]
    v_cache: jax.Array,
    k_stage: jax.Array,  # [L, B, T, Hkv, D] — per-layer staged new KV
    v_stage: jax.Array,
    page_tables: jax.Array,  # [B, MP] int32
    positions: jax.Array,  # [B, T] int32 absolute positions
    valid: jax.Array,  # [B, T] bool
    *,
    use_kernel: bool | None = None,
    mesh=None,
    k_scale: jax.Array | None = None,  # [L, P, S, Hkv] f32 (quantized pools)
    v_scale: jax.Array | None = None,
):
    """Write one step's staged KV for all layers into the caches in place.

    Returns (k_cache, v_cache) or, with scale planes,
    (k_cache, v_cache, k_scale, v_scale).

    Requires T == 1 (decode) or page-aligned chunk starts with T a multiple
    of min(T, S) (prefill — guaranteed by the scheduler's page-aligned
    chunking). `use_kernel` defaults to True on TPU. Under a tp mesh the
    kernel is shard_mapped: staging and cache both shard on the kv-head
    axis, every shard writes its own lanes of the same rows.

    valid=False lanes redirect to page 0 (the engine's reserved null
    page) instead of skipping the write — that redirect is what lets the
    fused K-step decode window (EngineConfig.decode_kstep) freeze
    finished rows MID-WINDOW entirely on device: a frozen row keeps
    dispatching through the same program shape, its KV writes land in
    the null page, and its real pages are untouched for the next owner.
    """
    quantized = k_scale is not None
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    if use_kernel and mesh is not None and mesh.shape.get("tp", 1) > 1:
        from functools import partial

        from dynamo_tpu.platform import get_shard_map

        shard_map = get_shard_map()
        from jax.sharding import PartitionSpec as P

        kv_spec = P(None, None, None, "tp", None)
        scale_spec = P(None, None, None, "tp")
        in_specs = [
            kv_spec, kv_spec, kv_spec, kv_spec,
            P(None, None), P(None, None), P(None, None),
        ]
        out_specs = [kv_spec, kv_spec]
        if quantized:
            in_specs += [scale_spec, scale_spec]
            out_specs += [scale_spec, scale_spec]

        def sharded(kc, vc, ks_st, vs_st, pt, pos, vl, *scales):
            return paged_write(
                kc, vc, ks_st, vs_st, pt, pos, vl,
                use_kernel=True, mesh=None,
                k_scale=scales[0] if scales else None,
                v_scale=scales[1] if scales else None,
            )

        fn = shard_map(
            sharded,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=tuple(out_specs),
            check_vma=False,
        )
        args = [k_cache, v_cache, k_stage, v_stage, page_tables, positions,
                valid]
        if quantized:
            args += [k_scale, v_scale]
        return fn(*args)
    L, b, t = k_stage.shape[0], k_stage.shape[1], k_stage.shape[2]
    s = k_cache.shape[2]

    if quantized:
        from dynamo_tpu.models.llama import quantize_kv_rows

        mode = "int8" if k_cache.dtype == jnp.int8 else "fp8"
        k_q, k_s = quantize_kv_rows(k_stage, mode)  # [L,B,T,Hkv,D], [L,B,T,Hkv]
        v_q, v_s = quantize_kv_rows(v_stage, mode)
    else:
        k_q, v_q, k_s, v_s = k_stage, v_stage, None, None

    if not use_kernel:
        # XLA scatter fallback (CPU, meshes): token-granular, one 5D
        # advanced-index scatter per cache (+ the scale planes when
        # quantized).
        page_of = positions // s
        slot_of = positions % s
        page_ids = jnp.take_along_axis(page_tables, page_of, axis=1)
        page_ids = jnp.where(valid, page_ids, 0).reshape(-1)
        slot_of = jnp.where(valid, slot_of, 0).reshape(-1)
        ks = k_q.reshape(L, b * t, *k_q.shape[3:])
        vs = v_q.reshape(L, b * t, *v_q.shape[3:])
        k_cache = k_cache.at[:, page_ids, slot_of].set(
            ks.astype(k_cache.dtype), mode="drop"
        )
        v_cache = v_cache.at[:, page_ids, slot_of].set(
            vs.astype(v_cache.dtype), mode="drop"
        )
        if not quantized:
            return k_cache, v_cache
        k_scale = k_scale.at[:, page_ids, slot_of].set(
            k_s.reshape(L, b * t, -1), mode="drop"
        )
        v_scale = v_scale.at[:, page_ids, slot_of].set(
            v_s.reshape(L, b * t, -1), mode="drop"
        )
        return k_cache, v_cache, k_scale, v_scale

    run = min(t, s)
    assert t % run == 0, f"chunk T={t} must be a multiple of run={run}"
    runs_per_seq = t // run
    nr = b * runs_per_seq
    # First token of each run determines its page/slot; invalid -> null.
    first_pos = positions[:, ::run]  # [B, T//R]
    first_valid = valid[:, ::run]
    page_ids = jnp.take_along_axis(page_tables, first_pos // s, axis=1)
    page_ids = jnp.where(first_valid, page_ids, 0).reshape(-1)
    slots = jnp.where(first_valid, first_pos % s, 0).reshape(-1)

    shape_tail = k_stage.shape[3:]
    k_src = k_q.reshape(L, nr, run, *shape_tail).astype(k_cache.dtype)
    v_src = v_q.reshape(L, nr, run, *shape_tail).astype(v_cache.dtype)
    srcs = [k_src, v_src]
    out_shape = [
        jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
        jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
    ]
    caches = [k_cache, v_cache]
    if quantized:
        srcs += [
            k_s.reshape(L, nr, run, *k_s.shape[3:]),
            v_s.reshape(L, nr, run, *v_s.shape[3:]),
        ]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        caches += [k_scale, v_scale]
    n_src = len(srcs)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (2 * n_src),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_src,
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    out = pl.pallas_call(
        functools.partial(
            _write_kernel, num_runs=nr, run=run, quantized=quantized
        ),
        out_shape=out_shape,
        grid_spec=grid_spec,
        # operands: pages, slots, *srcs, *caches — cache i (after the 2
        # scalar-prefetch operands and n_src staging arrays) aliases
        # output i, keeping every pool in place
        input_output_aliases={2 + n_src + i: i for i in range(n_src)},
        interpret=jax.default_backend() != "tpu",
    )(
        page_ids.astype(jnp.int32),
        slots.astype(jnp.int32),
        *srcs,
        *caches,
    )
    return tuple(out)

"""Pallas TPU kernels for the hot ops.

The engine's default compute path is plain XLA (models/llama.py) — fully
fused and fine for short contexts. These kernels replace the pieces where
hand-control over HBM traffic wins: paged-attention decode streams KV pages
HBM→VMEM once with double-buffered DMA instead of materializing the whole
gathered history (paged_gather) in HBM.
"""

from dynamo_tpu.ops.paged_attention import paged_decode_attention

__all__ = ["paged_decode_attention"]

"""Pallas TPU kernels for the hot ops.

The engine's default compute path is plain XLA (models/llama.py) — fully
fused and fine for short contexts. These kernels replace the pieces where
hand-control over HBM traffic wins: paged-attention decode streams KV pages
HBM→VMEM once with double-buffered DMA instead of materializing the whole
gathered history (paged_gather) in HBM.

Quantized pools (EngineConfig.kv_quantize): every kernel also has an
int8/fp8 mode — the page writer quantizes staged rows and lands narrow
pages + per-row f32 scale planes in one launch, and both readers DMA the
scale planes alongside their pages and dequantize in VMEM, so the cache's
HBM footprint and read traffic roughly halve with no fp copy ever
materialized.
"""

from dynamo_tpu.ops.paged_attention import paged_decode_attention

__all__ = ["paged_decode_attention"]

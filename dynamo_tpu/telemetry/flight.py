"""Flight recorder: an always-on bounded ring of per-step scheduler/
engine decisions — the evidence plane for "why is this worker slow".

The aggregate counters (EngineMetrics) and the phase histograms say how
much time went where over the worker's life; neither can say what the
scheduler decided *around second 41 when request r17 stopped emitting*.
The flight recorder can: every engine step appends one small structured
record — batch kind and bucket keys, rows prefilling/decoding, page-pool
deltas and watermark, dispatch/sync/host wall ms, overlap hits and
rollbacks, compile events, queue depths — into a bounded deque. Cost is
one dict build + deque append per step (~µs; bench.py `flight_overhead`
prices it <1% of token throughput), and the plane is host-side only:
with `EngineConfig.flight_recorder=False` the engine holds no recorder
and the token path is bit-identical.

Consumption:
- `GET /v1/debug/flight[?n=]` on whatever HTTP surface the engine's
  process has (the OpenAI frontend in single-process serving), via
  `telemetry.debug`;
- the worker ships its most recent window in every metrics frame
  (`worker.py _publish_loop`), so the metrics service can serve the
  whole fleet's recent windows from one place;
- the stall watchdog (`telemetry/watchdog.py`) snapshots the window
  around a stall into its diagnosis;
- `scripts/doctor.py` folds the windows into rule-based diagnoses
  (compile storm, preemption thrash, prefill-induced decode stall, ...).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

#: EngineMetrics counters whose per-step DELTA rides each record (the
#: cumulative values are already on the metrics plane; the deltas are
#: what localize an event to a step). Keyed by the short record field.
_DELTA_FIELDS = (
    ("disp_ms", "time_decode_dispatch_ms"),
    ("sync_ms", "time_decode_sync_ms"),
    ("host_ms", "time_decode_host_ms"),
    ("overlap_hits", "overlap_hits"),
    ("overlap_rollbacks", "overlap_rollbacks"),
    # speculative decoding (ngram or draft model): drafted/accepted per
    # step — a record with tokens but no spec_drafted is a plain step
    ("spec_drafted", "spec_drafted"),
    ("spec_accepted", "spec_accepted"),
    # on-device K-step decode windows: a record with kstep_steps > 1×
    # kstep_windows carries a fused multi-token window; per-step time is
    # the record's step_ms / kstep_steps
    ("kstep_windows", "kstep_windows"),
    ("kstep_steps", "kstep_steps"),
    ("compiles", "compiles"),
    ("compile_ms", "compile_ms"),
    ("preempted", "preemptions"),
    ("tokens", "generated_tokens"),
)

#: default records shipped per metrics frame (a frame goes out ~1/s; 32
#: records cover the last ~32 steps — enough for the doctor's rules
#: without bloating the metrics bus)
WIRE_RECORDS = 32


def tail(records: list, n: Optional[int]) -> list:
    """Most recent `n` records (all when n is None). The single trim
    used by the recorder AND the metrics service's fleet endpoint —
    records[-0:] would be the whole list, so n=0 is special-cased."""
    if n is None or n < 0:
        return records
    return records[-n:] if n else []


class FlightRecorder:
    """Bounded ring of per-step records. The engine thread appends;
    the publish loop / debug endpoints / watchdog snapshot — a small
    lock keeps the snapshot consistent (deque mutation during iteration
    raises)."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"flight ring capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        #: previous cumulative counter values for the per-step deltas
        self._prev: dict[str, float] = {}
        self._seq = 0

    def record_step(
        self,
        metrics,
        kind: str,
        step_ms: float,
        n_decode: int = 0,
        b_decode: int = 0,
        n_prefill: int = 0,
        t_bucket: int = 0,
        prefill_tokens: int = 0,
        waiting: int = 0,
        running: int = 0,
        free_pages: int = 0,
        active_pages: int = 0,
        watermark: int = 0,
    ) -> dict:
        """Append one step record. `metrics` is the engine's
        EngineMetrics — deltas against the previous record are computed
        here so the engine's call site stays one line."""
        rec: dict = {
            "seq": self._seq,
            "ts": round(time.time(), 4),
            "kind": kind,
            "step_ms": round(step_ms, 3),
            "n_decode": n_decode,
            "b_decode": b_decode,
            "n_prefill": n_prefill,
            "t_bucket": t_bucket,
            "prefill_tokens": prefill_tokens,
            "waiting": waiting,
            "running": running,
            "free_pages": free_pages,
            "active_pages": active_pages,
            "watermark": watermark,
        }
        prev = self._prev
        for field, attr in _DELTA_FIELDS:
            cur = getattr(metrics, attr, 0)
            d = cur - prev.get(attr, 0)
            prev[attr] = cur
            if isinstance(d, float):
                d = round(d, 3)
            if d:
                rec[field] = d
        self._seq += 1
        with self._lock:
            self._ring.append(rec)
        return rec

    def snapshot(self, n: Optional[int] = None) -> list[dict]:
        """Most recent `n` records, oldest first (all when n is None)."""
        with self._lock:
            out = list(self._ring)
        return tail(out, n)

    def to_wire(self, n: int = WIRE_RECORDS) -> list[dict]:
        """The window that rides the metrics frame (json/msgpack-safe)."""
        return self.snapshot(n)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

"""dynamo_tpu.telemetry — the cross-cutting observability plane.

- trace: spans + contextvar propagation + the in-memory trace ring
  (docs/observability.md); default OFF, enable with DYNTPU_TRACING=1 /
  DYNTPU_TRACE_RING=<n> / configure().
- phases: per-phase latency histograms (queue_wait, prefill,
  decode_step, router_dispatch, disagg_transfer) for /metrics.
- chrome_export: trace -> Chrome trace-event JSON (Perfetto).
- promlint: pure-python Prometheus exposition linter (tests gate every
  hand-rolled /metrics surface with it).
- slo: streaming quantile sketch (mergeable, bounded memory) + SLA
  attainment/goodput/burn-rate accounting — the fleet telemetry plane
  (docs/observability.md "Fleet view & SLO accounting").
"""

from dynamo_tpu.telemetry import phases, slo  # noqa: F401
from dynamo_tpu.telemetry.trace import (  # noqa: F401
    NOOP_SPAN,
    Span,
    TraceRing,
    configure,
    context_from_headers,
    current_span,
    enabled,
    extract,
    get_trace,
    inject,
    list_traces,
    record_span_dict,
    reset,
    span,
    wire_context,
)

"""dynamo_tpu.telemetry — the cross-cutting observability plane.

- trace: spans + contextvar propagation + the in-memory trace ring
  (docs/observability.md); default OFF, enable with DYNTPU_TRACING=1 /
  DYNTPU_TRACE_RING=<n> / configure().
- phases: per-phase latency histograms (queue_wait, prefill,
  decode_step, router_dispatch, disagg_transfer) for /metrics.
- chrome_export: trace -> Chrome trace-event JSON (Perfetto).
- promlint: pure-python Prometheus exposition linter (tests gate every
  hand-rolled /metrics surface with it).
- slo: streaming quantile sketch (mergeable, bounded memory) + SLA
  attainment/goodput/burn-rate accounting — the fleet telemetry plane
  (docs/observability.md "Fleet view & SLO accounting").
- flight: always-on bounded ring of per-step engine/scheduler records
  (the "what happened around second 41" plane).
- watchdog: per-request stall detection + structured diagnosis
  (dynamo_tpu_stalls_total{cause}, thread stacks, hard-deadline
  error-finish of wedged streams).
- debug: the /v1/debug/* payload layer (flight / programs / stalls /
  profile) shared by the frontend and metrics-service mounts.
"""

from dynamo_tpu.telemetry import events, phases, slo  # noqa: F401
from dynamo_tpu.telemetry.flight import FlightRecorder  # noqa: F401
from dynamo_tpu.telemetry.watchdog import (  # noqa: F401
    StallWatchdog,
    stall_counters,
)
from dynamo_tpu.telemetry.trace import (  # noqa: F401
    NOOP_SPAN,
    Span,
    TraceRing,
    configure,
    context_from_headers,
    current_span,
    current_trace_id,
    enabled,
    extract,
    get_trace,
    inject,
    list_traces,
    record_span_dict,
    reset,
    span,
    wire_context,
)

"""dynamo_tpu.telemetry — the cross-cutting observability plane.

- trace: spans + contextvar propagation + the in-memory trace ring
  (docs/observability.md); default OFF, enable with DYNTPU_TRACING=1 /
  DYNTPU_TRACE_RING=<n> / configure().
- phases: per-phase latency histograms (queue_wait, prefill,
  decode_step, router_dispatch, disagg_transfer) for /metrics.
- chrome_export: trace -> Chrome trace-event JSON (Perfetto).
- promlint: pure-python Prometheus exposition linter (tests gate every
  hand-rolled /metrics surface with it).
"""

from dynamo_tpu.telemetry import phases  # noqa: F401
from dynamo_tpu.telemetry.trace import (  # noqa: F401
    NOOP_SPAN,
    Span,
    TraceRing,
    configure,
    context_from_headers,
    current_span,
    enabled,
    extract,
    get_trace,
    inject,
    list_traces,
    record_span_dict,
    reset,
    span,
    wire_context,
)

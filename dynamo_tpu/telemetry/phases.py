"""Per-phase latency histograms (Prometheus exposition lines).

The aggregate time_*_ms counters (EngineMetrics) answer "where does the
fleet's time go"; these histograms answer "what does one request's phase
COST look like" — tails included. Observed unconditionally (they are
metrics, not traces; a few float compares under a lock per event), and
appended to both FrontendMetrics.expose() and MetricsService.expose()
so whichever process hosts the phase shows it on /metrics.

Phases:
  queue_wait_ms        admission wait in the engine scheduler
  prefill_ms           one prefill dispatch (host+device wall time)
  decode_step_ms       one decode dispatch
  mixed_step_ms        one mixed prefill+decode dispatch (mixed_steps)
  decode_stall_ms      gap between consecutive token emissions of one
                       running request when a prefill-carrying dispatch
                       ran in between — the prefill-induced decode stall.
                       The XOR scheduler pays whole backlog drains here;
                       mixed steps collapse it to one step.
  router_dispatch_ms   PushRouter pick->first response frame
  disagg_transfer_ms   remote prefill enqueue->KV landing
  compile_ms           one jit-program build+first-execution (engine
                       _jit_cache miss). Dominated by XLA compilation;
                       a busy histogram here means the program family
                       is churning (new buckets / fused-step counts /
                       mixed-shape combinations) — the compile hazard
                       the 3-axis mixed family introduced.
  handover_adopt_ms    worker handover, successor side: one batch's
                       page reservation armed -> bytes landed ->
                       registered (docs/operations.md "Rolling
                       upgrades & worker handover").
"""

from __future__ import annotations

import threading
import time

PREFIX = "dynamo_tpu_phase"

PHASES = (
    "queue_wait_ms",
    "prefill_ms",
    "decode_step_ms",
    "mixed_step_ms",
    "decode_stall_ms",
    "router_dispatch_ms",
    "disagg_transfer_ms",
    "compile_ms",
    # worker handover: successor-side batch adopt latency, reservation
    # armed -> pages registered (transfer landing included) — the
    # Grafana "Handover" row's latency panel
    "handover_adopt_ms",
)

#: ms ladder wide enough for a sub-ms decode step and a 60s stuck
#: transfer alike
BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0,
)


class PhaseHistograms:
    """Counts + sums per phase, plus (tracing only) the newest exemplar
    per bucket: with a trace_id attached, a bucket observation remembers
    which TRACE put it there, and the exposition emits it in OpenMetrics
    exemplar syntax — Grafana jumps from a latency-heatmap spike
    straight to the assembled trace at GET /v1/traces/{id}. With
    tracing off no exemplar is ever stored and the exposition is
    byte-identical to before."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}
        #: phase -> bucket index -> (trace_id, value_ms, unix_ts)
        self._exemplars: dict[str, dict[int, tuple[str, float, float]]] = {}

    def observe(
        self, phase: str, value_ms: float, trace_id: str | None = None
    ) -> None:
        with self._lock:
            counts = self._counts.get(phase)
            if counts is None:
                counts = self._counts[phase] = [0] * (len(BUCKETS_MS) + 1)
                self._sums[phase] = 0.0
            self._sums[phase] += value_ms
            idx = len(BUCKETS_MS)
            for i, b in enumerate(BUCKETS_MS):
                if value_ms <= b:
                    idx = i
                    break
            counts[idx] += 1
            if trace_id:
                self._exemplars.setdefault(phase, {})[idx] = (
                    trace_id, value_ms, time.time(),
                )

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._exemplars.clear()

    def expose_lines(self, exemplars: bool = False) -> list[str]:
        """Prometheus text lines for every phase that has observations.
        With `exemplars=True` (the OPENMETRICS rendering only — the
        classic text/plain parser rejects exemplar syntax, which would
        fail the whole scrape) bucket lines carry the stamped trace:
        `name_bucket{le="X"} N # {trace_id="..."} value ts`."""
        lines: list[str] = []
        with self._lock:
            for phase in PHASES:
                counts = self._counts.get(phase)
                if counts is None:
                    continue
                ex = self._exemplars.get(phase, {}) if exemplars else {}
                name = f"{PREFIX}_{phase}"
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for i, b in enumerate(BUCKETS_MS):
                    cum += counts[i]
                    lines.append(
                        f'{name}_bucket{{le="{b}"}} {cum}'
                        + _exemplar_suffix(ex.get(i))
                    )
                cum += counts[-1]
                lines.append(
                    f'{name}_bucket{{le="+Inf"}} {cum}'
                    + _exemplar_suffix(ex.get(len(BUCKETS_MS)))
                )
                lines.append(f"{name}_sum {self._sums[phase]}")
                lines.append(f"{name}_count {cum}")
        return lines


def _exemplar_suffix(ex: tuple[str, float, float] | None) -> str:
    if ex is None:
        return ""
    trace_id, value_ms, ts = ex
    return (
        f' # {{trace_id="{trace_id}"}} {round(value_ms, 6)} {round(ts, 3)}'
    )


phase_histograms = PhaseHistograms()


def observe(
    phase: str, value_ms: float, trace_id: str | None = None
) -> None:
    """Record one phase observation. `trace_id` stamps the bucket's
    exemplar; when omitted, the active trace context is used (always
    None with tracing off — one flag check, no contextvar touch on the
    disabled path)."""
    if trace_id is None:
        from dynamo_tpu.telemetry import trace as _trace

        trace_id = _trace.current_trace_id()
    phase_histograms.observe(phase, value_ms, trace_id)


def expose_lines(exemplars: bool = False) -> list[str]:
    return phase_histograms.expose_lines(exemplars=exemplars)

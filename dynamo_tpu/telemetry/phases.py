"""Per-phase latency histograms (Prometheus exposition lines).

The aggregate time_*_ms counters (EngineMetrics) answer "where does the
fleet's time go"; these histograms answer "what does one request's phase
COST look like" — tails included. Observed unconditionally (they are
metrics, not traces; a few float compares under a lock per event), and
appended to both FrontendMetrics.expose() and MetricsService.expose()
so whichever process hosts the phase shows it on /metrics.

Phases:
  queue_wait_ms        admission wait in the engine scheduler
  prefill_ms           one prefill dispatch (host+device wall time)
  decode_step_ms       one decode dispatch
  mixed_step_ms        one mixed prefill+decode dispatch (mixed_steps)
  decode_stall_ms      gap between consecutive token emissions of one
                       running request when a prefill-carrying dispatch
                       ran in between — the prefill-induced decode stall.
                       The XOR scheduler pays whole backlog drains here;
                       mixed steps collapse it to one step.
  router_dispatch_ms   PushRouter pick->first response frame
  disagg_transfer_ms   remote prefill enqueue->KV landing
  compile_ms           one jit-program build+first-execution (engine
                       _jit_cache miss). Dominated by XLA compilation;
                       a busy histogram here means the program family
                       is churning (new buckets / fused-step counts /
                       mixed-shape combinations) — the compile hazard
                       the 3-axis mixed family introduced.
  handover_adopt_ms    worker handover, successor side: one batch's
                       page reservation armed -> bytes landed ->
                       registered (docs/operations.md "Rolling
                       upgrades & worker handover").
"""

from __future__ import annotations

import threading

PREFIX = "dynamo_tpu_phase"

PHASES = (
    "queue_wait_ms",
    "prefill_ms",
    "decode_step_ms",
    "mixed_step_ms",
    "decode_stall_ms",
    "router_dispatch_ms",
    "disagg_transfer_ms",
    "compile_ms",
    # worker handover: successor-side batch adopt latency, reservation
    # armed -> pages registered (transfer landing included) — the
    # Grafana "Handover" row's latency panel
    "handover_adopt_ms",
)

#: ms ladder wide enough for a sub-ms decode step and a 60s stuck
#: transfer alike
BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0,
)


class PhaseHistograms:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}

    def observe(self, phase: str, value_ms: float) -> None:
        with self._lock:
            counts = self._counts.get(phase)
            if counts is None:
                counts = self._counts[phase] = [0] * (len(BUCKETS_MS) + 1)
                self._sums[phase] = 0.0
            self._sums[phase] += value_ms
            for i, b in enumerate(BUCKETS_MS):
                if value_ms <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()

    def expose_lines(self) -> list[str]:
        """Prometheus text lines for every phase that has observations."""
        lines: list[str] = []
        with self._lock:
            for phase in PHASES:
                counts = self._counts.get(phase)
                if counts is None:
                    continue
                name = f"{PREFIX}_{phase}"
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for i, b in enumerate(BUCKETS_MS):
                    cum += counts[i]
                    lines.append(f'{name}_bucket{{le="{b}"}} {cum}')
                cum += counts[-1]
                lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{name}_sum {self._sums[phase]}")
                lines.append(f"{name}_count {cum}")
        return lines


phase_histograms = PhaseHistograms()


def observe(phase: str, value_ms: float) -> None:
    phase_histograms.observe(phase, value_ms)


def expose_lines() -> list[str]:
    return phase_histograms.expose_lines()

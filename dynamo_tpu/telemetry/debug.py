"""In-process debug surface: the `/v1/debug/*` payload layer.

Engines register themselves here (weakly — a GC'd engine drops out) so
whatever HTTP surface the process happens to have (the OpenAI frontend
in single-process serving, the metrics service for its own process) can
serve:

  GET  /v1/debug/flight[?n=]   the flight-recorder window per engine
  GET  /v1/debug/programs      per-program cost-model attainment
                               (compile cost, cost_analysis flops/bytes,
                               measured ms/dispatch vs roofline)
  GET  /v1/debug/memory        per-device HBM byte breakdown (weights /
                               kv_pool / scratch / live / free / peak —
                               engine.memory_report, docs/
                               observability.md "Reading the perf
                               plane")
  GET  /v1/debug/mesh          mesh shape + axis names, per-param-group
                               sharding specs, process seat, dispatch
                               window (engine.mesh_report)
  GET  /v1/debug/stalls        watchdog counters + recent diagnoses
  POST /v1/debug/profile       {"steps": K[, "dir": path]} — arm a
                               jax.profiler capture for K engine steps
                               (501 when no engine/profiler is here)

Framework-free like telemetry/http_api.py: handlers pass raw strings /
parsed bodies in and get (json-able body, status) back, so the two
aiohttp mounts can't drift apart. Remote workers' windows are served by
the metrics service from their metrics frames instead (docs/
observability.md "Debugging a slow or stuck worker").
"""

from __future__ import annotations

import itertools
import os
import weakref
from typing import Optional

#: the only place HTTP-supplied profile captures may land — a debug
#: endpoint must not become an arbitrary-path write primitive
PROFILE_BASE = os.path.join("artifacts", "profile")

#: name -> engine (weak: an engine that fell out of scope must not be
#: resurrected by its debug surface)
_engines: "weakref.WeakValueDictionary[str, object]" = (
    weakref.WeakValueDictionary()
)
_counter = itertools.count()


def register_engine(engine, name: Optional[str] = None) -> str:
    """Called by JaxEngine at construction. Returns the registry key."""
    if name is None:
        model = getattr(getattr(engine, "config", None), "model", "engine")
        name = f"{model}-{next(_counter)}"
    _engines[name] = engine
    return name


def registered_engines() -> dict:
    return dict(_engines)


def _clear_registry() -> None:
    """Test hook: isolate registry state between tests."""
    _engines.clear()


#: fabric clients (RemoteFabric) living in this process — weak, like the
#: engine registry: whatever Prometheus surface the process has gauges
#: the control-plane connection state off them (docs/operations.md
#: "Control-plane HA")
_fabric_clients: "weakref.WeakSet" = weakref.WeakSet()


def register_fabric_client(client) -> None:
    """Called by RemoteFabric at construction."""
    _fabric_clients.add(client)


def control_plane_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global control-plane health: the degraded gauge (1 = no
    broker has answered past the budget; this process is serving from
    cached discovery / buffering publishes), outage counters, and
    client-observed broker failovers. Included by BOTH Prometheus
    surfaces; always emitted (zeros — including for LocalFabric
    processes, which are their own broker) so the dashboard
    panel-vs-emitted gate sees the families."""
    degraded = 0
    disconnected_s = 0.0
    entries = 0
    seconds = 0.0
    failovers = 0
    for c in list(_fabric_clients):
        if getattr(c, "degraded", False):
            degraded = 1
        disconnected_s = max(
            disconnected_s, float(getattr(c, "disconnected_s", 0.0) or 0.0)
        )
        entries += int(getattr(c, "degraded_total", 0) or 0)
        seconds += float(getattr(c, "degraded_seconds_total", 0.0) or 0.0)
        failovers += int(getattr(c, "failovers_total", 0) or 0)
    return [
        f"# TYPE {prefix}_control_plane_degraded gauge",
        f"{prefix}_control_plane_degraded {degraded}",
        f"# TYPE {prefix}_control_plane_disconnected_seconds gauge",
        f"{prefix}_control_plane_disconnected_seconds "
        f"{round(disconnected_s, 3)}",
        # "_entries_total", not "_total": the OpenMetrics rendering
        # strips counter _total suffixes into family names, and
        # "control_plane_degraded" is already the gauge's family
        f"# TYPE {prefix}_control_plane_degraded_entries_total counter",
        f"{prefix}_control_plane_degraded_entries_total {entries}",
        f"# TYPE {prefix}_control_plane_degraded_seconds_total counter",
        f"{prefix}_control_plane_degraded_seconds_total "
        f"{round(seconds, 3)}",
        f"# TYPE {prefix}_fabric_client_failovers_total counter",
        f"{prefix}_fabric_client_failovers_total {failovers}",
    ]


def spec_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global speculative-decoding exposition, summed over the
    registered in-process engines: `{prefix}_spec_*_total` counters plus
    the live acceptance-rate gauge. Included by BOTH Prometheus surfaces
    (FrontendMetrics for in-process serving, MetricsService for its own
    process) — the per-WORKER fleet view rides the metrics frames as
    `{prefix}_worker_spec_*` instead. Always emitted (zeros when no
    engine speculates) so dashboards and the panel-name gate see the
    families."""
    drafted = accepted = skip_inel = skip_cool = 0
    rate_num = rate_den = 0.0
    for eng in registered_engines().values():
        m = getattr(eng, "metrics", None)
        if m is None:
            continue
        drafted += getattr(m, "spec_drafted", 0)
        accepted += getattr(m, "spec_accepted", 0)
        skip_inel += getattr(m, "spec_skipped_ineligible", 0)
        skip_cool += getattr(m, "spec_skipped_cooldown", 0)
        # weight each engine's windowed rate by its windowed drafts:
        # an ACTIVELY-FAILING draft (rate 0, window drafted > 0) must
        # pull the aggregate down, while idle engines (window drained)
        # must not — gating on the rate's truthiness would conflate them
        wd = getattr(m, "spec_window_drafted", 0) or 0
        r = getattr(m, "spec_accept_rate", None)
        if wd > 0 and isinstance(r, (int, float)):
            rate_num += float(r) * wd
            rate_den += wd
    rate = rate_num / rate_den if rate_den else 0.0
    return [
        f"# TYPE {prefix}_spec_drafted_total counter",
        f"{prefix}_spec_drafted_total {drafted}",
        f"# TYPE {prefix}_spec_accepted_total counter",
        f"{prefix}_spec_accepted_total {accepted}",
        f"# TYPE {prefix}_spec_skipped_ineligible_total counter",
        f"{prefix}_spec_skipped_ineligible_total {skip_inel}",
        f"# TYPE {prefix}_spec_skipped_cooldown_total counter",
        f"{prefix}_spec_skipped_cooldown_total {skip_cool}",
        f"# TYPE {prefix}_spec_accept_rate gauge",
        f"{prefix}_spec_accept_rate {round(rate, 4)}",
    ]


def kstep_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global K-step decode-window exposition, summed over the
    registered in-process engines (EngineConfig.decode_kstep):
    windows/steps/fallback counters plus the live window-size gauge.
    Included by BOTH Prometheus surfaces like spec_lines; the per-WORKER
    fleet view rides the metrics frames as `{prefix}_worker_kstep_*`.
    Always emitted (zeros when no engine fuses windows) so dashboards
    and the panel-name gate see the families."""
    windows = steps = fallbacks = 0
    window_size = 0
    for eng in registered_engines().values():
        m = getattr(eng, "metrics", None)
        if m is None:
            continue
        windows += getattr(m, "kstep_windows", 0)
        steps += getattr(m, "kstep_steps", 0)
        fallbacks += getattr(m, "kstep_fallbacks", 0)
        # gauge: the largest live window across engines (0 = classic)
        window_size = max(window_size, getattr(m, "kstep_window_size", 0))
    return [
        f"# TYPE {prefix}_kstep_windows_total counter",
        f"{prefix}_kstep_windows_total {windows}",
        f"# TYPE {prefix}_kstep_steps_total counter",
        f"{prefix}_kstep_steps_total {steps}",
        f"# TYPE {prefix}_kstep_fallbacks_total counter",
        f"{prefix}_kstep_fallbacks_total {fallbacks}",
        f"# TYPE {prefix}_kstep_window_size gauge",
        f"{prefix}_kstep_window_size {window_size}",
    ]


def integrity_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global data-integrity counters: KV bytes whose checksum
    failed verification and were REJECTED — disk-tier blocks at rest
    (kvbm/tiers.py xxh3 trailer) and transfer-plane frames on the wire
    (runtime/codec.py framing). Always emitted (zeros included) so the
    dashboard-name gate sees the families; a nonzero rate is bit-rot or
    a failing link, never served tokens."""
    from dynamo_tpu.disagg import transfer as _transfer
    from dynamo_tpu.kvbm import tiers as _tiers

    return [
        f"# TYPE {prefix}_kvbm_disk_corrupt_total counter",
        f"{prefix}_kvbm_disk_corrupt_total {_tiers.disk_corrupt_total}",
        f"# TYPE {prefix}_transfer_corrupt_total counter",
        f"{prefix}_transfer_corrupt_total {_transfer.transfer_corrupt_total}",
    ]


def kv_index_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global KV index health (kv_router/indexer.py counters):
    sequence gaps detected, targeted resyncs run (and failed), drift
    blocks corrected, and the live stale-subtree gauge. Included by BOTH
    Prometheus surfaces — the process hosting a KV-aware router (the
    frontend in single-process serving) is where the index lives; the
    metrics service additionally folds router-published kv_index.status
    frames for multi-process fleets. Always emitted (zeros) so the
    dashboard panel-vs-emitted gate sees the families."""
    from dynamo_tpu.kv_router.indexer import (
        index_counters,
        process_stale_workers,
    )

    c = index_counters
    return [
        f"# TYPE {prefix}_kv_index_gaps_total counter",
        f"{prefix}_kv_index_gaps_total {c.gaps}",
        f"# TYPE {prefix}_kv_index_resyncs_total counter",
        f"{prefix}_kv_index_resyncs_total {c.resyncs}",
        f"# TYPE {prefix}_kv_index_resync_failures_total counter",
        f"{prefix}_kv_index_resync_failures_total {c.resync_failures}",
        f"# TYPE {prefix}_kv_index_drift_blocks_total counter",
        f"{prefix}_kv_index_drift_blocks_total {c.drift_blocks}",
        f"# TYPE {prefix}_kv_index_digest_mismatches_total counter",
        f"{prefix}_kv_index_digest_mismatches_total {c.digest_mismatches}",
        f"# TYPE {prefix}_kv_index_stale_workers gauge",
        f"{prefix}_kv_index_stale_workers {process_stale_workers()}",
    ]


#: the hbm_* family names in exposition order — one list shared by the
#: emitter below, the memory-report totals, and the tests that pin them
HBM_COMPONENTS = ("weights", "kv_pool", "scratch", "free", "peak")


def hbm_lines(prefix: str = "dynamo_tpu") -> list[str]:
    """Process-global HBM accounting exposition, per DEVICE, from the
    registered in-process engines' memory_report (docs/observability.md
    "Reading the perf plane"): `{prefix}_hbm_{weights,kv_pool,scratch,
    free,peak}_bytes{device=...}`. Included by BOTH Prometheus surfaces
    like spec_lines; the per-WORKER fleet rollup rides the metrics
    frames as `{prefix}_worker_hbm_*` instead. Always emitted (a zeroed
    device="0" series when no engine lives here) so dashboards and the
    panel-vs-emitted-names gate see the families."""
    per_dev: dict[str, dict[str, int]] = {}
    for eng in registered_engines().values():
        report = getattr(eng, "memory_report", None)
        if not callable(report):
            continue
        try:
            devices = report()["devices"]
        except Exception:
            continue
        for dev, row in devices.items():
            acc = per_dev.setdefault(dev, dict.fromkeys(HBM_COMPONENTS, 0))
            for comp in HBM_COMPONENTS:
                acc[comp] += int(row.get(f"{comp}_bytes", 0) or 0)
    if not per_dev:
        per_dev = {"0": dict.fromkeys(HBM_COMPONENTS, 0)}
    lines: list[str] = []
    for comp in HBM_COMPONENTS:
        lines.append(f"# TYPE {prefix}_hbm_{comp}_bytes gauge")
        for dev in sorted(per_dev):
            lines.append(
                f'{prefix}_hbm_{comp}_bytes{{device="{dev}"}} '
                f"{per_dev[dev][comp]}"
            )
    return lines


# -- payloads -------------------------------------------------------------


def parse_window(n_str: Optional[str]):
    """The `?n=` parse shared by the frontend AND metrics-service mounts
    (one copy, so the two can't drift): -> (n, error_body_or_None)."""
    if n_str is None:
        return None, None
    try:
        return int(n_str), None
    except ValueError:
        return None, {"error": "n must be int"}


def flight_payload(n_str: Optional[str]) -> tuple[dict, int]:
    """GET /v1/debug/flight?n=N -> (body, status)."""
    n, err = parse_window(n_str)
    if err is not None:
        return err, 400
    engines = {}
    for name, eng in sorted(registered_engines().items()):
        fl = getattr(eng, "flight", None)
        engines[name] = {
            "enabled": fl is not None,
            "records": fl.snapshot(n) if fl is not None else [],
        }
    return {"engines": engines}, 200


def programs_payload() -> tuple[dict, int]:
    """GET /v1/debug/programs -> per-engine program cost tables."""
    engines = {}
    for name, eng in sorted(registered_engines().items()):
        report = getattr(eng, "programs_report", None)
        engines[name] = report() if callable(report) else {}
    return {"engines": engines}, 200


def memory_payload() -> tuple[dict, int]:
    """GET /v1/debug/memory -> per-engine HBM accounting tables."""
    engines = {}
    for name, eng in sorted(registered_engines().items()):
        report = getattr(eng, "memory_report", None)
        engines[name] = report() if callable(report) else {}
    return {"engines": engines}, 200


def mesh_payload() -> tuple[dict, int]:
    """GET /v1/debug/mesh -> per-engine mesh/sharding introspection."""
    engines = {}
    for name, eng in sorted(registered_engines().items()):
        report = getattr(eng, "mesh_report", None)
        engines[name] = report() if callable(report) else {}
    return {"engines": engines}, 200


def stalls_payload() -> tuple[dict, int]:
    """GET /v1/debug/stalls -> process stall counters + diagnoses."""
    from dynamo_tpu.telemetry.watchdog import stall_counters

    diagnoses = []
    for eng in registered_engines().values():
        wd = getattr(eng, "_watchdog_ref", None)
        wd = wd() if callable(wd) else wd
        if wd is not None:
            diagnoses.extend(wd.diagnoses[-8:])
    return {
        "stalls_by_cause": stall_counters.snapshot(),
        "stalls_total": stall_counters.total,
        "diagnoses": diagnoses,
    }, 200


def profile_payload(body: Optional[dict]) -> tuple[dict, int]:
    """POST /v1/debug/profile -> arm a capture on every registered
    engine that supports it. Graceful 501 when jax.profiler is missing
    or no engine lives in this process (e.g. the metrics service)."""
    body = body or {}
    try:
        steps = int(body.get("steps", 8))
        if steps < 1:
            raise ValueError
    except (TypeError, ValueError):
        return {"error": "steps must be a positive int"}, 400
    outdir = body.get("dir")
    if outdir is not None:
        if not isinstance(outdir, str):
            return {"error": "dir must be a string path"}, 400
        # confine client-supplied dirs under PROFILE_BASE: this endpoint
        # is unauthenticated and os.makedirs at an attacker-chosen
        # absolute path is a write primitive (in-process callers of
        # engine.request_profile keep full path freedom)
        norm = os.path.normpath(outdir)
        if os.path.isabs(norm) or norm.split(os.sep, 1)[0] == "..":
            return {
                "error": "dir must be a relative path "
                         f"(captures land under {PROFILE_BASE}/)"
            }, 400
        outdir = os.path.join(PROFILE_BASE, norm)
    try:
        from jax import profiler as _profiler  # noqa: F401

        if not hasattr(_profiler, "start_trace"):
            raise ImportError("jax.profiler.start_trace unavailable")
    except Exception as e:
        return {"error": f"jax profiler unavailable: {e}"}, 501
    armed = {}
    for name, eng in sorted(registered_engines().items()):
        req = getattr(eng, "request_profile", None)
        if callable(req):
            try:
                armed[name] = req(steps, outdir)
            except Exception as e:  # an un-armable engine must not 500
                armed[name] = {"error": str(e)}
    if not armed:
        return {"error": "no profilable engine in this process"}, 501
    return {"armed": armed, "steps": steps}, 200

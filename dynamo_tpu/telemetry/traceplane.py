"""Fleet trace plane: span shipping, cross-process assembly, tail-based
sampling, and per-trace timeline breakdowns.

PR 4 gave every process a private trace ring — one request's spans end
up scattered across the frontend's, the router's, and each worker's
ring, so "show me the assembled trace of last night's p99 request" was
unanswerable. This module closes the loop:

- **Shipping** (every traced process): finished spans land in a bounded
  ship buffer via the trace module's sink hook; the process's telemetry
  shipper (the worker's publish loop, the frontend's ModelWatcher
  shipper, the planner service) drains it on the metrics-frame cadence
  and publishes msgpack batches on the `trace.spans` subject. Fleet
  events (telemetry/events.py) ride the same shipper on `fleet.events`.

- **Assembly** (metrics service): `TraceAssembler` groups incoming
  spans by trace_id, waits a quiet window for stragglers (the child's
  span frame arrives after the finish frame; a disagg prefill span
  crosses a queue hop), then finalizes the trace through the
  tail sampler. Memory is bounded twice: at most `max_open` in-flight
  assemblies (oldest evicted first, finalized as `incomplete` rather
  than dropped silently) and at most `keep` kept traces (LRU).

- **Tail sampling**: `TailSampler` keeps 100% of anomalous traces —
  error/4xx/5xx finishes, deadline expiries, stream replays, retry/
  mark_down dispatches, overloaded bounces, TTFT/e2e above the fleet's
  live SLO-sketch p95, incomplete assemblies — plus a deterministic
  seeded 1-in-N of healthy traffic, so the kept set is small but the
  interesting traces are always in it.

- **Breakdown**: `breakdown(spans)` partitions the root span's wall
  time into queue_wait / prefill / transfer / decode / decode_stall /
  dispatch / preprocess / replay_gap / other from the span tree — the
  machine-readable "where did this request's time go" that
  `GET /v1/traces/{id}` serves and doctor's slow-trace-attribution
  rule folds into its report.

Everything is default-off-safe: with tracing disabled nothing is
buffered or shipped and the token path is bit-identical (pinned in
tests/test_trace_plane.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Iterable, Optional

from dynamo_tpu.telemetry import events as events_mod
from dynamo_tpu.telemetry import trace as trace_mod

__all__ = [
    "TailSampler",
    "TraceAssembler",
    "TelemetryShipper",
    "breakdown",
    "drain_spans",
    "ensure_shipping",
    "pending_spans",
    "ship_once",
    "summarize",
]

#: ship-buffer capacity (finished spans awaiting publish); overflow
#: drops the OLDEST spans — their trace assembles `incomplete`, which
#: the sampler keeps, so loss is visible rather than silent
SHIP_BUFFER_CAP = 4096

_ship_lock = threading.Lock()
_ship_buffer: deque = deque(maxlen=SHIP_BUFFER_CAP)
_shipping_registered = False


def _sink(span_dict: dict) -> None:
    with _ship_lock:
        _ship_buffer.append(span_dict)


def ensure_shipping() -> None:
    """Register the ship buffer as the trace module's span sink (idempotent).
    Costs nothing while tracing is disabled — the sink is only invoked
    for recorded spans."""
    global _shipping_registered
    if not _shipping_registered:
        trace_mod.set_sink(_sink)
        _shipping_registered = True


def disable_shipping() -> None:
    """Unregister + drop the buffer (tests)."""
    global _shipping_registered
    trace_mod.set_sink(None)
    _shipping_registered = False
    with _ship_lock:
        _ship_buffer.clear()


def drain_spans() -> list[dict]:
    with _ship_lock:
        out = list(_ship_buffer)
        _ship_buffer.clear()
    return out


def pending_spans() -> int:
    with _ship_lock:
        return len(_ship_buffer)


async def ship_once(fabric, source: str = "") -> None:
    """Publish any buffered spans + fleet events. One batch per subject
    per call (the metrics-frame cadence keeps batches small). A failed
    publish drops the batch — the trace assembles incomplete and the
    sampler keeps it, which is the honest degradation."""
    import msgpack

    from dynamo_tpu.subjects import (
        FLEET_EVENTS_SUBJECT,
        TRACE_SPANS_SUBJECT,
    )

    spans = drain_spans()
    if spans:
        try:
            await fabric.publish(
                TRACE_SPANS_SUBJECT,
                {"source": source, "count": len(spans)},
                msgpack.packb(spans, use_bin_type=True, default=repr),
            )
        except Exception:
            pass  # dropped batch -> incomplete trace, kept by the sampler
    events = events_mod.drain()
    if events:
        if getattr(fabric, "connected", True) is False:
            # broker-less degraded mode: keep the timeline (bounded) —
            # the degraded/failover events themselves are what must
            # arrive once a broker answers again
            events_mod.requeue(events)
            return
        # one batch frame, like the spans — a coalesced 429 storm must
        # not serialize hundreds of publish round-trips on this loop
        try:
            await fabric.publish(
                FLEET_EVENTS_SUBJECT,
                {"source": source, "count": len(events)},
                msgpack.packb(events, use_bin_type=True, default=repr),
            )
        except Exception:
            events_mod.requeue(events)


class TelemetryShipper:
    """Background shipping loop for processes without a metrics publish
    loop of their own (the HTTP frontend, the planner service). The
    worker piggybacks `ship_once` on its existing `_publish_loop`
    instead."""

    def __init__(self, fabric, source: str = "", interval_s: float = 1.0):
        self.fabric = fabric
        self.source = source
        self.interval_s = interval_s
        self._task = None

    def start(self) -> None:
        import asyncio

        ensure_shipping()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.interval_s)
            await ship_once(self.fabric, self.source)

    async def stop(self, flush: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if flush:
            await ship_once(self.fabric, self.source)


# -- the span-tree breakdown ----------------------------------------------

#: breakdown phase names, in presentation order
BREAKDOWN_PHASES = (
    "preprocess", "dispatch", "queue_wait", "prefill", "transfer",
    "decode", "decode_stall", "replay_gap", "other",
)

#: span names that count as one worker-side "attempt" (a replayed
#: stream has several; the gaps between them are replay_gap)
_ATTEMPT_NAMES = ("engine.generate", "worker.generate", "child.generate")


def _span_end_ts(s: dict) -> float:
    start = float(s.get("start_ts") or 0.0)
    dur = s.get("duration_ms")
    return start + (float(dur) / 1000.0 if dur else 0.0)


def _first_token_ts(s: dict) -> Optional[float]:
    for ev in s.get("events") or ():
        if isinstance(ev, dict) and ev.get("name") == "first_token":
            try:
                return float(ev["ts"])
            except (KeyError, TypeError, ValueError):
                return None
    return None


def _root_of(spans: list[dict]) -> Optional[dict]:
    ids = {s.get("span_id") for s in spans}
    roots = [s for s in spans if s.get("parent_id") not in ids]
    if not roots:
        roots = spans
    for r in roots:
        if r.get("name") == "http.request":
            return r
    return min(
        roots, key=lambda s: float(s.get("start_ts") or 0.0), default=None
    )


def _attempts_of(spans: list[dict]) -> list[dict]:
    """Worker-side attempt spans, deepest available level first:
    engine.generate where present (the jax/external path), else
    worker.generate (mock workers), else child.generate."""
    for name in _ATTEMPT_NAMES:
        hits = [s for s in spans if s.get("name") == name]
        if hits:
            return sorted(
                hits, key=lambda s: float(s.get("start_ts") or 0.0)
            )
    return []


def breakdown(spans: list[dict]) -> Optional[dict]:
    """Partition the root span's wall time into phases, from the span
    tree alone. The phases sum to total_ms exactly (`other` absorbs the
    un-attributed remainder; cross-process clock skew that would push
    the sum past the total is clipped and reported as skew_ms) — the
    reconciliation the acceptance test pins to ±1 ms."""
    spans = [s for s in spans if isinstance(s, dict)]
    root = _root_of(spans)
    if root is None:
        return None
    t0 = float(root.get("start_ts") or 0.0)
    total = float(root.get("duration_ms") or 0.0)
    if total <= 0.0:
        total = max(
            (_span_end_ts(s) for s in spans), default=t0
        ) - t0
        total *= 1000.0
    phases = {p: 0.0 for p in BREAKDOWN_PHASES}

    for s in spans:
        if s.get("name") == "preprocess" and s.get("duration_ms"):
            phases["preprocess"] += float(s["duration_ms"])

    attempts = _attempts_of(spans)
    # remote-prefill hand-offs, attributed inside their enclosing attempt
    remote = [s for s in spans if s.get("name") == "disagg.remote_prefill"]
    remote_prefill = [s for s in spans if s.get("name") == "disagg.prefill"]

    for s in attempts:
        a0 = float(s.get("start_ts") or 0.0)
        dur = float(s.get("duration_ms") or 0.0)
        ft = _first_token_ts(s)
        pre_ms = (
            max(0.0, (ft - a0) * 1000.0) if ft is not None else dur
        )
        pre_ms = min(pre_ms, dur)
        attrs = s.get("attrs") or {}
        qw = min(pre_ms, max(0.0, float(attrs.get("queue_wait_ms") or 0.0)))
        # transfer: the decode-side hand-off window minus the prefill
        # compute nested inside it (the queue ride + KV landing)
        transfer = 0.0
        rprefill = 0.0
        for r in remote:
            r0 = float(r.get("start_ts") or 0.0)
            if not (a0 <= r0 <= _span_end_ts(s) + 1e-9):
                continue
            rdur = float(r.get("duration_ms") or 0.0)
            nested = sum(
                float(p.get("duration_ms") or 0.0)
                for p in remote_prefill
                if r0 <= float(p.get("start_ts") or 0.0)
                <= _span_end_ts(r) + 1e-9
            )
            rprefill += min(nested, rdur)
            transfer += max(0.0, rdur - nested)
        transfer = min(transfer, max(0.0, pre_ms - qw))
        prefill = (
            min(rprefill, max(0.0, pre_ms - qw - transfer))
            if rprefill
            else max(0.0, pre_ms - qw - transfer)
        )
        decode_win = max(0.0, dur - pre_ms)
        stall = min(
            decode_win,
            max(0.0, float(attrs.get("decode_stall_ms") or 0.0)),
        )
        phases["queue_wait"] += qw
        phases["transfer"] += transfer
        phases["prefill"] += prefill
        phases["decode_stall"] += stall
        phases["decode"] += decode_win - stall
        # whatever of the pre-token window queue_wait+transfer+prefill
        # did not explain (disagg queue wait happens remotely) stays in
        # prefill via the else-branch above — nothing is dropped

    for a, b in zip(attempts, attempts[1:]):
        gap = (float(b.get("start_ts") or 0.0) - _span_end_ts(a)) * 1000.0
        if gap > 0.0:
            phases["replay_gap"] += gap

    # router overhead: dispatch start -> first attempt start (pick,
    # connect, retries, backoff) — disjoint from the attempt windows
    dispatches = [s for s in spans if s.get("name") == "router.dispatch"]
    if dispatches and attempts:
        d0 = min(float(s.get("start_ts") or 0.0) for s in dispatches)
        a0 = float(attempts[0].get("start_ts") or 0.0)
        phases["dispatch"] = max(0.0, (a0 - d0) * 1000.0)
    elif dispatches:
        phases["dispatch"] = sum(
            float(s.get("duration_ms") or 0.0) for s in dispatches
        )

    attributed = sum(phases.values())
    skew_ms = 0.0
    if attributed > total:
        # cross-process clock skew (or overlapping spans) pushed the
        # parts past the whole: scale down proportionally so the
        # partition invariant holds, and report the excess honestly
        skew_ms = attributed - total
        if attributed > 0.0:
            scale = total / attributed
            for k in phases:
                phases[k] *= scale
        attributed = total
    phases["other"] = max(0.0, total - attributed)

    ranked = sorted(
        ((k, v) for k, v in phases.items() if k != "other" and v > 0.0),
        key=lambda kv: kv[1], reverse=True,
    )
    return {
        "total_ms": round(total, 3),
        "phases": {k: round(v, 3) for k, v in phases.items()},
        "dominant": ranked[0][0] if ranked else None,
        "attempts": len(attempts),
        **({"skew_ms": round(skew_ms, 3)} if skew_ms else {}),
    }


def summarize(trace_id: str, spans: list[dict]) -> dict:
    """Search-index row for one assembled trace: endpoint/status/worker
    facets + the breakdown, computed once at finalize time."""
    root = _root_of(spans) or {}
    attrs = root.get("attrs") or {}
    workers: set[str] = set()
    services: set[str] = set()
    ttft_ms = None
    t0 = float(root.get("start_ts") or 0.0)
    for s in spans:
        services.add(str(s.get("service") or "?"))
        a = s.get("attrs") or {}
        for key in ("instance_id", "chosen"):
            v = a.get(key)
            if isinstance(v, str) and v:
                workers.add(v)
        if ttft_ms is None and s.get("name") in _ATTEMPT_NAMES:
            ft = _first_token_ts(s)
            if ft is not None and t0:
                ttft_ms = max(0.0, (ft - t0) * 1000.0)
    if attrs.get("ttft_ms") is not None:
        try:
            ttft_ms = float(attrs["ttft_ms"])
        except (TypeError, ValueError):
            pass
    status = attrs.get("http_status")
    if status is None:
        status = (
            "error"
            if any(s.get("status") not in (None, "ok") for s in spans)
            else "ok"
        )
    return {
        "trace_id": trace_id,
        "root": root.get("name"),
        "start_ts": t0,
        "duration_ms": root.get("duration_ms"),
        "status": str(status),
        "endpoint": attrs.get("endpoint"),
        "model": attrs.get("model"),
        "ttft_ms": round(ttft_ms, 3) if ttft_ms is not None else None,
        "spans": len(spans),
        "services": sorted(services),
        "workers": sorted(workers),
        "breakdown": breakdown(spans),
    }


# -- tail-based sampling ---------------------------------------------------

#: span event names that mark a trace anomalous, -> keep reason
_ANOMALY_EVENTS = {
    "replay": "replay",
    "mark_down": "retry",
    "overloaded": "overloaded",
}


def _healthy_hash(trace_id: str, seed: int) -> int:
    import xxhash

    return xxhash.xxh64_intdigest(trace_id.encode(), seed=seed)


class TailSampler:
    """Keep decision over an ASSEMBLED trace (that is what makes it
    tail-based: the decision runs after the outcome is known, not at
    the root). `slo_p95s` is an injected callable returning the live
    fleet p95s ({"ttft_ms": ..., "e2e_ms": ...}, empty when cold) so
    "slow" tracks the fleet's actual distribution, not a static
    threshold; static floors can be layered on via slow_ttft_ms /
    slow_e2e_ms."""

    def __init__(
        self,
        healthy_rate: int = 10,
        seed: int = 0,
        slo_p95s: Optional[Callable[[], dict]] = None,
        slow_ttft_ms: Optional[float] = None,
        slow_e2e_ms: Optional[float] = None,
    ):
        self.healthy_rate = max(0, int(healthy_rate))
        self.seed = int(seed)
        self.slo_p95s = slo_p95s
        self.slow_ttft_ms = slow_ttft_ms
        self.slow_e2e_ms = slow_e2e_ms

    def decide(
        self,
        trace_id: str,
        spans: list[dict],
        incomplete: bool = False,
        summary: Optional[dict] = None,
    ) -> tuple[bool, list[str]]:
        """-> (keep, reasons). Anomalies always keep; a healthy trace
        keeps iff its seeded hash lands in the 1-in-N slot (deterministic
        across restarts and across assemblers sharing the seed).
        `summary` lets the assembler pass its precomputed summarize()
        so a finalize does the O(spans) breakdown work once."""
        reasons: list[str] = []
        if incomplete:
            reasons.append("incomplete")
        root = _root_of(spans) or {}
        attrs = root.get("attrs") or {}
        status = attrs.get("http_status")
        try:
            if status is not None and int(status) >= 400:
                reasons.append(f"http_{int(status)}")
        except (TypeError, ValueError):
            pass
        for s in spans:
            if s.get("status") not in (None, "ok"):
                reasons.append("error")
                break
        for s in spans:
            for ev in s.get("events") or ():
                name = ev.get("name") if isinstance(ev, dict) else None
                reason = _ANOMALY_EVENTS.get(name)
                if reason is not None and reason not in reasons:
                    reasons.append(reason)
                elif (
                    isinstance(name, str)
                    and "deadline" in name
                    and "deadline" not in reasons
                ):
                    reasons.append("deadline")
        if summary is None:
            summary = summarize(trace_id, spans)
        p95s = {}
        if self.slo_p95s is not None:
            try:
                p95s = self.slo_p95s() or {}
            except Exception:
                p95s = {}
        ttft = summary.get("ttft_ms")
        thr_ttft = _min_defined(p95s.get("ttft_ms"), self.slow_ttft_ms)
        if ttft is not None and thr_ttft is not None and ttft > thr_ttft:
            reasons.append("slow_ttft")
        e2e = summary.get("duration_ms")
        thr_e2e = _min_defined(p95s.get("e2e_ms"), self.slow_e2e_ms)
        if e2e is not None and thr_e2e is not None and float(e2e) > thr_e2e:
            reasons.append("slow_e2e")
        if reasons:
            return True, reasons
        if (
            self.healthy_rate > 0
            and _healthy_hash(trace_id, self.seed) % self.healthy_rate == 0
        ):
            return True, ["healthy_sample"]
        return False, []


def _min_defined(*vals: Optional[float]) -> Optional[float]:
    xs = [float(v) for v in vals if v is not None]
    return min(xs) if xs else None


# -- cross-process assembly ------------------------------------------------


class TraceAssembler:
    """Group shipped spans by trace_id, finalize after a quiet window,
    sample, keep. Thread-safe (the metrics service's pump task and its
    HTTP handlers share it).

    Bounds: `max_open` concurrent assemblies (evicting the LRU one
    finalizes it immediately as incomplete=likely — never a silent
    drop), `keep` kept traces, MAX_SPANS_PER_TRACE spans each."""

    MAX_SPANS_PER_TRACE = 512

    def __init__(
        self,
        sampler: Optional[TailSampler] = None,
        window_s: float = 2.0,
        max_age_s: float = 30.0,
        max_open: int = 2048,
        keep: int = 512,
        now_fn: Callable[[], float] = time.monotonic,
    ):
        self.sampler = sampler or TailSampler(
            healthy_rate=int(
                os.environ.get("DYNTPU_TRACE_SAMPLE_RATE", "10") or 10
            )
        )
        self.window_s = window_s
        self.max_age_s = max_age_s
        self.max_open = max_open
        self.keep = keep
        self.now_fn = now_fn
        self._lock = threading.Lock()
        #: trace_id -> [spans, first_seen, last_seen, span_id_set]
        self._open: "OrderedDict[str, list]" = OrderedDict()
        #: trace_id -> {"summary", "spans", "kept_reasons", "incomplete"}
        self._kept: "OrderedDict[str, dict]" = OrderedDict()
        # counters (exposed as dynamo_tpu_trace_* on the metrics service)
        self.spans_received = 0
        self.kept_total: dict[str, int] = {}
        self.dropped_total = 0
        self.incomplete_total = 0
        self.evicted_total = 0

    # -- ingest ------------------------------------------------------------

    def add_spans(self, spans: Iterable[Any]) -> None:
        now = self.now_fn()
        evict: list[tuple[str, list]] = []
        with self._lock:
            for s in spans:
                if not isinstance(s, dict):
                    continue
                tid = s.get("trace_id")
                if not isinstance(tid, str) or not tid:
                    continue
                self.spans_received += 1
                entry = self._open.get(tid)
                if entry is None:
                    if tid in self._kept:
                        # straggler after finalize: attach to the kept
                        # trace so late child frames don't vanish
                        self._attach_straggler(tid, s)
                        continue
                    entry = self._open[tid] = [[], now, now, set()]
                    while len(self._open) > self.max_open:
                        old_tid, old = self._open.popitem(last=False)
                        self.evicted_total += 1
                        evict.append((old_tid, old))
                if len(entry[0]) < self.MAX_SPANS_PER_TRACE:
                    entry[0].append(s)
                    sid = s.get("span_id")
                    if isinstance(sid, str):
                        entry[3].add(sid)
                entry[2] = now
                self._open.move_to_end(tid)
        for tid, entry in evict:
            self._finalize(tid, entry, forced=True)

    @staticmethod
    def _spans_incomplete(spans: list[dict]) -> bool:
        """The structural half of _is_incomplete, reusable after
        straggler attach: more (or fewer) than one root, or a
        mark_down event (a worker vanished mid-trace)."""
        ids = {s.get("span_id") for s in spans}
        roots = sum(
            1
            for s in spans
            if s.get("parent_id") is None or s.get("parent_id") not in ids
        )
        if roots != 1:
            return True
        for s in spans:
            for ev in s.get("events") or ():
                if isinstance(ev, dict) and ev.get("name") == "mark_down":
                    return True
        return False

    def _attach_straggler(self, tid: str, s: dict) -> None:
        """A span arriving AFTER its trace finalized (a shipper on a
        slower cadence than the assembly window): attach it, and
        re-evaluate the incomplete flag — the straggler may be exactly
        the missing stitch, and a now-complete trace must stop reading
        as a lost one. Caller holds the lock."""
        doc = self._kept[tid]
        if len(doc["spans"]) >= self.MAX_SPANS_PER_TRACE:
            return
        doc["spans"].append(s)
        if doc["incomplete"] and not self._spans_incomplete(doc["spans"]):
            doc["incomplete"] = False
            self.incomplete_total = max(0, self.incomplete_total - 1)
        doc["summary"] = {
            **summarize(tid, doc["spans"]),
            "kept_reasons": doc["kept_reasons"],
            "incomplete": doc["incomplete"],
        }

    # -- finalize ----------------------------------------------------------

    def _is_incomplete(self, entry: list) -> bool:
        """A trace is incomplete when a subtree lost its stitch (some
        span's parent never arrived, beyond the one remote root a
        traceparent header explains) or a worker vanished mid-trace
        (a mark_down event: a SIGKILLed worker's in-flight spans never
        end, so they never ship) — the signatures of lost spans."""
        return self._spans_incomplete(entry[0])

    def sweep(self) -> int:
        """Finalize assemblies quiet past the window (or alive past
        max_age). Returns how many finalized."""
        now = self.now_fn()
        done: list[tuple[str, list]] = []
        with self._lock:
            for tid, entry in list(self._open.items()):
                if (
                    now - entry[2] >= self.window_s
                    or now - entry[1] >= self.max_age_s
                ):
                    done.append((tid, entry))
                    del self._open[tid]
        for tid, entry in done:
            self._finalize(tid, entry, forced=False)
        return len(done)

    def flush(self) -> None:
        """Finalize everything now (tests / shutdown)."""
        with self._lock:
            done = list(self._open.items())
            self._open.clear()
        for tid, entry in done:
            self._finalize(tid, entry, forced=False)

    def _finalize(self, tid: str, entry: list, forced: bool) -> None:
        spans = entry[0]
        if not spans:
            return
        incomplete = forced or self._is_incomplete(entry)
        # one summarize() (it owns the O(spans) breakdown) serves both
        # the sampling decision and the kept doc
        summary = summarize(tid, spans)
        keep, reasons = self.sampler.decide(
            tid, spans, incomplete, summary=summary
        )
        if incomplete:
            self.incomplete_total += 1
        if not keep:
            self.dropped_total += 1
            return
        reason = reasons[0] if reasons else "healthy_sample"
        with self._lock:
            self.kept_total[reason] = self.kept_total.get(reason, 0) + 1
            self._kept[tid] = {
                "summary": {
                    **summary,
                    "kept_reasons": reasons,
                    "incomplete": incomplete,
                },
                "spans": spans,
                "kept_reasons": reasons,
                "incomplete": incomplete,
            }
            while len(self._kept) > self.keep:
                self._kept.popitem(last=False)

    # -- queries -----------------------------------------------------------

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            doc = self._kept.get(trace_id)
            if doc is not None:
                return {
                    "trace_id": trace_id,
                    "spans": list(doc["spans"]),
                    "summary": dict(doc["summary"]),
                    "kept_reasons": list(doc["kept_reasons"]),
                    "incomplete": doc["incomplete"],
                }
            entry = self._open.get(trace_id)
            if entry is not None:
                # still assembling: serve what exists, honestly flagged
                return {
                    "trace_id": trace_id,
                    "spans": list(entry[0]),
                    "summary": summarize(trace_id, list(entry[0])),
                    "kept_reasons": [],
                    "incomplete": True,
                    "assembling": True,
                }
        return None

    def search(
        self,
        min_ms: Optional[float] = None,
        status: Optional[str] = None,
        worker: Optional[str] = None,
        endpoint: Optional[str] = None,
        since: Optional[float] = None,
        sort: str = "recent",
        limit: int = 50,
    ) -> list[dict]:
        """Kept-trace summaries matching every given filter. sort:
        `recent` (newest kept first) or `duration` (slowest first) —
        the worst-trace query doctor and fleet_top ride."""
        with self._lock:
            docs = [dict(d["summary"]) for d in self._kept.values()]
        out = []
        for s in docs:
            dur = s.get("duration_ms")
            if min_ms is not None and (dur is None or dur < min_ms):
                continue
            if status is not None and str(s.get("status")) != status:
                continue
            if worker is not None and worker not in (s.get("workers") or ()):
                continue
            if endpoint is not None and s.get("endpoint") != endpoint:
                continue
            if since is not None and float(s.get("start_ts") or 0) < since:
                continue
            out.append(s)
        if sort == "duration":
            out.sort(key=lambda s: float(s.get("duration_ms") or 0.0),
                     reverse=True)
        else:
            out.reverse()  # kept order is oldest-first
        return out[: max(0, limit)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "spans_received_total": self.spans_received,
                "kept_total": dict(self.kept_total),
                "dropped_total": self.dropped_total,
                "incomplete_total": self.incomplete_total,
                "evicted_total": self.evicted_total,
                "open": len(self._open),
                "kept": len(self._kept),
            }

    def expose_lines(self, prefix: str = "dynamo_tpu") -> list[str]:
        st = self.stats()
        lines = [
            f"# TYPE {prefix}_trace_spans_received_total counter",
            f"{prefix}_trace_spans_received_total "
            f"{st['spans_received_total']}",
            f"# TYPE {prefix}_traces_kept_total counter",
        ]
        for reason, n in sorted(st["kept_total"].items()):
            lines.append(
                f'{prefix}_traces_kept_total{{reason="{reason}"}} {n}'
            )
        if not st["kept_total"]:
            lines.append(
                f'{prefix}_traces_kept_total{{reason="healthy_sample"}} 0'
            )
        lines += [
            f"# TYPE {prefix}_traces_dropped_total counter",
            f"{prefix}_traces_dropped_total {st['dropped_total']}",
            f"# TYPE {prefix}_traces_incomplete_total counter",
            f"{prefix}_traces_incomplete_total {st['incomplete_total']}",
            f"# TYPE {prefix}_trace_assembler_open gauge",
            f"{prefix}_trace_assembler_open {st['open']}",
            f"# TYPE {prefix}_trace_assembler_kept gauge",
            f"{prefix}_trace_assembler_kept {st['kept']}",
        ]
        return lines

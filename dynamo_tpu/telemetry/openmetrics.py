"""OpenMetrics rendering of the hand-rolled Prometheus expositions.

The classic 0.0.4 text format has no exemplar syntax — a trailing
`# {trace_id="..."} v ts` on a bucket line makes the classic parser
fail the ENTIRE scrape. So exemplars (the heatmap-spike → assembled-
trace jump, docs/observability.md "Fleet traces & event timeline")
only ride the OpenMetrics rendering, served when the scraper asks for
it via content negotiation — which Prometheus does by default
(`Accept: application/openmetrics-text;version=1.0.0,...`).

`to_openmetrics(classic_text)` converts the classic rendering:
  - counter families declare their name WITHOUT the `_total` suffix
    (OpenMetrics names the family `x`; its samples are `x_total`)
  - the `# EOF` terminator is appended
Histogram/gauge families and all sample lines pass through unchanged
(exemplar tails included). `negotiate(accept_header)` decides which
rendering a request gets.
"""

from __future__ import annotations

import re

#: the content type OpenMetrics responses declare
CONTENT_TYPE = "application/openmetrics-text"
CONTENT_TYPE_FULL = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_COUNTER_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*)_total counter$"
)


def negotiate(accept_header: str | None) -> bool:
    """True when the scraper's Accept header asks for OpenMetrics."""
    return bool(accept_header) and CONTENT_TYPE in accept_header


def to_openmetrics(classic_text: str) -> str:
    """Classic exposition -> OpenMetrics exposition (see module doc)."""
    out = []
    for line in classic_text.splitlines():
        m = _COUNTER_TYPE_RE.match(line)
        if m is not None:
            out.append(f"# TYPE {m.group(1)} counter")
        else:
            out.append(line)
    out.append("# EOF")
    return "\n".join(out) + "\n"

"""Fleet event timeline: structured control-plane events on the fabric.

The self-healing machinery (planner decisions, role flips, handovers,
drains, shed episodes, stream replays, KV-index resyncs) used to emit
only counters — an incident could be graphed but not *reconstructed*.
This module gives every process one cheap, dependency-free call:

    events.record("role_flip", severity="info", source=instance_id,
                  src="prefill", dst="decode")

Events land in a bounded process-local buffer; whichever telemetry
shipper the process runs (the worker's publish loop, the frontend's
ModelWatcher shipper, the planner service) drains the buffer and
publishes batches on the `fleet.events` subject. The metrics service
folds them into a fleet-wide `EventRing` served at
`GET /v1/fleet/events`, exposed as
`dynamo_tpu_fleet_events_total{type,severity}` (the Grafana annotation
layer queries `changes()` over it), and joined to slow traces by time
window (a kept trace's breakdown names the fleet events that overlapped
it — docs/observability.md "Fleet traces & event timeline").

`record()` never raises and never blocks beyond a lock; a full buffer
drops the OLDEST events (the timeline is an operational aid, not a
ledger). Recording is always on — an event is a control-plane fact,
not a trace — but costs one dict + list append per occurrence, and the
noisy per-request sources (shed, replay) coalesce into per-source
episodes so a 429 storm is one event with a count, not ten thousand.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

#: canonical event type names. The Grafana annotation CI gate
#: (tests/test_grafana_dashboards.py) validates every annotation
#: query's `type="..."` matcher against this tuple, so a renamed or
#: mistyped event can't silently blank an annotation layer.
EVENT_TYPES = (
    "planner_decision",   # ControlRunner scale_up/scale_down actuation
    "role_flip",          # worker flipped prefill<->decode in place
    "handover",           # live KV migration phase transitions
    "drain",              # graceful wind-down started (SIGTERM / admin)
    "worker_lost",        # a worker's frames aged out unannounced
    "shed",               # load-shed episode (429s, coalesced)
    "stream_replay",      # a dead worker's stream continued on a survivor
    "kv_resync",          # KV index gap/drift repaired by resync
    # control-plane HA (docs/operations.md "Control-plane HA")
    "broker_promote",     # a warm standby promoted itself to primary
    "broker_demote",      # a stale-fenced broker demoted (split-brain refusal)
    "broker_failover",    # a client's established broker address changed
    "degraded",           # broker-less mode entered/left (phase attr)
    # KV economy (docs/operations.md "The KV economy")
    "kv_migration",       # a hot prefix pushed source->dest (or fallback)
    "kv_demotion",        # TierPolicy demoted cold blocks HBM->host/disk
)

SEVERITIES = ("info", "warning", "critical")

#: process-local buffer capacity (events awaiting shipping)
BUFFER_CAP = 512

_lock = threading.Lock()
_buffer: deque = deque(maxlen=BUFFER_CAP)


def record(
    etype: str,
    severity: str = "info",
    source: str = "",
    coalesce_s: float = 0.0,
    **attrs,
) -> None:
    """Buffer one fleet event for the process's telemetry shipper.

    `coalesce_s`: if the newest buffered event shares (type, source)
    and is younger than this, bump its `count` and refresh its attrs
    instead of appending — per-request sources (shed, replay) become
    per-episode events. Never raises."""
    try:
        now = time.time()
        if severity not in SEVERITIES:
            severity = "info"
        with _lock:
            if coalesce_s > 0.0 and _buffer:
                last = _buffer[-1]
                if (
                    last["type"] == etype
                    and last["source"] == source
                    and now - last["ts"] < coalesce_s
                ):
                    last["count"] = int(last.get("count", 1)) + 1
                    last["severity"] = max(
                        last["severity"], severity,
                        key=SEVERITIES.index,
                    )
                    last["attrs"].update(attrs)
                    return
            _buffer.append(
                {
                    "ts": now,
                    "type": str(etype),
                    "severity": severity,
                    "source": str(source),
                    "count": 1,
                    "attrs": dict(attrs),
                }
            )
    except Exception:
        pass  # telemetry must never take down the caller


def drain() -> list[dict]:
    """Pop every buffered event (the shipper's side of the contract)."""
    with _lock:
        out = list(_buffer)
        _buffer.clear()
    return out


def requeue(batch: list[dict]) -> None:
    """Put drained-but-unshipped events back, in order (a failed publish
    during a broker outage must not eat the timeline — the degraded-mode
    and failover events are exactly what must ship on reconnect). The
    buffer stays bounded: oldest events fall off first."""
    with _lock:
        combined = list(batch) + list(_buffer)
        _buffer.clear()
        _buffer.extend(combined[-BUFFER_CAP:])


def pending() -> int:
    with _lock:
        return len(_buffer)


def reset() -> None:
    """Drop buffered events (tests)."""
    with _lock:
        _buffer.clear()


class EventRing:
    """Bounded fleet-wide event store at the metrics service.

    Events arrive from `fleet.events` publishes (and locally, e.g. the
    aggregator's worker_lost detection); each gets a monotonically
    increasing `id` so `GET /v1/fleet/events?since=<id>` can tail.
    Eviction is oldest-first; the (type, severity) counters stay
    monotonic across eviction — they feed the
    `dynamo_tpu_fleet_events_total` family Grafana's annotation layer
    queries with `changes()`."""

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._next_id = 1
        #: monotonic (type, severity) -> count, eviction-proof
        self.counters: dict[tuple[str, str], int] = {}

    def add(self, event: dict) -> Optional[dict]:
        """Validate + store one event; returns the stored copy (with its
        id) or None when the frame is garbage."""
        if not isinstance(event, dict):
            return None
        etype = event.get("type")
        if not isinstance(etype, str) or not etype:
            return None
        try:
            ts = float(event.get("ts") or time.time())
        except (TypeError, ValueError):
            ts = time.time()
        severity = event.get("severity")
        if severity not in SEVERITIES:
            severity = "info"
        attrs = event.get("attrs")
        stored = {
            "ts": ts,
            "type": etype,
            "severity": severity,
            "source": str(event.get("source") or ""),
            "count": max(1, int(event.get("count") or 1)),
            "attrs": dict(attrs) if isinstance(attrs, dict) else {},
        }
        with self._lock:
            stored["id"] = self._next_id
            self._next_id += 1
            self._events.append(stored)
            key = (etype, severity)
            self.counters[key] = self.counters.get(key, 0) + stored["count"]
        return stored

    def query(
        self,
        since_id: Optional[int] = None,
        since_ts: Optional[float] = None,
        etype: Optional[str] = None,
        severity: Optional[str] = None,
        source: Optional[str] = None,
        limit: int = 200,
    ) -> list[dict]:
        """Newest-last slice of the ring matching every given filter."""
        with self._lock:
            evs = list(self._events)
        out = []
        for e in evs:
            if since_id is not None and e["id"] <= since_id:
                continue
            if since_ts is not None and e["ts"] < since_ts:
                continue
            if etype is not None and e["type"] != etype:
                continue
            if severity is not None and e["severity"] != severity:
                continue
            if source is not None and e["source"] != source:
                continue
            out.append(e)
        return out[-limit:] if limit > 0 else []

    def overlapping(
        self, t0: float, t1: float, pad_s: float = 0.5, limit: int = 32
    ) -> list[dict]:
        """Events inside [t0-pad, t1+pad] — the trace<->timeline join:
        a slow trace's breakdown names the fleet events that were
        happening while it ran."""
        with self._lock:
            evs = list(self._events)
        hits = [e for e in evs if t0 - pad_s <= e["ts"] <= t1 + pad_s]
        return hits[-limit:] if limit > 0 else []

    def expose_lines(self, prefix: str = "dynamo_tpu") -> list[str]:
        """`dynamo_tpu_fleet_events_total{type,severity}` — the Grafana
        annotation layer's query target (changes() over it marks event
        moments on the dashboards)."""
        with self._lock:
            items = sorted(self.counters.items())
        if not items:
            return []
        name = f"{prefix}_fleet_events_total"
        lines = [f"# TYPE {name} counter"]
        for (etype, severity), n in items:
            lines.append(
                f'{name}{{type="{etype}",severity="{severity}"}} {n}'
            )
        return lines

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

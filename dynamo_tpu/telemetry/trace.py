"""Dependency-free distributed tracing: spans, context propagation, ring.

The repo's observability plane is hand-rolled (no prometheus_client, no
opentelemetry in the image) — this module follows suit. One request
produces one TRACE (a 32-hex id minted at the HTTP frontend from an
incoming `traceparent`/`x-request-id` header, or generated); every hop
contributes SPANS (named, timed, attributed) stitched by
(trace_id, parent span id):

  frontend `http.request`
    └─ `preprocess`
    └─ router `router.dispatch` ── `kv.choose` (matched blocks / overlap)
         └─ worker `worker.generate`          (rides fabric metadata)
              └─ engine `engine.generate`
                   └─ ext-child `child.generate`  (rides the external
                                                   wire; shipped back as
                                                   `span` frames)
              └─ disagg `disagg.remote_prefill`   (rides the prefill
                                                   queue item)

Propagation is a contextvar inside a process (everything that runs in
the request's asyncio task sees the current span) and a small wire dict
`{"trace_id", "span_id"}` across processes — carried in the fabric
request-header `metadata` (ingress/PushRouter), the external-engine
`generate` frame, and `RemotePrefillRequest.trace`.

Default OFF: with no env toggle, `span()` yields a shared no-op object,
the contextvar is never touched, and nothing is recorded — serving is
bit-identical. Enable with `DYNTPU_TRACING=1` (ring of 256 traces) or
`DYNTPU_TRACE_RING=<n>` (explicit capacity; 0 keeps tracing off), or
programmatically via `configure()`.

Finished spans land in a bounded in-memory ring keyed by trace_id —
served by `GET /v1/traces/{id}` / `GET /v1/traces?limit=N` on the HTTP
frontend and the metrics service, exportable as Chrome trace-event JSON
(telemetry/chrome_export.py), and joined with JSONL logs for free via
logging_config.JsonlFormatter's trace_id/span_id injection.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import re
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Iterator, Optional

__all__ = [
    "Span",
    "TraceRing",
    "configure",
    "enabled",
    "span",
    "current_span",
    "current_trace_id",
    "set_sink",
    "wire_context",
    "inject",
    "extract",
    "context_from_headers",
    "get_trace",
    "list_traces",
    "record_span_dict",
    "ring",
    "reset",
]

_HEX32 = re.compile(r"^[0-9a-f]{32}$")
_HEX16 = re.compile(r"^[0-9a-f]{16}$")
_TRACEPARENT = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)

#: the single contextvar carrying the active span for this task tree
_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "dyntpu_current_span", default=None
)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed unit of work. Wall-clock anchored at start; duration via
    the monotonic perf counter so clock steps can't produce negative or
    inflated spans. end() is idempotent; the first call records."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "service",
        "start_ts", "duration_ms", "status", "attrs", "events", "_t0",
        "_done",
    )

    def __init__(
        self,
        name: str,
        service: str,
        trace_id: str,
        parent_id: Optional[str] = None,
        attrs: Optional[dict] = None,
    ):
        self.name = name
        self.service = service
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.status = "ok"
        self.attrs: dict[str, Any] = dict(attrs or {})
        self.events: list[dict] = []
        self._done = False

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append(
            {"ts": time.time(), "name": name, "attrs": attrs}
        )

    def wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def end(self, status: Optional[str] = None) -> None:
        if self._done:
            return
        self._done = True
        self.duration_ms = (time.perf_counter() - self._t0) * 1000.0
        if status is not None:
            self.status = status
        _tracer.record(self.to_dict())

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start_ts": self.start_ts,
            "duration_ms": self.duration_ms,
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """Shared do-nothing span: the disabled path allocates nothing and
    never touches the contextvar."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    status = "ok"

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def end(self, status: Optional[str] = None) -> None:
        pass

    def wire(self) -> dict:
        return {}


NOOP_SPAN = _NoopSpan()


class TraceRing:
    """Bounded store of finished spans keyed by trace_id. Capacity counts
    TRACES (insertion order eviction), so one chatty request can't evict
    a thousand quiet ones span-by-span. Thread-safe: spans arrive from
    the event loop and the engine thread alike."""

    #: spans kept per trace — a client that reuses one x-request-id (so
    #: one deterministic trace id) forever must not grow a list without
    #: bound; past the cap new spans are dropped
    MAX_SPANS_PER_TRACE = 512

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, list[dict]]" = OrderedDict()

    def record(self, span_dict: dict) -> None:
        tid = span_dict.get("trace_id")
        if not tid or self.capacity <= 0:
            return
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                while len(self._traces) >= self.capacity:
                    self._traces.popitem(last=False)
                spans = self._traces[tid] = []
            if len(spans) < self.MAX_SPANS_PER_TRACE:
                spans.append(span_dict)

    def get(self, trace_id: str) -> Optional[list[dict]]:
        with self._lock:
            spans = self._traces.get(trace_id)
            return list(spans) if spans is not None else None

    def list(self, limit: int = 50) -> list[dict]:
        """Newest-first trace summaries. Adopted spans are third-party
        input (the external wire) — every field access here tolerates
        missing keys rather than 500ing the /v1/traces endpoint."""
        if limit <= 0:
            return []
        with self._lock:
            items = list(self._traces.items())[-limit:]
        out = []
        for tid, spans in reversed(items):
            # local root: parent absent OR remote (minted from an incoming
            # traceparent header, so the parent span lives upstream)
            local_ids = {s.get("span_id") for s in spans}
            roots = [
                s for s in spans if s.get("parent_id") not in local_ids
            ]
            head = roots[0] if roots else (spans[0] if spans else {})
            out.append(
                {
                    "trace_id": tid,
                    "root": head.get("name"),
                    "service": head.get("service"),
                    "start_ts": min(
                        (
                            s["start_ts"]
                            for s in spans
                            if isinstance(
                                s.get("start_ts"), (int, float)
                            )
                        ),
                        default=None,
                    ),
                    "duration_ms": head.get("duration_ms"),
                    "spans": len(spans),
                    "services": sorted(
                        {str(s.get("service") or "?") for s in spans}
                    ),
                }
            )
        return out

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


class _Tracer:
    def __init__(self) -> None:
        ring_env = os.environ.get("DYNTPU_TRACE_RING", "")
        try:
            ring_size = int(ring_env) if ring_env else 0
        except ValueError:
            ring_size = 0
        toggled = os.environ.get("DYNTPU_TRACING", "").lower() in (
            "1", "true", "yes", "on"
        )
        self.enabled = toggled or ring_size > 0
        self.ring = TraceRing(ring_size if ring_size > 0 else 256)
        #: optional finished-span sink beside the ring — the fleet trace
        #: plane's ship buffer (telemetry/traceplane.py) registers here
        #: so every finished span can ride the fabric to the metrics
        #: service. None (the default) costs one attribute read.
        self.sink = None

    def configure(
        self,
        enabled: Optional[bool] = None,
        ring_size: Optional[int] = None,
    ) -> None:
        if ring_size is not None:
            if ring_size <= 0:
                self.enabled = False
            else:
                self.ring.capacity = ring_size
        if enabled is not None:
            self.enabled = enabled

    def record(self, span_dict: dict) -> None:
        if self.enabled:
            self.ring.record(span_dict)
            sink = self.sink
            if sink is not None:
                try:
                    sink(span_dict)
                except Exception:
                    pass  # shipping must never break span recording


_tracer = _Tracer()
ring = _tracer.ring


def configure(
    enabled: Optional[bool] = None, ring_size: Optional[int] = None
) -> None:
    """Programmatic toggle (the CLI's --trace flag; tests)."""
    _tracer.configure(enabled=enabled, ring_size=ring_size)


def enabled() -> bool:
    return _tracer.enabled


def reset() -> None:
    """Drop all recorded traces (tests)."""
    _tracer.ring.clear()


def current_span() -> Optional[Span]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    """The active trace id, or None (always None when tracing is off) —
    the exemplar hook for the phase histograms: one enabled-flag check
    plus a contextvar read, cheap enough for per-observe use."""
    if not _tracer.enabled:
        return None
    cur = _current.get()
    return cur.trace_id if cur is not None else None


def set_sink(sink) -> None:
    """Register (or clear, with None) the finished-span sink the fleet
    trace plane ships from. At most one sink; last call wins."""
    _tracer.sink = sink


def _resolve_parent(parent: Any) -> tuple[Optional[str], Optional[str]]:
    """-> (trace_id, parent span_id) from a Span, a wire dict, or None."""
    if isinstance(parent, Span):
        return parent.trace_id, parent.span_id
    if isinstance(parent, dict):
        tid = parent.get("trace_id")
        if isinstance(tid, str) and _HEX32.match(tid):
            sid = parent.get("span_id")
            if not (isinstance(sid, str) and _HEX16.match(sid)):
                sid = None
            return tid, sid
    return None, None


@contextlib.contextmanager
def span(
    name: str,
    service: str = "app",
    parent: Any = None,
    attrs: Optional[dict] = None,
) -> Iterator[Span]:
    """Open a span as the task's current one. Parent resolution: the
    explicit `parent` (a Span or wire dict) wins; else the contextvar's
    current span; else this starts a fresh trace — an absent or corrupt
    upstream context degrades to a new root, never an error."""
    if not _tracer.enabled:
        yield NOOP_SPAN  # type: ignore[misc]
        return
    trace_id, parent_id = _resolve_parent(parent)
    if trace_id is None:
        cur = _current.get()
        if cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            trace_id = new_trace_id()
    sp = Span(name, service, trace_id, parent_id=parent_id, attrs=attrs)
    token = _current.set(sp)
    status: Optional[str] = None
    try:
        yield sp
    except BaseException as e:  # noqa: BLE001 — status tagging; re-raised
        if isinstance(e, Exception):
            sp.set_attr("error", f"{type(e).__name__}: {e}")
            status = "error"
        else:
            status = "cancelled"
        raise
    finally:
        try:
            _current.reset(token)
        except ValueError:
            # a span opened inside a generator can be finalized from a
            # different context (event-loop-driven aclose); the var copy
            # dies with that context, so a failed reset is harmless —
            # recording the span still matters
            pass
        sp.end(status)


def wire_context() -> Optional[dict]:
    """The current span as a wire dict, or None (also None when off)."""
    if not _tracer.enabled:
        return None
    cur = _current.get()
    return cur.wire() if cur is not None else None


def inject(metadata: dict) -> dict:
    """Put the current trace context into a fabric-metadata-style dict
    (mutates and returns it). No-op when tracing is off or no span is
    active — remote peers then see no `trace` key at all."""
    ctx = wire_context()
    if ctx:
        metadata["trace"] = ctx
    return metadata


def extract(metadata: Any) -> Optional[dict]:
    """The inverse of inject: a validated wire dict or None. Malformed
    values degrade to None (fresh trace downstream), never raise."""
    if not isinstance(metadata, dict):
        return None
    ctx = metadata.get("trace")
    tid, sid = _resolve_parent(ctx if isinstance(ctx, dict) else None)
    if tid is None:
        return None
    return {"trace_id": tid, "span_id": sid}


def context_from_headers(headers: Any) -> Optional[dict]:
    """Mint the frontend's trace context from HTTP headers.

    `traceparent` (W3C: 00-<trace32>-<span16>-<flags>) wins; else an
    `x-request-id` becomes the trace id (verbatim if it already is 32
    lowercase hex, else hashed to 32 hex so the id is deterministic and
    greppable from the original). Absent/malformed headers -> None (the
    caller starts a fresh root trace)."""
    try:
        tp = headers.get("traceparent")
        if tp:
            m = _TRACEPARENT.match(tp.strip().lower())
            if m:
                return {"trace_id": m.group(1), "span_id": m.group(2)}
        rid = headers.get("x-request-id")
        if rid:
            rid = rid.strip()
            if _HEX32.match(rid):
                return {"trace_id": rid, "span_id": None}
            digest = hashlib.md5(rid.encode()).hexdigest()
            return {"trace_id": digest, "span_id": None}
    except Exception:
        return None
    return None


def record_span_dict(span_dict: Any) -> None:
    """Adopt an already-finished span produced by another process (the
    external-engine child ships these over the wire). Validated loosely;
    garbage is dropped, not raised."""
    if not _tracer.enabled or not isinstance(span_dict, dict):
        return
    tid = span_dict.get("trace_id")
    if not (isinstance(tid, str) and _HEX32.match(tid)):
        return
    # through record(), not the ring directly: adopted spans must reach
    # the fleet trace plane's ship sink like locally-finished ones
    _tracer.record(span_dict)


def get_trace(trace_id: str) -> Optional[list[dict]]:
    return _tracer.ring.get(trace_id)


def list_traces(limit: int = 50) -> list[dict]:
    return _tracer.ring.list(limit)

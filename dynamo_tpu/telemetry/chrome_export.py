"""Chrome trace-event export: one trace -> a JSON document loadable in
Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Mapping: every service (frontend / router / worker / engine / ext-child
/ prefill) becomes a pid with a process_name metadata event; every span
becomes a complete ("ph": "X") event on its own tid lane within that
pid (lanes keep concurrent spans of one service from visually merging);
span events become instant ("ph": "i") events on the same lane. ts/dur
are integer MICROSECONDS with ts anchored at each span's wall-clock
start — cross-process spans line up as well as the hosts' clocks do.

Usage:
  python -m dynamo_tpu.telemetry.chrome_export <trace_id> \
      [--url http://127.0.0.1:8080] [-o out.json]
or in-process: `export_trace(trace_id)` writes `<trace_id>.json`.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from dynamo_tpu.telemetry import trace as _trace


def to_chrome_trace(spans: list[dict]) -> dict:
    """Span dicts (trace.Span.to_dict shape) -> trace-event JSON doc."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    lanes: dict[int, int] = {}  # pid -> next tid lane
    for s in sorted(spans, key=lambda s: s.get("start_ts") or 0.0):
        service = str(s.get("service") or "app")
        pid = pids.setdefault(service, len(pids) + 1)
        if pid not in lanes:
            events.append(
                {
                    "ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "args": {"name": service},
                }
            )
            lanes[pid] = 0
        lanes[pid] += 1
        tid = lanes[pid]
        ts_us = int(float(s.get("start_ts") or 0.0) * 1e6)
        dur_us = max(1, int(float(s.get("duration_ms") or 0.0) * 1e3))
        events.append(
            {
                "name": str(s.get("name") or "span"),
                "cat": service,
                "ph": "X",
                "ts": ts_us,
                "dur": dur_us,
                "pid": pid,
                "tid": tid,
                "args": {
                    "span_id": s.get("span_id"),
                    "parent_id": s.get("parent_id"),
                    "status": s.get("status"),
                    **(s.get("attrs") or {}),
                },
            }
        )
        for ev in s.get("events") or ():
            events.append(
                {
                    "name": str(ev.get("name") or "event"),
                    "cat": service,
                    "ph": "i",
                    "s": "t",
                    "ts": int(float(ev.get("ts") or 0.0) * 1e6),
                    "pid": pid,
                    "tid": tid,
                    "args": dict(ev.get("attrs") or {}),
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_trace(
    trace_id: str,
    path: Optional[str] = None,
    spans: Optional[list[dict]] = None,
) -> str:
    """Write `<trace_id>.json` (or `path`) for one recorded trace from
    this process's ring (or an explicit span list). Returns the path;
    raises KeyError when the trace is unknown."""
    if spans is None:
        spans = _trace.get_trace(trace_id)
    if spans is None:
        raise KeyError(f"trace {trace_id!r} not in the ring")
    path = path or f"{trace_id}.json"
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    return path


def main(argv: Optional[list[str]] = None) -> None:
    import argparse
    import urllib.request

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace_id")
    p.add_argument(
        "--url", default=os.environ.get(
            "DYNTPU_TRACE_URL", "http://127.0.0.1:8080"
        ),
        help="base URL of a frontend/metrics service serving /v1/traces",
    )
    p.add_argument("-o", "--output", default=None)
    args = p.parse_args(argv)
    with urllib.request.urlopen(
        f"{args.url}/v1/traces/{args.trace_id}", timeout=10
    ) as resp:
        doc = json.loads(resp.read())
    path = export_trace(
        args.trace_id, path=args.output, spans=doc["spans"]
    )
    print(path)


if __name__ == "__main__":
    main()

"""Perf-regression ledger: schema-versioned performance rows on disk.

Every benchmark surface in the repo (bench.py, scripts/
tpu_decode_profile.py, scripts/tpu_round.sh) appends one row per run to
``artifacts/perf_ledger.jsonl`` — an append-only JSONL file that turns
the scattered BENCH_r*.json / artifacts/tpu/*.json artifacts into one
diffable performance history. ``scripts/perf_diff.py`` compares any two
rounds (or a round vs BASELINE.json) with per-metric tolerance bands
and exits nonzero on regression; the doctor's perf-regression rule
wraps the same comparison (docs/observability.md "Reading the perf
plane").

Row schema (version 1):

  {"schema": 1, "round": "r03", "source": "bench", "ok": true,
   "platform": "tpu", "ts": null,
   "config": {"model": "tiny", "isl": 64, ...},
   "fingerprint": "1a2b3c4d5e6f",      # sha256 of canonical config
   "metrics": {"tok_s": 651.55, "mfu": 0.021, ...},
   "note": null}

``metrics`` is an open name→number map — rows carry whatever the
producing surface measured (tok_s, p50_ttft_s, p50_itl_s, mfu,
ms_per_dispatch, attainment, hbm_peak_bytes, ...). A failed run still
gets a row (``ok: false``, empty metrics, the error in ``note``) so the
ledger records that the round happened; diffs treat such rows as having
nothing to compare. ``config`` + ``fingerprint`` let a diff flag
apples-to-oranges comparisons (different model/workload) instead of
silently reporting a "regression" that is really a config change.

The direction table below says which way is better per metric — a diff
without it can't tell a tok/s drop from a TTFT drop.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import Optional

SCHEMA_VERSION = 1

#: repo-relative default; producers resolve against the repo root (the
#: directory bench.py runs from) so rows from every surface land in ONE
#: file
DEFAULT_LEDGER = os.path.join("artifacts", "perf_ledger.jsonl")

#: +1 = higher is better (throughput-like), -1 = lower is better
#: (latency/footprint-like). Metrics absent here are reported in diffs
#: but never flagged as regressions — direction unknown.
METRIC_DIRECTION = {
    "tok_s": +1,
    "mfu": +1,
    "attainment": +1,
    "vs_baseline": +1,
    "spec_accept_rate": +1,
    "p50_ttft_s": -1,
    "p50_itl_s": -1,
    "ms_per_dispatch": -1,
    "ms_per_token_row": -1,
    "hbm_peak_bytes": -1,
    "compile_ms": -1,
}

#: fractional tolerance band per metric before a worse-direction delta
#: counts as a regression. Throughput on shared CI boxes jitters a few
#: percent run-to-run (BENCH_r04→r05 moved 12% on the same code); the
#: default band is deliberately wider than single-run noise.
DEFAULT_TOLERANCE = 0.08
METRIC_TOLERANCE = {
    "tok_s": 0.08,
    "mfu": 0.08,
    "attainment": 0.05,
    "p50_ttft_s": 0.15,
    "p50_itl_s": 0.15,
    "ms_per_dispatch": 0.15,
    "hbm_peak_bytes": 0.02,
}

_REQUIRED_FIELDS = ("schema", "round", "source", "ok", "metrics", "config")


def config_fingerprint(config: dict) -> str:
    """Stable 12-hex-digit fingerprint of a config dict (sorted-key
    canonical JSON). Two rows with the same fingerprint measured the
    same workload; differing fingerprints make a diff advisory."""
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def make_row(
    round_name: str,
    source: str,
    metrics: dict,
    config: dict,
    ok: bool = True,
    platform: Optional[str] = None,
    ts: Optional[str] = None,
    note: Optional[str] = None,
) -> dict:
    """Build a schema-current row. ``metrics`` values must be finite
    numbers; Nones and NaNs are dropped rather than stored (a diff
    can't band-compare them)."""
    clean = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if v != v:  # NaN
            continue
        clean[str(k)] = v
    config = dict(config or {})
    return {
        "schema": SCHEMA_VERSION,
        "round": str(round_name),
        "source": str(source),
        "ok": bool(ok),
        "platform": platform,
        "ts": ts,
        "config": config,
        "fingerprint": config_fingerprint(config),
        "metrics": clean,
        "note": note,
    }


def validate_row(row: dict) -> list:
    """Schema check → list of human-readable problems (empty = valid)."""
    errs = []
    if not isinstance(row, dict):
        return ["row is not an object"]
    for f in _REQUIRED_FIELDS:
        if f not in row:
            errs.append(f"missing field {f!r}")
    if errs:
        return errs
    if row["schema"] != SCHEMA_VERSION:
        errs.append(
            f"schema {row['schema']!r} != {SCHEMA_VERSION} "
            "(bump needs a migration note in docs/migrating.md)"
        )
    if not isinstance(row["round"], str) or not row["round"]:
        errs.append("round must be a non-empty string")
    if not isinstance(row["ok"], bool):
        errs.append("ok must be a bool")
    if not isinstance(row["metrics"], dict):
        errs.append("metrics must be an object")
    else:
        for k, v in row["metrics"].items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                errs.append(f"metric {k!r} is not a number")
    if not isinstance(row["config"], dict):
        errs.append("config must be an object")
    elif row.get("fingerprint") != config_fingerprint(row["config"]):
        errs.append("fingerprint does not match config")
    return errs


def append_row(row: dict, path: str = DEFAULT_LEDGER) -> None:
    """Validate then append one JSON line. Raises ValueError on an
    invalid row — a corrupt producer must fail loudly, not poison the
    ledger every run."""
    errs = validate_row(row)
    if errs:
        raise ValueError(f"invalid ledger row: {'; '.join(errs)}")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")


def read_rows(path: str, strict: bool = False):
    """Read a ledger → (rows, problems). Tolerant by default: a
    malformed line is reported in ``problems`` and skipped, so one bad
    append never bricks every future diff. ``strict=True`` raises
    instead (the schema round-trip test uses it)."""
    rows, problems = [], []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                problems.append(f"line {ln}: bad JSON ({e})")
                if strict:
                    raise ValueError(problems[-1])
                continue
            errs = validate_row(row)
            if errs:
                problems.append(f"line {ln}: {'; '.join(errs)}")
                if strict:
                    raise ValueError(problems[-1])
                continue
            rows.append(row)
    return rows, problems


def rows_by_round(rows) -> dict:
    """round → latest row for that round (file order; last wins —
    re-running a round supersedes its earlier rows)."""
    out: dict = {}
    for r in rows:
        out[r["round"]] = r
    return out


def compare_rows(row_a: dict, row_b: dict, tolerance: dict = None) -> dict:
    """Pure comparison → {"comparable", "advisory", "rows": [...],
    "regressions": [names]}. Shared by scripts/perf_diff.py and the
    doctor's perf-regression rule."""
    tol = dict(tolerance or {})
    out = {
        "round_a": row_a["round"], "round_b": row_b["round"],
        "comparable": True, "advisory": False, "note": None,
        "rows": [], "regressions": [],
    }
    if not row_a["ok"] or not row_b["ok"]:
        bad = row_a["round"] if not row_a["ok"] else row_b["round"]
        out["comparable"] = False
        out["note"] = f"round {bad} failed (ok=false) — nothing to compare"
        return out
    if row_a.get("fingerprint") != row_b.get("fingerprint"):
        # e.g. TPU round vs CPU-fallback round: report deltas but never
        # fail CI over a workload change
        out["advisory"] = True
        out["note"] = (
            "config fingerprints differ "
            f"({row_a.get('fingerprint')} vs {row_b.get('fingerprint')}) — "
            "advisory only, no regression verdicts"
        )
    shared = sorted(set(row_a["metrics"]) & set(row_b["metrics"]))
    if not shared:
        out["comparable"] = False
        out["note"] = out["note"] or "no shared metrics between rounds"
        return out
    for name in shared:
        a, b = float(row_a["metrics"][name]), float(row_b["metrics"][name])
        direction = METRIC_DIRECTION.get(name)
        band = tol.get(
            name,
            METRIC_TOLERANCE.get(
                name, DEFAULT_TOLERANCE
            ),
        )
        rel = (b - a) / abs(a) if a else (0.0 if b == a else float("inf"))
        # worse-direction magnitude: positive means B is worse than A
        worse = -rel * direction if direction else 0.0
        verdict = "n/a"
        if direction is not None:
            if worse > band:
                verdict = "REGRESSION"
            elif worse < -band:
                verdict = "improved"
            else:
                verdict = "ok"
        out["rows"].append({
            "metric": name, "a": a, "b": b,
            "rel": rel, "band": band, "verdict": verdict,
        })
        if verdict == "REGRESSION" and not out["advisory"]:
            out["regressions"].append(name)
    # one-sided metrics: visible, never verdicted
    for name in sorted(set(row_a["metrics"]) ^ set(row_b["metrics"])):
        side = "a" if name in row_a["metrics"] else "b"
        out["rows"].append({
            "metric": name,
            "a": row_a["metrics"].get(name),
            "b": row_b["metrics"].get(name),
            "rel": None, "band": None,
            "verdict": f"only in {side}",
        })
    return out


# -- producers: one row builder per benchmark surface ----------------------

#: bench.py extras keys that are workload identity (the config
#: fingerprint), not measurements. attention_impl is deliberately NOT
#: here — it records which impl bench CHOSE (code behavior, not
#: workload), and fingerprinting it would make every auto-selection
#: change look like a different workload. kv_quantize IS identity: a
#: quantized-KV run must not diff clean against an unquantized one.
_BENCH_CONFIG_KEYS = (
    "platform", "model", "params", "num_requests", "isl", "osl",
    "kv_quantize",
)

#: bench.py payload/extras keys that are band-comparable measurements
_BENCH_METRIC_KEYS = (
    "p50_ttft_s", "p50_itl_s", "mfu", "attainment", "hbm_peak_bytes",
    "decode_dispatch_ms", "decode_sync_ms", "decode_host_ms",
)


def row_from_bench(doc: dict, round_name: str, source: str = "bench") -> dict:
    """Build a row from a bench.py emission — either the bare payload
    ``{"metric", "value", "unit", "vs_baseline", "extras"}`` or the
    BENCH_r*.json driver wrapper ``{"n", "cmd", "rc", "tail",
    "parsed"}``. A failed round (rc != 0 / parsed null) becomes an
    ``ok: false`` row with the error's last line in ``note`` — the
    ledger records every round, diffs skip the empty ones."""
    payload = doc
    note = None
    if "parsed" in doc or "rc" in doc:  # driver wrapper
        payload = doc.get("parsed")
        if payload is None or doc.get("rc", 0) != 0:
            tail = (doc.get("tail") or "").strip().splitlines()
            note = tail[-1][:200] if tail else "round failed, no output"
            return make_row(
                round_name, source, {}, {"cmd": doc.get("cmd")},
                ok=False, note=note,
            )
    extras = payload.get("extras") or {}
    config = {"metric": payload.get("metric"), "unit": payload.get("unit")}
    for k in _BENCH_CONFIG_KEYS:
        if k in extras:
            config[k] = extras[k]
    metrics = {"tok_s": payload.get("value")}
    if "vs_baseline" in payload:
        metrics["vs_baseline"] = payload["vs_baseline"]
    for k in _BENCH_METRIC_KEYS:
        if k in extras:
            metrics[k] = extras[k]
    return make_row(
        round_name, source, metrics, config,
        ok="error" not in payload,
        platform=extras.get("platform"),
        note=payload.get("error"),
    )


def row_from_decode_profile(doc: dict, round_name: str) -> dict:
    """Build a row from scripts/tpu_decode_profile.py's
    decode_profile.json: headline tok_s / ms_per_dispatch from the
    LARGEST batch's best impl (the serving-shaped point), per-impl
    detail under prefixed names."""
    batches = doc.get("batches") or {}
    config = {
        "platform": doc.get("platform"),
        "model": doc.get("model"),
        "k_steps": doc.get("k_steps"),
        "batches": sorted(batches, key=lambda b: int(b)),
    }
    metrics: dict = {}
    if batches:
        largest = max(batches, key=lambda b: int(b))
        row = batches[largest]
        best = None
        for impl in ("xla", "pallas"):
            full = row.get(f"full_{impl}") or {}
            pure = row.get(f"pure_{impl}") or {}
            if "tok_s" in full:
                metrics[f"{impl}_tok_s"] = full["tok_s"]
            if "ms_per_dispatch" in pure:
                metrics[f"{impl}_ms_per_dispatch"] = pure["ms_per_dispatch"]
            if "tok_s" in full and (best is None or full["tok_s"] > best[0]):
                best = (full["tok_s"], pure.get("ms_per_dispatch"))
        if best is not None:
            metrics["tok_s"] = best[0]
            if best[1] is not None:
                metrics["ms_per_dispatch"] = best[1]
    return make_row(
        round_name, "decode_profile", metrics, config,
        ok=bool(metrics), platform=doc.get("platform"),
        note=None if metrics else "no batches profiled",
    )


def row_from_baseline(doc: dict, round_name: str = "BASELINE") -> dict:
    """Pseudo-row from BASELINE.json's ``published`` block so perf_diff
    can compare a live round against the repo's recorded bar."""
    pub = doc.get("published") or {}
    metrics = {
        "tok_s": pub.get("output_tok_s_per_chip"),
        "p50_ttft_s": pub.get("p50_ttft_s"),
        "mfu": pub.get("mfu"),
    }
    config = {
        "metric": "output_tok_s_per_chip",
        "workload": pub.get("workload"),
        "platform": "tpu",
    }
    return make_row(
        round_name, "baseline", metrics, config, platform="tpu",
        note=pub.get("recorded"),
    )


def main(argv=None) -> int:
    """CLI for shell producers (scripts/tpu_round.sh):
    ``python -m dynamo_tpu.telemetry.perf_ledger --append-bench
    artifacts/tpu/bench_1b.json --round r06`` appends one validated
    row; --append-decode-profile does the same for profile JSON."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--append-bench", metavar="FILE")
    ap.add_argument("--append-decode-profile", metavar="FILE")
    ap.add_argument("--round", dest="round_name")
    ap.add_argument("--source", default=None)
    ap.add_argument("--ledger", default=DEFAULT_LEDGER)
    args = ap.parse_args(argv)
    src = args.append_bench or args.append_decode_profile
    if not src or not args.round_name:
        ap.error("need --round and one of --append-bench / "
                 "--append-decode-profile")
    try:
        with open(src) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_ledger: cannot read {src}: {e}", file=sys.stderr)
        return 1
    if args.append_bench:
        row = row_from_bench(doc, args.round_name,
                             source=args.source or "bench")
    else:
        row = row_from_decode_profile(doc, args.round_name)
    append_row(row, args.ledger)
    print(f"perf_ledger: appended round={row['round']} "
          f"source={row['source']} ok={row['ok']} "
          f"metrics={sorted(row['metrics'])} -> {args.ledger}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Stall watchdog: per-request progress monitoring + structured
diagnosis of wedged streams.

The SLO plane (telemetry/slo.py) says a worker's ITL p95 regressed; the
trace ring says where one request went. Neither fires when a stream
simply STOPS — a wedged device tunnel, a deadlocked engine thread, an
admission that never happens — the client just hangs. The watchdog
closes that gap:

- every streamed request is `track()`ed when its output queue opens and
  `progress()`ed on each emission (engine-thread side, a dict write);
- the engine loop brackets each dispatch with `step_begin()/step_end()`
  so a dispatch that never returns is distinguishable from an idle
  engine;
- a checker (asyncio task on the worker's event loop — deliberately NOT
  the engine thread, which is the thing being watched) compares each
  request's last-progress age against N× the SLO plane's live ITL
  estimate (clamped to a floor), and emits a structured diagnosis when
  it trips: the cause, the flight-recorder window around the stall, the
  request's trace/span ids (PR 4), and all-thread Python stacks via
  `sys._current_frames` (the dependency-free sibling of
  `faulthandler.dump_traceback`).

Diagnoses go to the JSONL log plane (logging_config.JsonlFormatter
merges the `stall` extra into the record) and bump the process-global
`dynamo_tpu_stalls_total{cause}` counter exposed on both Prometheus
surfaces. Default is diagnose-only: the stream is left alone (the stall
may be a 40 s XLA compile). With a hard deadline configured
(`EngineConfig.stall_hard_deadline_s` / `--stall-hard-deadline`), a
request stalled past the deadline is error-finished through its output
queue — the client gets an error frame instead of hanging forever —
and aborted from the scheduler.

Causes (machine-readable, the `{cause}` label):
  queue_wait      no first emission within the queue-wait budget
  stalled_stream  emissions started, then stopped for > threshold
  engine_stuck    a dispatch entered the engine and never returned
                  (attributed to every tracked request; the engine
                  thread's stack in the diagnosis says where it sits)
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import traceback
from typing import Callable, Optional

logger = logging.getLogger(__name__)

#: flight records included in a diagnosis window
DIAGNOSIS_FLIGHT_RECORDS = 32

#: cap on formatted stack depth per thread (diagnoses ride the JSONL
#: log plane; an unbounded recursion must not produce a 1 MB record)
_MAX_STACK_FRAMES = 40


class StallCounters:
    """Process-global `dynamo_tpu_stalls_total{cause}` counters —
    the phases-histogram pattern: module-level, appended to every
    Prometheus surface the process serves."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_cause: dict[str, int] = {}

    def bump(self, cause: str) -> None:
        with self._lock:
            self._by_cause[cause] = self._by_cause.get(cause, 0) + 1

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._by_cause)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._by_cause.values())

    def reset(self) -> None:
        with self._lock:
            self._by_cause.clear()

    def expose_lines(self) -> list[str]:
        snap = self.snapshot()
        if not snap:
            return []
        name = "dynamo_tpu_stalls_total"
        lines = [f"# TYPE {name} counter"]
        for cause, n in sorted(snap.items()):
            lines.append(f'{name}{{cause="{cause}"}} {n}')
        return lines


stall_counters = StallCounters()


def thread_stacks(max_frames: int = _MAX_STACK_FRAMES) -> dict[str, str]:
    """All-thread Python stacks, keyed `"<name>-<ident>"`. The engine
    thread's entry is the "where is it stuck" evidence when a dispatch
    wedges inside jax/XLA/the device tunnel."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        stack = traceback.format_stack(frame)[-max_frames:]
        out[f"{names.get(tid, 'thread')}-{tid}"] = "".join(stack)
    return out


class _Tracked:
    __slots__ = ("request_id", "trace", "first_seen", "last_progress",
                 "emissions", "diagnosed", "wedged")

    def __init__(self, request_id: str, trace: Optional[dict], now: float):
        self.request_id = request_id
        self.trace = trace
        self.first_seen = now
        self.last_progress: Optional[float] = None  # None until 1st token
        self.emissions = 0
        self.diagnosed = False
        self.wedged = False


class StallWatchdog:
    """One per engine runner. Thread-safe on the ingest side (track/
    progress/done/step_begin/step_end are dict writes under a lock);
    `check()` is pure-ish (reads state, emits diagnoses) so tests can
    drive it with an injected clock without the asyncio wrapper."""

    CAUSES = ("queue_wait", "stalled_stream", "engine_stuck")

    def __init__(
        self,
        itl_estimate_ms: Optional[Callable[[], Optional[float]]] = None,
        flight=None,
        stall_factor: float = 32.0,
        stall_min_s: float = 5.0,
        queue_wait_budget_s: float = 120.0,
        hard_deadline_s: Optional[float] = None,
        on_wedged: Optional[Callable[[str, dict], None]] = None,
        interval_s: float = 1.0,
        clock=time.monotonic,
        counters: Optional[StallCounters] = None,
        window_steps: Optional[Callable[[], int]] = None,
    ):
        #: live ITL estimate (ms) from the SLO plane; None = no traffic
        #: yet, fall back to the floor
        self._itl_estimate_ms = itl_estimate_ms
        #: live emission window size (tokens per host visit): 1 for the
        #: classic per-token loop, K under on-device K-step decode
        #: windows (EngineConfig.decode_kstep). A healthy K-window
        #: stream emits every K×ITL, so the stall factor is floored at
        #: 2K — otherwise a configured factor below K would diagnose
        #: every healthy stream as stalled.
        self._window_steps = window_steps
        self.flight = flight
        self.stall_factor = stall_factor
        self.stall_min_s = stall_min_s
        self.queue_wait_budget_s = queue_wait_budget_s
        self.hard_deadline_s = hard_deadline_s
        self.on_wedged = on_wedged
        self.interval_s = interval_s
        self._clock = clock
        #: per-watchdog counters (each worker's metrics frame reports its
        #: own); the process-global `stall_counters` is bumped alongside
        #: for the Prometheus surfaces
        self.counters = counters if counters is not None else StallCounters()
        self._lock = threading.Lock()
        self._tracked: dict[str, _Tracked] = {}
        #: engine-dispatch liveness: perf time the current step entered
        #: the engine, or None when no dispatch is in flight
        self._step_started: Optional[float] = None
        self._task = None
        #: diagnoses emitted since boot (bounded; /v1/debug consumers +
        #: tests read it)
        self.diagnoses: list[dict] = []
        self._max_diagnoses = 64

    # -- ingest (any thread) ----------------------------------------------

    def track(self, request_id: str, trace: Optional[dict] = None) -> None:
        with self._lock:
            self._tracked[request_id] = _Tracked(
                request_id, trace, self._clock()
            )

    def progress(self, request_id: str) -> None:
        with self._lock:
            t = self._tracked.get(request_id)
            if t is not None:
                t.last_progress = self._clock()
                t.emissions += 1
                t.diagnosed = False  # recovered: re-arm

    def done(self, request_id: str) -> None:
        with self._lock:
            self._tracked.pop(request_id, None)

    def step_begin(self) -> None:
        with self._lock:
            self._step_started = self._clock()

    def step_end(self) -> None:
        with self._lock:
            self._step_started = None

    # -- judgement ---------------------------------------------------------

    def stall_threshold_s(self) -> float:
        """N× the SLO plane's live ITL estimate, floored at stall_min_s
        (cold engines / first compiles legitimately take seconds). Under
        K-step decode windows the factor itself is floored at 2× the
        live window size — emissions arrive once per K tokens, so K×ITL
        gaps are the healthy cadence, not a stall."""
        est = None
        if self._itl_estimate_ms is not None:
            try:
                est = self._itl_estimate_ms()
            except Exception:
                est = None
        if est is None or est <= 0:
            return self.stall_min_s
        factor = self.stall_factor
        if self._window_steps is not None:
            try:
                k = int(self._window_steps())
            except Exception:
                k = 1
            if k > 1:
                factor = max(factor, 2.0 * k)
        return max(self.stall_min_s, factor * est / 1000.0)

    def check(self, now: Optional[float] = None) -> list[dict]:
        """One watchdog pass: returns the NEW diagnoses (already logged
        and counted). Hard-deadline wedge actions fire from here too."""
        now = self._clock() if now is None else now
        threshold = self.stall_threshold_s()
        with self._lock:
            step_started = self._step_started
            tracked = list(self._tracked.values())
        engine_stuck = (
            step_started is not None
            and now - step_started > max(threshold, self.stall_min_s)
        )
        out: list[dict] = []
        #: (flight window, stacks) captured ONCE per pass — a wedged
        #: dispatch with N concurrent streams must not format N stack
        #: dumps and N ring snapshots in one checker tick
        evidence: Optional[tuple] = None
        for t in tracked:
            if t.wedged:
                continue
            if t.last_progress is None:
                stalled_s = now - t.first_seen
                if engine_stuck and stalled_s > threshold:
                    cause: Optional[str] = "engine_stuck"
                elif stalled_s > self.queue_wait_budget_s:
                    cause = "queue_wait"
                else:
                    cause = None
            else:
                stalled_s = now - t.last_progress
                if stalled_s <= threshold:
                    cause = None
                else:
                    cause = "engine_stuck" if engine_stuck else "stalled_stream"
            wedge = (
                self.hard_deadline_s is not None
                and stalled_s > self.hard_deadline_s
            )
            if cause is None:
                if not wedge:
                    continue
                # the hard deadline outranks the cause heuristics: a
                # client past it must not keep hanging just because no
                # cause tripped yet (e.g. no first emission with the
                # queue-wait budget above the deadline)
                cause = (
                    "queue_wait" if t.last_progress is None
                    else "stalled_stream"
                )
            if not t.diagnosed:
                t.diagnosed = True
                if evidence is None:
                    evidence = (
                        self.flight.snapshot(DIAGNOSIS_FLIGHT_RECORDS)
                        if self.flight is not None
                        else [],
                        thread_stacks(),
                    )
                out.append(
                    self._diagnose(t, cause, stalled_s, threshold, evidence)
                )
            if wedge:
                t.wedged = True
                self._wedge(t, cause, stalled_s)
        return out

    def _diagnose(
        self, t: _Tracked, cause: str, stalled_s: float,
        threshold_s: float, evidence: tuple,
    ) -> dict:
        flight_window, stacks = evidence
        diag = {
            "request_id": t.request_id,
            "cause": cause,
            "stalled_s": round(stalled_s, 3),
            "threshold_s": round(threshold_s, 3),
            "emissions": t.emissions,
            "trace": t.trace or {},
            "flight": flight_window,
            "stacks": stacks,
        }
        self.counters.bump(cause)
        if self.counters is not stall_counters:
            stall_counters.bump(cause)
        self.diagnoses.append(diag)
        del self.diagnoses[: -self._max_diagnoses]
        # the JSONL log plane is the durable sink: JsonlFormatter merges
        # the extra into the record (and injects trace ids when absent)
        logger.error(
            "stall watchdog: request %s %s for %.1fs (threshold %.1fs)",
            t.request_id, cause, stalled_s, threshold_s,
            extra={"stall": diag},
        )
        return diag

    def _wedge(self, t: _Tracked, cause: str, stalled_s: float) -> None:
        logger.error(
            "stall watchdog: hard deadline (%.1fs) exceeded for %s (%s); "
            "error-finishing the stream",
            self.hard_deadline_s, t.request_id, cause,
        )
        if self.on_wedged is not None:
            try:
                self.on_wedged(
                    t.request_id,
                    {"cause": cause, "stalled_s": round(stalled_s, 3)},
                )
            except Exception:
                logger.exception("stall watchdog wedge action failed")

    # -- asyncio wrapper ---------------------------------------------------

    def start(self) -> None:
        """Spawn the periodic checker on the RUNNING event loop. The
        watchdog must live off the engine thread — that thread is the
        primary suspect."""
        import asyncio

        async def loop():
            while True:
                await asyncio.sleep(self.interval_s)
                try:
                    self.check()
                except Exception:
                    logger.exception("stall watchdog check failed")

        self._task = asyncio.get_running_loop().create_task(loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

"""The trace-API payloads shared by every HTTP surface that serves a
process's trace ring (the OpenAI frontend and the metrics service both
mount GET /v1/traces and GET /v1/traces/{trace_id}). Framework-free:
handlers pass raw query/path strings in and get (json-able body, http
status) back, so the two aiohttp mounts can't drift apart."""

from __future__ import annotations

from typing import Optional

from dynamo_tpu.telemetry import trace as _trace


def traces_payload(limit_str: Optional[str]) -> tuple[dict, int]:
    """GET /v1/traces?limit=N -> (body, status)."""
    try:
        limit = int(limit_str) if limit_str is not None else 50
    except ValueError:
        return {"error": "limit must be int"}, 400
    return {
        "enabled": _trace._tracer.enabled,
        "traces": _trace.list_traces(limit),
    }, 200


def trace_payload(
    trace_id: str, fmt: Optional[str] = None
) -> tuple[dict, int]:
    """GET /v1/traces/{trace_id}[?format=chrome] -> (body, status)."""
    spans = _trace.get_trace(trace_id)
    if spans is None:
        return {"error": f"trace {trace_id!r} not found"}, 404
    if fmt == "chrome":
        from dynamo_tpu.telemetry.chrome_export import to_chrome_trace

        return to_chrome_trace(spans), 200
    return {"trace_id": trace_id, "spans": spans}, 200

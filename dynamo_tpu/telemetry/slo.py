"""Streaming SLO accounting: quantile sketch + sliding windows + burn rates.

Dependency-free (no ddsketch/prometheus_client in the image), bounded
memory, and MERGEABLE — the properties the fleet plane needs: every
worker/frontend keeps its own sketches, ships them as a compact wire
dict on the metrics bus, and the metrics service merges them into one
fleet view whose percentiles match the percentiles of the pooled raw
observations (tests/test_slo_sketch.py pins <=1% rank error against
exact numpy.percentile on adversarial distributions).

Three layers:

- `QuantileSketch`: DDSketch-style log-bucketed sketch with relative
  bucket width 2*alpha (default 0.5%). Small streams (<= EXACT_CAP
  observations) stay EXACT — raw values, numpy-style linear-interpolated
  quantiles — and spill into buckets only past the cap, so a lightly
  loaded fleet reports exact percentiles and a heavily loaded one pays
  bounded memory. Each bucket keeps (count, sum, min, max): pure point
  masses answer EXACTLY (min == max), continuous mass interpolates
  inside the bucket — the worst-case rank error of a quantile query is
  the mass of one bucket, which a 1%-wide bucket keeps well under 1%
  for anything that isn't a sub-bucket point/continuum mixture. Merging
  is bucket-wise addition (exact concatenation while both sides are
  still exact): merge(a, b) == merge(b, a) and equals the sketch of the
  concatenated stream — associativity is structural, not approximate.

- `SloTracker`: per-endpoint/worker SLA accounting. Cumulative sketches
  for TTFT / ITL / e2e, within-SLA + goodput counters (tokens served by
  requests that met their SLA), and a ring of time slices powering
  sliding-window attainment and multi-window burn-rate gauges
  (burn rate = (1 - attainment) / (1 - objective): 1.0 = exactly
  spending the error budget, >1 = burning it faster).

- `merge_trackers(wires)`: the fleet-side fold over published
  `SloTracker.to_wire()` dicts (malformed wires are skipped, never
  raised — a worker's garbage must not take down the fleet view).

Everything here is host-side Python on the metrics path only; the token
path never calls into this module.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

#: relative half-width of one bucket (0.5% => ~1% wide buckets). 2545
#: buckets would cover 1ns..1e8ms densely; storage is sparse, so real
#: sketches hold a few dozen.
DEFAULT_ALPHA = 0.005

#: values at or below this clamp into the bottom bucket (latencies are
#: positive; zero shows up from clock granularity)
_MIN_VALUE = 1e-9

#: the quantiles every exposition reports
EXPOSED_QUANTILES = (0.5, 0.9, 0.95, 0.99)

#: raw values kept before spilling into log buckets (exact quantiles up
#: to here; ~4 KB of floats at the cap)
EXACT_CAP = 512


class QuantileSketch:
    """Log-bucketed mergeable quantile sketch (DDSketch-flavored).

    Buckets are indexed by ceil(log_gamma(v)) with gamma = (1+a)/(1-a);
    each holds [count, sum, min, max]. Memory is O(distinct buckets),
    bounded by the dynamic range of the data (~2.5k buckets for 11
    decades at the default alpha).
    """

    __slots__ = ("alpha", "_log_gamma", "buckets", "count", "total",
                 "_exact")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        if not 0.0 < alpha < 0.5:
            raise ValueError(f"alpha must be in (0, 0.5), got {alpha}")
        self.alpha = alpha
        self._log_gamma = math.log((1.0 + alpha) / (1.0 - alpha))
        #: bucket index -> [count, sum, min, max]
        self.buckets: dict[int, list[float]] = {}
        self.count = 0
        self.total = 0.0
        #: raw values while small (exact quantiles); None once spilled
        self._exact: Optional[list[float]] = []

    # -- ingest ------------------------------------------------------------

    def _index(self, value: float) -> int:
        return math.ceil(math.log(max(value, _MIN_VALUE)) / self._log_gamma)

    def _bucket_insert(self, v: float) -> None:
        b = self.buckets.get(self._index(v))
        if b is None:
            self.buckets[self._index(v)] = [1, v, v, v]
        else:
            b[0] += 1
            b[1] += v
            if v < b[2]:
                b[2] = v
            elif v > b[3]:
                b[3] = v

    def _spill(self) -> None:
        """Move the exact values into log buckets (one-way)."""
        if self._exact is None:
            return
        for v in self._exact:
            self._bucket_insert(v)
        self._exact = None

    def observe(self, value: float) -> None:
        v = float(value)
        if v != v:  # NaN: clock skew artifacts must not poison the sketch
            return
        v = max(v, _MIN_VALUE)
        if self._exact is not None:
            if len(self._exact) < EXACT_CAP:
                self._exact.append(v)
            else:
                self._spill()
                self._bucket_insert(v)
        else:
            self._bucket_insert(v)
        self.count += 1
        self.total += v

    def merge(self, other: "QuantileSketch") -> None:
        """Fold `other` into self. While both sides are still exact and
        fit the cap, the merge IS concatenation (exact quantiles);
        otherwise both spill and merge bucket-wise (exact, associative).
        Sketches must share alpha — the fleet protocol pins it."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} != "
                f"{other.alpha}"
            )
        if (
            self._exact is not None
            and other._exact is not None
            and len(self._exact) + len(other._exact) <= EXACT_CAP
        ):
            self._exact.extend(other._exact)
        else:
            self._spill()
            if other._exact is not None:
                for v in other._exact:
                    self._bucket_insert(v)
            else:
                for idx, (c, s, mn, mx) in other.buckets.items():
                    b = self.buckets.get(idx)
                    if b is None:
                        self.buckets[idx] = [c, s, mn, mx]
                    else:
                        b[0] += c
                        b[1] += s
                        b[2] = min(b[2], mn)
                        b[3] = max(b[3], mx)
        self.count += other.count
        self.total += other.total

    # -- query -------------------------------------------------------------

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile q in [0, 1]; None on an empty sketch.
        Exact (numpy-style linear interpolation) while the stream is
        small; bucket-approximate past EXACT_CAP."""
        if self.count == 0:
            return None
        q = min(max(q, 0.0), 1.0)
        target = q * (self.count - 1)
        if self._exact is not None:
            xs = sorted(self._exact)
            lo = int(target)
            frac = target - lo
            if lo + 1 < len(xs) and frac:
                return xs[lo] + frac * (xs[lo + 1] - xs[lo])
            return xs[min(lo, len(xs) - 1)]
        cum = 0
        last = None
        for idx in sorted(self.buckets):
            c, s, mn, mx = last = self.buckets[idx]
            if target < cum + c:
                if mn == mx or c == 1:
                    return mn
                frac = (target - cum) / (c - 1)
                return mn + min(frac, 1.0) * (mx - mn)
            cum += c
        return last[3] if last else None

    def quantiles(self, qs: Sequence[float] = EXPOSED_QUANTILES) -> dict:
        return {q: self.quantile(q) for q in qs}

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    # -- wire --------------------------------------------------------------

    def to_wire(self) -> dict:
        """Compact msgpack/json-safe dict: raw values while exact
        ("x"), bucket quintuples after spilling ("b")."""
        out: dict = {"alpha": self.alpha, "n": self.count, "sum": self.total}
        if self._exact is not None:
            out["x"] = list(self._exact)
        else:
            out["b"] = [
                [idx, c, s, mn, mx]
                for idx, (c, s, mn, mx) in sorted(self.buckets.items())
            ]
        return out

    @classmethod
    def from_wire(cls, wire: dict) -> "QuantileSketch":
        sk = cls(alpha=float(wire.get("alpha", DEFAULT_ALPHA)))
        if "x" in wire and "b" not in wire:
            for v in wire["x"]:
                sk.observe(float(v))
            return sk
        sk._exact = None
        for idx, c, s, mn, mx in wire.get("b", ()):
            sk.buckets[int(idx)] = [int(c), float(s), float(mn), float(mx)]
        sk.count = int(wire.get("n", sum(b[0] for b in sk.buckets.values())))
        sk.total = float(
            wire.get("sum", sum(b[1] for b in sk.buckets.values()))
        )
        return sk


@dataclass(frozen=True)
class SlaTargets:
    """What 'within SLA' means for one endpoint/worker. A None target is
    not judged (e.g. unary requests have no TTFT)."""

    ttft_ms: Optional[float] = 2000.0
    itl_ms: Optional[float] = 200.0
    e2e_ms: Optional[float] = None
    #: SLO objective the burn rate is priced against (0.99 = 1% budget)
    objective: float = 0.99

    def ok(self, ttft_ms, itl_ms, e2e_ms) -> bool:
        if self.ttft_ms is not None and ttft_ms is not None:
            if ttft_ms > self.ttft_ms:
                return False
        if self.itl_ms is not None and itl_ms is not None:
            if itl_ms > self.itl_ms:
                return False
        if self.e2e_ms is not None and e2e_ms is not None:
            if e2e_ms > self.e2e_ms:
                return False
        return True

    def to_wire(self) -> dict:
        return {
            "ttft_ms": self.ttft_ms,
            "itl_ms": self.itl_ms,
            "e2e_ms": self.e2e_ms,
            "objective": self.objective,
        }


@dataclass
class _Slice:
    """One time slice of the attainment ring."""

    start: float = 0.0
    requests: int = 0
    within_sla: int = 0
    tokens: int = 0
    goodput_tokens: int = 0


#: burn-rate windows (seconds) — a fast window that pages and a slow one
#: that confirms, the standard multi-window pattern
DEFAULT_WINDOWS = (60.0, 600.0)

#: seconds per ring slice (windows must be multiples of this)
SLICE_S = 5.0


class SloTracker:
    """Streaming SLO accounting for one endpoint or worker: cumulative
    sketches + SLA/goodput counters + sliding-window attainment.

    Thread-safe (the engine thread observes, the publish loop serializes).
    """

    METRICS = ("ttft_ms", "itl_ms", "e2e_ms")

    def __init__(
        self,
        sla: Optional[SlaTargets] = None,
        windows: Sequence[float] = DEFAULT_WINDOWS,
        clock=time.monotonic,
    ):
        self.sla = sla or SlaTargets()
        self.windows = tuple(float(w) for w in windows)
        self._clock = clock
        self._lock = threading.Lock()
        self.sketches = {m: QuantileSketch() for m in self.METRICS}
        self.requests_total = 0
        self.within_sla_total = 0
        self.tokens_total = 0
        self.goodput_tokens_total = 0
        #: ring of slices spanning the LONGEST window
        n = max(1, int(max(self.windows, default=SLICE_S) / SLICE_S))
        self._ring: list[_Slice] = [_Slice() for _ in range(n)]

    # -- ingest ------------------------------------------------------------

    def observe(self, metric: str, value_ms: float) -> None:
        """Feed one latency sample into the named sketch
        (ttft_ms | itl_ms | e2e_ms)."""
        with self._lock:
            self.sketches[metric].observe(value_ms)

    def _slot(self, now: float) -> _Slice:
        i = int(now / SLICE_S) % len(self._ring)
        sl = self._ring[i]
        start = (now // SLICE_S) * SLICE_S
        if sl.start != start:
            self._ring[i] = sl = _Slice(start=start)
        return sl

    def finish_request(
        self,
        ttft_ms: Optional[float] = None,
        itl_ms: Optional[float] = None,
        e2e_ms: Optional[float] = None,
        tokens: int = 0,
    ) -> bool:
        """Account one completed request (its samples should already have
        been fed via observe()). Returns the SLA judgement."""
        ok = self.sla.ok(ttft_ms, itl_ms, e2e_ms)
        with self._lock:
            now = self._clock()
            sl = self._slot(now)
            self.requests_total += 1
            self.tokens_total += tokens
            sl.requests += 1
            sl.tokens += tokens
            if ok:
                self.within_sla_total += 1
                self.goodput_tokens_total += tokens
                sl.within_sla += 1
                sl.goodput_tokens += tokens
        return ok

    # -- query -------------------------------------------------------------

    def _window_counts(self, window_s: float, now: float) -> tuple[int, int]:
        lo = now - window_s
        n = ok = 0
        for sl in self._ring:
            if sl.start >= lo - SLICE_S and sl.requests:
                n += sl.requests
                ok += sl.within_sla
        return n, ok

    def attainment(self, window_s: Optional[float] = None) -> float:
        """Fraction of requests within SLA (1.0 when idle — no traffic
        burns no budget)."""
        with self._lock:
            if window_s is None:
                n, ok = self.requests_total, self.within_sla_total
            else:
                n, ok = self._window_counts(window_s, self._clock())
        return ok / n if n else 1.0

    def burn_rate(self, window_s: float) -> float:
        a = self.attainment(window_s)
        budget = 1.0 - self.sla.objective
        return (1.0 - a) / budget if budget > 0 else 0.0

    # -- wire --------------------------------------------------------------

    def to_wire(self) -> dict:
        with self._lock:
            now = self._clock()
            return {
                "sla": self.sla.to_wire(),
                "sketches": {
                    m: sk.to_wire() for m, sk in self.sketches.items()
                },
                "requests_total": self.requests_total,
                "within_sla_total": self.within_sla_total,
                "tokens_total": self.tokens_total,
                "goodput_tokens_total": self.goodput_tokens_total,
                "windows": {
                    str(int(w)): list(self._window_counts(w, now))
                    for w in self.windows
                },
            }


@dataclass
class MergedSlo:
    """Fleet-side fold of SloTracker wires (one role, or the whole fleet)."""

    sketches: dict = field(
        default_factory=lambda: {m: QuantileSketch() for m in SloTracker.METRICS}
    )
    requests_total: int = 0
    within_sla_total: int = 0
    tokens_total: int = 0
    goodput_tokens_total: int = 0
    #: window-seconds -> [requests, within_sla]
    windows: dict = field(default_factory=dict)
    sources: int = 0
    objective: float = 0.99

    def attainment(self, window: Optional[str] = None) -> float:
        if window is None:
            n, ok = self.requests_total, self.within_sla_total
        else:
            n, ok = self.windows.get(window, (0, 0))
        return ok / n if n else 1.0

    def burn_rate(self, window: str) -> float:
        budget = 1.0 - self.objective
        return (1.0 - self.attainment(window)) / budget if budget > 0 else 0.0

    def to_snapshot(self) -> dict:
        """JSON-safe summary for /v1/fleet."""
        out: dict = {
            "sources": self.sources,
            "requests_total": self.requests_total,
            "within_sla_total": self.within_sla_total,
            "tokens_total": self.tokens_total,
            "goodput_tokens_total": self.goodput_tokens_total,
            "attainment": round(self.attainment(), 6),
            "windows": {},
        }
        for w in sorted(self.windows, key=lambda x: int(x)):
            out["windows"][w] = {
                "requests": self.windows[w][0],
                "attainment": round(self.attainment(w), 6),
                "burn_rate": round(self.burn_rate(w), 4),
            }
        for m, sk in self.sketches.items():
            if sk.count:
                out[m] = {
                    f"p{int(q * 100)}": round(v, 3)
                    for q, v in sk.quantiles().items()
                    if v is not None
                }
                out[m]["n"] = sk.count
        return out


def merge_trackers(wires: Iterable[dict]) -> MergedSlo:
    """Fold published tracker wires into one MergedSlo. Malformed wires
    are skipped (the fleet view degrades by one worker, never dies)."""
    out = MergedSlo()
    for wire in wires:
        if not isinstance(wire, dict) or not isinstance(
            wire.get("sketches"), dict
        ):
            continue  # structurally not a tracker wire
        try:
            sketches = {
                m: QuantileSketch.from_wire(wire["sketches"][m])
                for m in SloTracker.METRICS
                if m in wire.get("sketches", {})
            }
            for m, sk in sketches.items():
                if abs(sk.alpha - out.sketches[m].alpha) > 1e-12:
                    # alpha mismatch would raise mid-merge below and
                    # leave MergedSlo partially folded — reject the
                    # whole wire up front instead
                    raise ValueError("sketch alpha mismatch")
            req = int(wire.get("requests_total", 0))
            ok = int(wire.get("within_sla_total", 0))
            toks = int(wire.get("tokens_total", 0))
            good = int(wire.get("goodput_tokens_total", 0))
            windows = {
                str(w): (int(n), int(k))
                for w, (n, k) in dict(wire.get("windows", {})).items()
            }
            objective = float(
                dict(wire.get("sla") or {}).get("objective", 0.99)
            )
        except Exception:
            continue  # one garbage wire must not kill the fleet fold
        for m, sk in sketches.items():
            out.sketches[m].merge(sk)
        out.requests_total += req
        out.within_sla_total += ok
        out.tokens_total += toks
        out.goodput_tokens_total += good
        for w, (n, k) in windows.items():
            cur = out.windows.get(w, (0, 0))
            out.windows[w] = (cur[0] + n, cur[1] + k)
        out.objective = objective  # fleet convention: one shared objective
        out.sources += 1
    return out


def expose_lines(prefix: str, scopes) -> list[str]:
    """Prometheus text lines for a set of SLO scopes sharing one metric
    prefix. `scopes` is a list of (labels, tracker-or-MergedSlo) where
    `labels` is a rendered label body WITHOUT braces (e.g.
    'endpoint="chat"' or 'role="decode"'); each family is declared ONCE
    with every scope's samples under it (the Prometheus text format
    keeps a family's series together — the promlint gate in tests
    validates the shapes). Families are emitted only when populated."""
    resolved: list[tuple[str, MergedSlo]] = []
    for labels, src in scopes:
        if isinstance(src, SloTracker):
            src = merge_trackers([src.to_wire()])
        resolved.append((labels, src))
    lines: list[str] = []
    fams: dict[str, tuple[str, list[tuple[str, float]]]] = {}

    def fam(name: str, ptype: str, samples: list[tuple[str, float]]):
        if not samples:
            return
        entry = fams.setdefault(name, (ptype, []))
        entry[1].extend(samples)

    for labels, src in resolved:
        sep = "," if labels else ""
        for m in SloTracker.METRICS:
            sk = src.sketches[m]
            if not sk.count:
                continue
            fam(
                m, "gauge",
                [
                    (f'{labels}{sep}quantile="{q}"', round(v, 4))
                    for q, v in sk.quantiles().items()
                    if v is not None
                ],
            )
        if src.requests_total or src.sources:
            fam("requests_total", "counter", [(labels, src.requests_total)])
            fam(
                "sla_requests_total", "counter",
                [(labels, src.within_sla_total)],
            )
            fam("tokens_total", "counter", [(labels, src.tokens_total)])
            fam(
                "goodput_tokens_total", "counter",
                [(labels, src.goodput_tokens_total)],
            )
            fam(
                "attainment", "gauge",
                [(f'{labels}{sep}window="all"', round(src.attainment(), 6))]
                + [
                    (
                        f'{labels}{sep}window="{w}s"',
                        round(src.attainment(w), 6),
                    )
                    for w in sorted(src.windows, key=lambda x: int(x))
                ],
            )
            fam(
                "burn_rate", "gauge",
                [
                    (
                        f'{labels}{sep}window="{w}s"',
                        round(src.burn_rate(w), 4),
                    )
                    for w in sorted(src.windows, key=lambda x: int(x))
                ],
            )
    for name, (ptype, samples) in fams.items():
        lines.append(f"# TYPE {prefix}_{name} {ptype}")
        for lbl, val in samples:
            lines.append(
                f"{prefix}_{name}{{{lbl}}} {val}" if lbl
                else f"{prefix}_{name} {val}"
            )
    return lines

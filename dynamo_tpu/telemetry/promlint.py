"""Pure-python Prometheus text-exposition linter.

The repo hand-rolls its exposition (no client library in the image), so
nothing structurally validates what /metrics emits — a stray duplicate
`# TYPE`, an unescaped label value, or a non-monotonic histogram bucket
silently corrupts scrapes. `lint(text)` returns a list of human-readable
problems (empty == clean); tests run it against FrontendMetrics.expose()
and MetricsService.expose() so future metric additions can't regress
the format.

Checks:
  - sample/metadata line shape (name, optional {labels}, float value)
  - label syntax + escaping (\\, \", \\n escaped inside quoted values)
  - at most one `# TYPE` per metric family, declared before its samples
  - every sample belongs to a declared family (suffix-aware for
    histogram/summary series)
  - counters end in `_total` (per the Prometheus naming convention)
  - histograms: per-label-set cumulative buckets are monotonically
    non-decreasing, an `le="+Inf"` bucket exists and equals `_count`
  - OpenMetrics exemplars (`... # {trace_id="..."} value [ts]`):
    REJECTED in classic mode — the 0.0.4 parser fails the whole scrape
    on one — and validated in `lint(text, openmetrics=True)`: only on
    histogram buckets or counters, valid label syntax, combined
    label-set length <= 128 runes, numeric value (and timestamp when
    present), and — for buckets — the exemplar value lies within the
    bucket's bounds (prev_le, le]. The phase histograms stamp these
    with kept-trace ids on the negotiated OpenMetrics rendering only
    (docs/observability.md "Fleet traces & event timeline").
  - `openmetrics=True` also relaxes counter family naming (OpenMetrics
    declares the family WITHOUT `_total`; samples keep it) and accepts
    the `# EOF` terminator.
"""

from __future__ import annotations

import math
import re

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_METRIC_RE = re.compile(
    rf"^({_NAME})(\{{(.*)\}})?\s+(\S+)(\s+\S+)?$"
)
_LABEL_RE = re.compile(
    rf'({_NAME})="((?:[^"\\\n]|\\\\|\\"|\\n)*)"'
)
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (\w+)$")
_EXEMPLAR_RE = re.compile(r"^\{(.*)\}\s+(\S+)(?:\s+(\S+))?$")
_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_SUFFIXES = {
    "histogram": ("_bucket", "_sum", "_count"),
    "summary": ("_sum", "_count"),
    "counter": ("_total", "_created"),
}
#: OpenMetrics: an exemplar's label names + values together must not
#: exceed 128 UTF-8 characters
_EXEMPLAR_MAX_RUNES = 128


def _parse_labels(raw: str, line_no: int, errors: list[str]) -> dict:
    labels: dict[str, str] = {}
    pos = 0
    raw = raw.strip()
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            errors.append(
                f"line {line_no}: bad label syntax/escaping at "
                f"{raw[pos:pos + 40]!r}"
            )
            return labels
        if m.group(1) in labels:
            errors.append(
                f"line {line_no}: duplicate label {m.group(1)!r}"
            )
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                errors.append(
                    f"line {line_no}: expected ',' between labels at "
                    f"{raw[pos:pos + 20]!r}"
                )
                return labels
            pos += 1
    return labels


def _family_of(name: str, types: dict[str, str]) -> str | None:
    """Which declared family a sample name belongs to (suffix-aware)."""
    if name in types:
        return name
    for fam, t in types.items():
        for suf in _SUFFIXES.get(t, ()):
            if name == fam + suf:
                return fam
    return None


def _lint_exemplar(
    raw: str,
    line_no: int,
    name: str,
    fam: str,
    ftype: str,
    sample_labels: dict,
    errors: list[str],
    bucket_exemplars: dict,
) -> None:
    """Validate one exemplar tail (the part after ` # `). Bucket
    exemplar values are recorded for the bounds check in the histogram
    post-pass (the lower bound needs the sorted bucket ladder)."""
    m = _EXEMPLAR_RE.match(raw.strip())
    if m is None:
        errors.append(
            f"line {line_no}: malformed exemplar {raw[:60]!r}"
        )
        return
    is_bucket = ftype == "histogram" and name == fam + "_bucket"
    if not is_bucket and ftype != "counter":
        errors.append(
            f"line {line_no}: exemplar on a {ftype} sample {name!r} "
            "(only histogram buckets and counters may carry exemplars)"
        )
        return
    labels = _parse_labels(m.group(1), line_no, errors)
    runes = sum(len(k) + len(v) for k, v in labels.items())
    if runes > _EXEMPLAR_MAX_RUNES:
        errors.append(
            f"line {line_no}: exemplar label set is {runes} runes "
            f"(OpenMetrics caps it at {_EXEMPLAR_MAX_RUNES})"
        )
    try:
        value = float(m.group(2))
    except ValueError:
        errors.append(
            f"line {line_no}: non-numeric exemplar value {m.group(2)!r}"
        )
        return
    if m.group(3) is not None:
        try:
            float(m.group(3))
        except ValueError:
            errors.append(
                f"line {line_no}: non-numeric exemplar timestamp "
                f"{m.group(3)!r}"
            )
    if is_bucket:
        le = sample_labels.get("le")
        if le is not None:
            lev = math.inf if le == "+Inf" else float(le)
            key = tuple(
                sorted(
                    (k, v) for k, v in sample_labels.items() if k != "le"
                )
            )
            bucket_exemplars.setdefault(fam, {}).setdefault(
                key, []
            ).append((lev, value, line_no))


def lint(text: str, openmetrics: bool = False) -> list[str]:
    errors: list[str] = []
    types: dict[str, str] = {}
    seen_sample_of: set[str] = set()
    # histogram state: family -> {label-key-without-le: [(le, cum), ...]}
    buckets: dict[str, dict[tuple, list[tuple[float, float]]]] = {}
    counts: dict[str, dict[tuple, float]] = {}
    # exemplar state: family -> {key: [(le, exemplar value, line)]}
    bucket_exemplars: dict[str, dict[tuple, list[tuple]]] = {}

    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE"):
                m = _TYPE_RE.match(line)
                if m is None:
                    errors.append(f"line {i}: malformed TYPE line")
                    continue
                fam, t = m.group(1), m.group(2)
                if t not in _VALID_TYPES:
                    errors.append(
                        f"line {i}: unknown metric type {t!r} for {fam}"
                    )
                if fam in types:
                    errors.append(
                        f"line {i}: duplicate '# TYPE {fam}'"
                    )
                if fam in seen_sample_of:
                    errors.append(
                        f"line {i}: TYPE for {fam} declared after its "
                        "samples"
                    )
                types[fam] = t
                if (
                    t == "counter"
                    and not fam.endswith("_total")
                    and not openmetrics
                ):
                    errors.append(
                        f"line {i}: counter {fam!r} must end in '_total'"
                    )
            continue  # other comments (# HELP, # EOF) are fine
        base, _, exemplar = line.partition(" # ")
        m = _METRIC_RE.match(base)
        if m is None:
            errors.append(f"line {i}: unparseable sample {line!r:.80}")
            continue
        name, _, rawlabels, value = (
            m.group(1), m.group(2), m.group(3), m.group(4),
        )
        labels = (
            _parse_labels(rawlabels, i, errors) if rawlabels else {}
        )
        try:
            val = float(value)
        except ValueError:
            errors.append(f"line {i}: non-numeric value {value!r}")
            continue
        fam = _family_of(name, types)
        if fam is None:
            errors.append(
                f"line {i}: sample {name!r} has no preceding '# TYPE'"
            )
            continue
        seen_sample_of.add(fam)
        if exemplar:
            if not openmetrics:
                # the classic 0.0.4 parser fails the WHOLE scrape on an
                # exemplar tail — it must never reach that surface
                errors.append(
                    f"line {i}: exemplar on a classic text-format "
                    "exposition (OpenMetrics-only syntax)"
                )
            else:
                _lint_exemplar(
                    exemplar, i, name, fam, types[fam], labels, errors,
                    bucket_exemplars,
                )
        if types[fam] == "histogram":
            key = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            if name == fam + "_bucket":
                le = labels.get("le")
                if le is None:
                    errors.append(
                        f"line {i}: histogram bucket without 'le' label"
                    )
                    continue
                lev = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(fam, {}).setdefault(key, []).append(
                    (lev, val)
                )
            elif name == fam + "_count":
                counts.setdefault(fam, {})[key] = val

    for fam, series in buckets.items():
        for key, pairs in series.items():
            pairs.sort(key=lambda p: p[0])
            prev = -math.inf
            for le, cum in pairs:
                if cum < prev:
                    errors.append(
                        f"{fam}{dict(key)}: bucket le={le} count {cum} "
                        f"< previous {prev} (non-monotonic)"
                    )
                prev = cum
            if not pairs or pairs[-1][0] != math.inf:
                errors.append(
                    f"{fam}{dict(key)}: missing le=\"+Inf\" bucket"
                )
            else:
                total = counts.get(fam, {}).get(key)
                if total is not None and total != pairs[-1][1]:
                    errors.append(
                        f"{fam}{dict(key)}: _count {total} != +Inf "
                        f"bucket {pairs[-1][1]}"
                    )
            # exemplar bounds: each bucket's exemplar value must lie in
            # (prev_le, le] of the sorted ladder (a tiny tolerance
            # absorbs the exposition's value rounding)
            ladder = [le for le, _ in pairs]
            for le, exval, line_no in bucket_exemplars.get(fam, {}).get(
                key, ()
            ):
                if le not in ladder:
                    continue  # bucket itself already flagged above
                idx = ladder.index(le)
                prev_le = ladder[idx - 1] if idx > 0 else -math.inf
                if exval > le + 1e-9 or exval <= prev_le - 1e-6:
                    errors.append(
                        f"{fam}{dict(key)}: exemplar value {exval} on "
                        f"bucket le={le} (line {line_no}) is outside "
                        f"the bucket's bounds ({prev_le}, {le}]"
                    )
    return errors

"""Pure-python Prometheus text-exposition linter.

The repo hand-rolls its exposition (no client library in the image), so
nothing structurally validates what /metrics emits — a stray duplicate
`# TYPE`, an unescaped label value, or a non-monotonic histogram bucket
silently corrupts scrapes. `lint(text)` returns a list of human-readable
problems (empty == clean); tests run it against FrontendMetrics.expose()
and MetricsService.expose() so future metric additions can't regress
the format.

Checks:
  - sample/metadata line shape (name, optional {labels}, float value)
  - label syntax + escaping (\\, \", \\n escaped inside quoted values)
  - at most one `# TYPE` per metric family, declared before its samples
  - every sample belongs to a declared family (suffix-aware for
    histogram/summary series)
  - counters end in `_total` (per the Prometheus naming convention)
  - histograms: per-label-set cumulative buckets are monotonically
    non-decreasing, an `le="+Inf"` bucket exists and equals `_count`
"""

from __future__ import annotations

import math
import re

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_METRIC_RE = re.compile(
    rf"^({_NAME})(\{{(.*)\}})?\s+(\S+)(\s+\S+)?$"
)
_LABEL_RE = re.compile(
    rf'({_NAME})="((?:[^"\\\n]|\\\\|\\"|\\n)*)"'
)
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (\w+)$")
_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_SUFFIXES = {
    "histogram": ("_bucket", "_sum", "_count"),
    "summary": ("_sum", "_count"),
    "counter": ("_total", "_created"),
}


def _parse_labels(raw: str, line_no: int, errors: list[str]) -> dict:
    labels: dict[str, str] = {}
    pos = 0
    raw = raw.strip()
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            errors.append(
                f"line {line_no}: bad label syntax/escaping at "
                f"{raw[pos:pos + 40]!r}"
            )
            return labels
        if m.group(1) in labels:
            errors.append(
                f"line {line_no}: duplicate label {m.group(1)!r}"
            )
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                errors.append(
                    f"line {line_no}: expected ',' between labels at "
                    f"{raw[pos:pos + 20]!r}"
                )
                return labels
            pos += 1
    return labels


def _family_of(name: str, types: dict[str, str]) -> str | None:
    """Which declared family a sample name belongs to (suffix-aware)."""
    if name in types:
        return name
    for fam, t in types.items():
        for suf in _SUFFIXES.get(t, ()):
            if name == fam + suf:
                return fam
    return None


def lint(text: str) -> list[str]:
    errors: list[str] = []
    types: dict[str, str] = {}
    seen_sample_of: set[str] = set()
    # histogram state: family -> {label-key-without-le: [(le, cum), ...]}
    buckets: dict[str, dict[tuple, list[tuple[float, float]]]] = {}
    counts: dict[str, dict[tuple, float]] = {}

    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE"):
                m = _TYPE_RE.match(line)
                if m is None:
                    errors.append(f"line {i}: malformed TYPE line")
                    continue
                fam, t = m.group(1), m.group(2)
                if t not in _VALID_TYPES:
                    errors.append(
                        f"line {i}: unknown metric type {t!r} for {fam}"
                    )
                if fam in types:
                    errors.append(
                        f"line {i}: duplicate '# TYPE {fam}'"
                    )
                if fam in seen_sample_of:
                    errors.append(
                        f"line {i}: TYPE for {fam} declared after its "
                        "samples"
                    )
                types[fam] = t
                if t == "counter" and not fam.endswith("_total"):
                    errors.append(
                        f"line {i}: counter {fam!r} must end in '_total'"
                    )
            continue  # other comments (# HELP) are fine
        m = _METRIC_RE.match(line)
        if m is None:
            errors.append(f"line {i}: unparseable sample {line!r:.80}")
            continue
        name, _, rawlabels, value = (
            m.group(1), m.group(2), m.group(3), m.group(4),
        )
        labels = (
            _parse_labels(rawlabels, i, errors) if rawlabels else {}
        )
        try:
            val = float(value)
        except ValueError:
            errors.append(f"line {i}: non-numeric value {value!r}")
            continue
        fam = _family_of(name, types)
        if fam is None:
            errors.append(
                f"line {i}: sample {name!r} has no preceding '# TYPE'"
            )
            continue
        seen_sample_of.add(fam)
        if types[fam] == "histogram":
            key = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            if name == fam + "_bucket":
                le = labels.get("le")
                if le is None:
                    errors.append(
                        f"line {i}: histogram bucket without 'le' label"
                    )
                    continue
                lev = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(fam, {}).setdefault(key, []).append(
                    (lev, val)
                )
            elif name == fam + "_count":
                counts.setdefault(fam, {})[key] = val

    for fam, series in buckets.items():
        for key, pairs in series.items():
            pairs.sort(key=lambda p: p[0])
            prev = -math.inf
            for le, cum in pairs:
                if cum < prev:
                    errors.append(
                        f"{fam}{dict(key)}: bucket le={le} count {cum} "
                        f"< previous {prev} (non-monotonic)"
                    )
                prev = cum
            if not pairs or pairs[-1][0] != math.inf:
                errors.append(
                    f"{fam}{dict(key)}: missing le=\"+Inf\" bucket"
                )
            else:
                total = counts.get(fam, {}).get(key)
                if total is not None and total != pairs[-1][1]:
                    errors.append(
                        f"{fam}{dict(key)}: _count {total} != +Inf "
                        f"bucket {pairs[-1][1]}"
                    )
    return errors

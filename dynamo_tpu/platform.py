"""Backend-selection hygiene for process entry points.

The TPU image's sitecustomize rewrites jax's platform list to "axon,cpu"
at interpreter start, overriding a JAX_PLATFORMS environment variable the
operator set. Normally the axon (TPU tunnel) backend fails fast when
unavailable and jax falls back to cpu — but a wedged tunnel HANGS backend
init instead, freezing any process that merely touches jax.devices().

Entry points call honor_jax_platforms_env() first: if the operator
explicitly set JAX_PLATFORMS, that choice is restored via jax.config
(which sitecustomize cannot override post-hoc — backends initialize
lazily, so this works as long as it runs before first device use).
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    requested = os.environ.get("JAX_PLATFORMS")
    if not requested:
        return
    import jax

    if jax.config.jax_platforms != requested:
        jax.config.update("jax_platforms", requested)

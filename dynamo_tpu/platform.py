"""Backend-selection hygiene for process entry points.

The TPU image's sitecustomize rewrites jax's platform list to "axon,cpu"
at interpreter start, overriding a JAX_PLATFORMS environment variable the
operator set. Normally the axon (TPU tunnel) backend fails fast when
unavailable and jax falls back to cpu — but a wedged tunnel HANGS backend
init instead, freezing any process that merely touches jax.devices().

Entry points call honor_jax_platforms_env() first: if the operator
explicitly set JAX_PLATFORMS, that choice is restored via jax.config
(which sitecustomize cannot override post-hoc — backends initialize
lazily, so this works as long as it runs before first device use).
"""

from __future__ import annotations

import os


def get_shard_map():
    """The shard_map entry point across jax versions: `jax.shard_map`
    (0.6+) or `jax.experimental.shard_map.shard_map` (the baked
    toolchain's 0.4.x, where the replication-check kwarg is spelled
    `check_rep` instead of `check_vma`). Every in-tree user imports
    through here so a toolchain bump is a one-line change."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None and callable(fn):
        return fn
    from jax.experimental.shard_map import shard_map

    def compat(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return shard_map(f, **kwargs)

    return compat


def tpu_compiler_params(**kwargs):
    """pltpu compiler-params across jax versions: `CompilerParams`
    (0.6+) vs `TPUCompilerParams` (the baked toolchain's 0.4.x). Same
    one-import-site rule as get_shard_map."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)


def honor_jax_platforms_env() -> None:
    requested = os.environ.get("JAX_PLATFORMS")
    if not requested:
        return
    import jax

    if jax.config.jax_platforms != requested:
        jax.config.update("jax_platforms", requested)


def enable_persistent_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory so
    worker restarts (and repeated bench topologies) skip recompiles.

    The reference inherits this from its engines (vLLM caches compiled
    CUDA graphs); for a JAX engine the equivalent is
    jax_compilation_cache_dir. Serving restart cost on TPU is otherwise
    dominated by XLA: a llama3-1b worker compiles ~60-120 s of programs
    at boot. Opt out with DYN_COMPILE_CACHE=off; override the location
    with DYN_COMPILE_CACHE=<dir>."""
    path = os.environ.get("DYN_COMPILE_CACHE")
    if path and path.lower() in ("off", "0", "none", "disabled"):
        return
    try:
        import jax

        if not path:
            if (
                jax.config.jax_compilation_cache_dir
                or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            ):
                return  # operator already configured a cache — keep it
            path = os.path.join(
                os.path.expanduser("~"), ".cache", "dynamo_tpu", "xla"
            )
        os.makedirs(path, exist_ok=True)
        if jax.config.jax_compilation_cache_dir != path:
            jax.config.update("jax_compilation_cache_dir", path)
            # default min-compile-time gate (1 s) would skip most decode
            # buckets; cache everything non-trivial
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.2
            )
    except Exception:  # cache is an optimization, never a boot failure
        pass


#: per-generation public chip numbers, ONE table for every consumer:
#: device_kind substring tag -> (bf16 peak FLOP/s, peak HBM bytes/s,
#: HBM capacity bytes per chip). The MFU gauge, the program cost model
#: (/v1/debug/programs) and the HBM accounting plane (/v1/debug/memory)
#: all resolve through _device_peaks() so their denominators can never
#: disagree (they used to live as two drifting copies below). Order
#: matters: longer/more-specific tags first ("v5e" before "v5lite"
#: would both miss "v5 lite" after the space strip — keep both).
_TPU_GENERATIONS = (
    ("v6e", (918e12, 1640e9, 32e9)),
    ("v6", (918e12, 1640e9, 32e9)),
    ("v5p", (459e12, 2765e9, 95e9)),
    ("v5e", (197e12, 819e9, 16e9)),
    ("v5lite", (197e12, 819e9, 16e9)),
    ("v4", (275e12, 1228e9, 32e9)),
)

#: column indexes into the _TPU_GENERATIONS rows + their env overrides
#: and nominal CPU-dev fallbacks (documented in each public accessor)
_PEAK_COLUMNS = {
    "flops": (0, "DYNTPU_PEAK_FLOPS", 1e12),
    "bytes_per_s": (1, "DYNTPU_PEAK_BYTES", 1e11),
    "hbm_bytes": (2, "DYNTPU_HBM_BYTES", 16e9),
}


def _device_peaks(column: str) -> float:
    """Resolve one peak column for the attached accelerator: the TPU
    generation table on TPU, the column's env override elsewhere, else
    its nominal CPU-dev fallback."""
    idx, env_var, nominal = _PEAK_COLUMNS[column]
    import jax

    try:
        if jax.default_backend() == "tpu":
            kind = jax.devices()[0].device_kind.lower().replace(" ", "")
            for tag, peaks in _TPU_GENERATIONS:
                if tag in kind:
                    return peaks[idx]
    except Exception:
        pass
    try:
        env = float(os.environ.get(env_var, "") or 0.0)
        if env > 0:
            return env
    except ValueError:
        pass
    return nominal


def device_peak_flops() -> float:
    """Per-chip peak FLOP/s for the attached accelerator — the
    denominator of the live MFU gauge (docs/PERF.md "Live MFU gauge").
    TPU generations resolve to their public bf16 peaks; off-TPU the
    fallback comes from DYNTPU_PEAK_FLOPS (else a nominal 1e12 so the
    gauge stays a plausible (0,1] number on CPU dev boxes instead of
    vanishing)."""
    return _device_peaks("flops")


def device_peak_bytes_per_s() -> float:
    """Per-chip peak HBM bandwidth — the memory roof of the per-program
    cost model (engine.programs_report / GET /v1/debug/programs). TPU
    generations resolve to their public HBM numbers; off-TPU the
    fallback comes from DYNTPU_PEAK_BYTES (else a nominal 1e11 so
    attainment stays a plausible fraction on CPU dev boxes)."""
    return _device_peaks("bytes_per_s")


def device_hbm_bytes() -> float:
    """Per-chip HBM capacity — the `free` denominator of the HBM
    accounting plane (engine.memory_report / GET /v1/debug/memory) when
    the backend exposes no memory_stats (the documented CPU fallback).
    TPU generations resolve to their public capacities; off-TPU the
    fallback comes from DYNTPU_HBM_BYTES (else a nominal 16e9, the v5e
    capacity, so free/peak stay plausible on CPU dev boxes)."""
    return _device_peaks("hbm_bytes")

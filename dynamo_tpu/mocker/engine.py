"""Mock engine: a zero-hardware stand-in worker.

Simulates a paged-KV continuous-batching engine faithfully enough to test
routing and observability with no TPU: it runs a real PageAllocator (so
prefix caching, eviction, and KV events are REAL — same code as JaxEngine),
simulated prefill/decode timing, and deterministic token output (reference:
the mocker component — lib/llm/src/mocker/engine.rs:60, kv_manager.rs:121,
protocols.rs MockEngineArgs :72).
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.engine.page_table import KvEvent, PageAllocator
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.tokens import TokenBlockSequence


@dataclass(frozen=True)
class MockEngineArgs:
    num_pages: int = 256
    page_size: int = 16
    #: simulated seconds per prefill token / per decode step
    prefill_s_per_token: float = 0.0001
    decode_s_per_step: float = 0.002
    vocab_size: int = 256
    salt: str = "mock"


class MockEngine:
    def __init__(
        self,
        args: MockEngineArgs = MockEngineArgs(),
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.args = args
        self.allocator = PageAllocator(
            args.num_pages, args.page_size, on_event=on_kv_event
        )
        self.active_requests = 0
        self.requests_received = 0

    def _next_token(self, history: list[int]) -> int:
        h = hashlib.blake2b(bytes(str(history[-8:]), "utf-8"), digest_size=4)
        return int.from_bytes(h.digest(), "little") % self.args.vocab_size

    async def generate(self, context, request: PreprocessedRequest):
        a = self.args
        self.active_requests += 1
        self.requests_received += 1
        chain = TokenBlockSequence(
            request.token_ids, block_size=a.page_size, salt=a.salt
        )
        hashes = chain.sequence_hashes()
        cached = self.allocator.lookup(hashes)
        need = -(-(len(request.token_ids) + 1) // a.page_size) - len(cached)
        pages = self.allocator.allocate(max(need, 0)) or []
        all_pages = cached + pages
        try:
            # simulated prefill (cached prefix is free)
            uncached = len(request.token_ids) - len(cached) * a.page_size
            await asyncio.sleep(max(uncached, 0) * a.prefill_s_per_token)
            # register the prompt's full blocks for prefix reuse (and so KV
            # events cover the prompt, which is what routing matches on)
            for bi in range(len(cached), len(chain.blocks)):
                if bi < len(all_pages):
                    blk = chain.blocks[bi]
                    self.allocator.register(
                        all_pages[bi],
                        blk.sequence_hash,
                        blk.parent_sequence_hash,
                        blk.tokens,
                    )
            history = list(request.token_ids)
            produced = 0
            while produced < request.max_tokens:
                if context.cancelled:
                    return
                await asyncio.sleep(a.decode_s_per_step)
                tok = self._next_token(history)
                history.append(tok)
                committed = chain.append(tok)
                if committed is not None:
                    # register the newly-filled page for prefix reuse
                    page_idx = committed.block_index
                    if page_idx < len(all_pages):
                        self.allocator.register(
                            all_pages[page_idx],
                            committed.sequence_hash,
                            committed.parent_sequence_hash,
                            committed.tokens,
                        )
                    grown = self.allocator.allocate(1)
                    if grown:
                        all_pages.extend(grown)
                produced += 1
                stop = (
                    not request.ignore_eos and tok in request.stop_token_ids
                ) or produced >= request.max_tokens
                yield {
                    "token_ids": [tok],
                    "finish_reason": ("stop" if tok in request.stop_token_ids else "length") if stop else None,
                }
                if stop:
                    return
        finally:
            self.active_requests -= 1
            if all_pages:
                self.allocator.free(all_pages)

"""Mock engine: a zero-hardware stand-in worker.

Simulates a paged-KV continuous-batching engine faithfully enough to test
routing, planner, and capacity behavior with no TPU. Unlike a
sleep-per-request fake, this runs the reference mocker's actual shape
(lib/llm/src/mocker/engine.rs:60, scheduler.rs:197, kv_manager.rs:121):

- one BATCHED step loop ticks every `decode_s_per_step`; all running
  requests advance together (continuous batching), so fleet-level load,
  queueing, and latency under concurrency are simulated, not faked;
- a real PageAllocator backs the KV pool — prefix caching, eviction, and
  KV events are REAL (same code as JaxEngine);
- admission is WATERMARK-gated (kv_manager.rs watermark checks): a request
  only joins the batch if its pages fit while keeping `watermark` of the
  pool free; otherwise it queues (visible as num_waiting to the planner);
- prefill is chunked under a shared per-tick token budget, so long prompts
  cost proportional ticks and delay TTFT realistically; cached prefix
  blocks are free;
- decode growth that can't get a page PREEMPTS the request back to the
  queue (pages freed), the reference scheduler's block-exhaustion path.
"""

from __future__ import annotations

import asyncio
import hashlib
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.engine.page_table import KvEvent, PageAllocator
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.tokens import TokenBlockSequence


@dataclass(frozen=True)
class MockEngineArgs:
    num_pages: int = 256
    page_size: int = 16
    #: simulated step tick (all running requests produce one token per tick)
    decode_s_per_step: float = 0.002
    #: shared chunked-prefill token budget per tick (scheduler.rs batching)
    prefill_tokens_per_step: int = 512
    #: max concurrently-running requests (batch cap)
    max_batch: int = 32
    #: fraction of the pool kept free at admission (kv_manager watermark)
    watermark: float = 0.05
    vocab_size: int = 256
    salt: str = "mock"
    #: legacy knob kept for compat: folded into the prefill budget model
    prefill_s_per_token: float = 0.0


@dataclass
class _Req:
    request: PreprocessedRequest
    context: object
    chain: TokenBlockSequence
    hashes: list
    out_q: asyncio.Queue
    pages: list = field(default_factory=list)
    cached_blocks: int = 0
    prefill_left: int = 0  # uncached prompt tokens still to prefill
    history: list = field(default_factory=list)
    produced: int = 0
    preemptions: int = 0


class MockEngine:
    def __init__(
        self,
        args: MockEngineArgs = MockEngineArgs(),
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
        sla=None,
        slo_windows=None,
    ):
        self.args = args
        self.allocator = PageAllocator(
            args.num_pages, args.page_size, on_event=on_kv_event
        )
        self.active_requests = 0
        self.requests_received = 0
        self.generated_tokens = 0
        self.preemptions = 0
        self._waiting: deque[_Req] = deque()
        self._running: list[_Req] = []
        self._loop_task: Optional[asyncio.Task] = None
        #: real SLO plane (telemetry/slo.py) fed with MEASURED stream
        #: latencies — mock fleets are full citizens of the fleet
        #: telemetry plane, so the closed-loop planner's burn/attainment
        #: signals work against a 500-worker mocker fleet exactly as
        #: against JaxEngine workers (ROADMAP item 4's scale proof)
        from dynamo_tpu.telemetry.slo import SloTracker

        self.slo = SloTracker(
            sla=sla,
            **({"windows": tuple(slo_windows)} if slo_windows else {}),
        )

    # -- queue visibility (planner/metrics) --------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    @property
    def num_running(self) -> int:
        return len(self._running)

    def _next_token(self, history: list[int]) -> int:
        h = hashlib.blake2b(bytes(str(history[-8:]), "utf-8"), digest_size=4)
        return int.from_bytes(h.digest(), "little") % self.args.vocab_size

    # -- public API ---------------------------------------------------------

    async def generate(self, context, request: PreprocessedRequest):
        import time as _time

        a = self.args
        self.active_requests += 1
        self.requests_received += 1
        chain = TokenBlockSequence(
            request.token_ids, block_size=a.page_size, salt=a.salt
        )
        req = _Req(
            request=request,
            context=context,
            chain=chain,
            hashes=list(chain.sequence_hashes()),
            out_q=asyncio.Queue(),
            history=list(request.token_ids),
        )
        self._waiting.append(req)
        self._ensure_loop()
        # measured stream latencies feed the SLO plane: TTFT includes
        # queue wait (the saturation signal the planner scales on)
        t0 = _time.monotonic()
        t_first = t_last = None
        tokens = 0
        try:
            while True:
                item = await req.out_q.get()
                if item is None:
                    return
                if "error" in item:
                    # Same stream protocol as AsyncEngineRunner.drain:
                    # raising turns a capacity rejection into a typed HTTP
                    # failure instead of an empty 200 "stop" completion.
                    raise RuntimeError(item["error"])
                now = _time.monotonic()
                n = len(item.get("token_ids", ()))
                tokens += n
                self.generated_tokens += n
                if n:
                    if t_first is None:
                        t_first = now
                        self.slo.observe("ttft_ms", (now - t0) * 1000.0)
                    elif t_last is not None:
                        self.slo.observe(
                            "itl_ms", (now - t_last) * 1000.0
                        )
                    t_last = now
                yield item
        finally:
            self.active_requests -= 1
            req.context = _CANCELLED  # consumer gone: step loop reaps it
            if t_first is not None:
                now = _time.monotonic()
                e2e = (now - t0) * 1000.0
                itl = (
                    (now - t_first) / max(1, tokens - 1) * 1000.0
                    if tokens > 1
                    else None
                )
                self.slo.observe("e2e_ms", e2e)
                self.slo.finish_request(
                    ttft_ms=(t_first - t0) * 1000.0,
                    itl_ms=itl,
                    e2e_ms=e2e,
                    tokens=tokens,
                )

    # -- step loop ----------------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(
                self._step_loop()
            )

    async def _step_loop(self) -> None:
        idle_ticks = 0
        while idle_ticks < 50:
            await asyncio.sleep(self.args.decode_s_per_step)
            if self._step():
                idle_ticks = 0
            else:
                idle_ticks += 1

    def _step(self) -> bool:
        """One engine tick: reap cancels, admit, prefill-chunk, decode.
        Returns True when any request is resident."""
        self._reap_cancelled()
        self._admit()
        budget = self.args.prefill_tokens_per_step
        for req in list(self._running):
            if req.prefill_left > 0:
                if budget <= 0:
                    continue
                step = min(req.prefill_left, budget)
                req.prefill_left -= step
                budget -= step
                if req.prefill_left == 0:
                    self._register_prompt(req)
            else:
                self._decode_one(req)
        return bool(self._running or self._waiting)

    def _reap_cancelled(self) -> None:
        for q in [r for r in self._running if r.context.cancelled]:
            self._finish(q, emit=None)
        for q in [r for r in self._waiting if r.context.cancelled]:
            self._waiting.remove(q)
            q.out_q.put_nowait(None)

    def _admit(self) -> None:
        a = self.args
        while self._waiting and len(self._running) < a.max_batch:
            req = self._waiting[0]
            # After a preemption the tokens to (re)prefill are the FULL
            # history (prompt + produced), not just the original prompt —
            # sizing from the prompt would leave later blocks pageless and
            # silently unregistered.
            tokens = req.history
            # Gate with match_length (no refs, no LRU movement): a blocked
            # head-of-line request polls every tick and must not perturb
            # eviction order while it waits.
            cached_n = self.allocator.match_length(req.hashes)
            need = max(-(-(len(tokens) + 1) // a.page_size) - cached_n, 0)
            max_admittable = (
                a.num_pages - 1 - int(a.watermark * a.num_pages)
            )
            if need > max_admittable:
                # Can NEVER fit: reject instead of wedging the queue.
                self._waiting.popleft()
                req.out_q.put_nowait(
                    {
                        "error": (
                            f"prompt needs {need} KV pages; pool admits at "
                            f"most {max_admittable}"
                        ),
                    }
                )
                req.out_q.put_nowait(None)
                continue
            # Watermark: admission must leave `watermark` of the pool free.
            if self.allocator.num_free - need < a.watermark * a.num_pages:
                return  # head-of-line blocks; keeps FIFO fairness
            cached = self.allocator.lookup(req.hashes)
            n_new = max(-(-(len(tokens) + 1) // a.page_size) - len(cached), 0)
            pages = self.allocator.allocate(n_new) if n_new else []
            if pages is None:
                if cached:
                    self.allocator.free(cached)
                return
            self._waiting.popleft()
            req.cached_blocks = len(cached)
            req.pages = list(cached) + list(pages)
            req.prefill_left = max(
                len(tokens) - len(cached) * a.page_size, 0
            )
            self._running.append(req)
            if req.prefill_left == 0:
                self._register_prompt(req)

    def _register_prompt(self, req: _Req) -> None:
        for bi in range(req.cached_blocks, len(req.chain.blocks)):
            if bi < len(req.pages):
                blk = req.chain.blocks[bi]
                self.allocator.register(
                    req.pages[bi],
                    blk.sequence_hash,
                    blk.parent_sequence_hash,
                    blk.tokens,
                )

    def _decode_one(self, req: _Req) -> None:
        r = req.request
        tok = self._next_token(req.history)
        committed = req.chain.append(tok)
        if committed is not None:
            page_idx = committed.block_index
            if page_idx < len(req.pages):
                self.allocator.register(
                    req.pages[page_idx],
                    committed.sequence_hash,
                    committed.parent_sequence_hash,
                    committed.tokens,
                )
            grown = self.allocator.allocate(1)
            if grown is None:
                # Block exhaustion: preempt back to the queue (pages
                # freed; prefix blocks stay cached for the re-run).
                self.preemptions += 1
                req.preemptions += 1
                self.allocator.free(req.pages)
                req.pages = []
                self._running.remove(req)
                # re-prefill from scratch next admission (cache helps)
                req.chain = TokenBlockSequence(
                    req.history, block_size=self.args.page_size,
                    salt=self.args.salt,
                )
                req.hashes = list(req.chain.sequence_hashes())
                self._waiting.appendleft(req)
                return
            req.pages.extend(grown)
        req.history.append(tok)
        req.produced += 1
        stop = (
            not r.ignore_eos and tok in r.stop_token_ids
        ) or req.produced >= r.max_tokens
        item = {
            "token_ids": [tok],
            "finish_reason": (
                ("stop" if tok in r.stop_token_ids else "length")
                if stop
                else None
            ),
        }
        if stop:
            self._finish(req, emit=item)
        else:
            req.out_q.put_nowait(item)

    def _finish(self, req: _Req, emit: Optional[dict]) -> None:
        if req in self._running:
            self._running.remove(req)
        if req.pages:
            self.allocator.free(req.pages)
            req.pages = []
        if emit is not None:
            req.out_q.put_nowait(emit)
        req.out_q.put_nowait(None)


class _Cancelled:
    cancelled = True


_CANCELLED = _Cancelled()

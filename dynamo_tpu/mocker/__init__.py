from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

__all__ = ["MockEngine", "MockEngineArgs"]

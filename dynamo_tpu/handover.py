"""Worker handover: live KV migration between workers (ISSUE 12 tentpole).

A retiring worker stops admissions (the PR-8 drain machinery), exports its
device-registered KV blocks in the canonical quantized wire format, ships
them to a successor over the EXISTING disagg transfer planes (the
successor pre-reserves pages and arms a transfer waiter, so the bytes
ride the very same `KvTransferClient.send` path — device / shm / bulk /
inline, checksummed end-to-end — that remote prefill uses), and the
successor registers the landed pages, publishing `stored` KV events so
KV-aware routing scores it immediately. In-flight streams then continue
on the successor via the PR-10 crash-replay path — their prompt blocks
are already warm, so the replayed prefill is a prefix-cache hit, not a
recompute — and the retiring process exits 0.

This module holds the orchestration-side helpers shared by worker.py and
the planner actuators (planner/service.py FleetHandover, FleetFlipper):
topological ordering of the registered block graph, byte-bounded
batching, and the one-shot direct ingress call.

Failure semantics (docs/operations.md "Rolling upgrades & worker
handover"): any fault mid-extract / mid-offer / mid-transfer / mid-adopt
degrades the handover to the plain drain path — the worker finishes (or
severs) its in-flight work and exits; streams continue on survivors by
replay-with-recompute; the successor's reservation watchdog frees its
pages. No phase can hang a stream or leak a page on either side.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional, Sequence

import msgpack

from dynamo_tpu.runtime.codec import encode_frame, read_frame

logger = logging.getLogger(__name__)

#: blocks shipped per transfer batch; each batch is an independently
#: adoptable topo-contiguous segment, so a mid-migration failure leaves
#: the successor with a usable prefix, never a broken chain
BATCH_BLOCKS = int(os.environ.get("DYN_KV_HANDOVER_BATCH_BLOCKS", "64"))

#: byte budget for one handover (hottest chains ship first; beyond this
#: the remainder stays behind and is recomputed on demand). 0 = unbounded.
MAX_BYTES = int(os.environ.get("DYN_KV_HANDOVER_MAX_BYTES", "0"))

#: successor-side reservation watchdog: pages allocated for an offer are
#: freed if the bytes never land inside this window
ADOPT_TIMEOUT_S = float(os.environ.get("DYN_KV_HANDOVER_ADOPT_TIMEOUT", "30"))


def topo_order_metas(page_meta_values) -> list[tuple]:
    """Order (seq_hash, parent_hash, tokens) triples parents-first.

    Input is the allocator's registered-page metadata (any order). The
    output is a DFS preorder over the block forest rooted at
    parent_hash=None — every block appears after its parent, so any
    topo-contiguous batch prefix is adoptable on its own. Orphan
    subtrees (parent evicted locally) are dropped: the successor could
    never prefix-match into them, and `adopt_blocks` would refuse them
    anyway."""
    by_hash: dict[int, tuple] = {}
    for h, p, tokens in page_meta_values:
        by_hash[h] = (p, tokens)
    children: dict[Optional[int], list[int]] = {}
    roots: list[int] = []
    for h, (p, _) in by_hash.items():
        if p is None:
            roots.append(h)
        elif p in by_hash:
            children.setdefault(p, []).append(h)
        # else: orphan subtree — skipped
    out: list[tuple] = []
    stack = sorted(roots, reverse=True)
    while stack:
        h = stack.pop()
        p, tokens = by_hash[h]
        out.append((h, p, tokens))
        stack.extend(sorted(children.get(h, ()), reverse=True))
    return out


def batches(metas: Sequence[tuple], batch_blocks: int = 0):
    """Yield topo-contiguous meta batches of at most `batch_blocks`."""
    n = batch_blocks or BATCH_BLOCKS
    for i in range(0, len(metas), n):
        yield metas[i : i + n]


def metas_to_wire(metas: Sequence[tuple]) -> list:
    return [
        [int(h), None if p is None else int(p), list(t)] for h, p, t in metas
    ]


def metas_from_wire(wire) -> list[tuple]:
    return [(int(h), None if p is None else int(p), tuple(t)) for h, p, t in wire]


async def call_ingress(
    host: str,
    port: int,
    endpoint: str,
    body: Optional[dict] = None,
    timeout: float = 10.0,
    request_id: str = "direct",
) -> dict:
    """One-shot direct call to a worker's ingress `endpoint`: returns the
    FIRST data frame as a dict. Raises RuntimeError on an error frame
    (message preserved) and on an empty stream. Used by worker→worker
    handover offers and the planner's flip/handover actuators — peers that
    have no PushRouter and need exactly one request/reply."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(
            encode_frame(
                {"op": "call", "request_id": request_id, "endpoint": endpoint},
                msgpack.packb(body or {}, use_bin_type=True),
            )
        )
        await writer.drain()
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            left = deadline - asyncio.get_running_loop().time()
            if left <= 0:
                raise asyncio.TimeoutError(
                    f"{endpoint} call to {host}:{port} timed out"
                )
            header, payload = await asyncio.wait_for(read_frame(reader), left)
            op = header.get("op")
            if op == "error":
                raise RuntimeError(header.get("message") or f"{endpoint} failed")
            if op == "data":
                reply = msgpack.unpackb(payload, raw=False)
                return reply if isinstance(reply, dict) else {"reply": reply}
            if op == "end":
                raise RuntimeError(f"{endpoint} returned no reply")
            # anything else (stray frames): keep reading until deadline
    finally:
        writer.close()

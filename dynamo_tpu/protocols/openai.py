"""OpenAI-compatible protocol types (chat/completions/embeddings) + SSE.

Pydantic models for the public HTTP surface, with a Dynamo-style extension
block (`ext` here, `nvext` in the reference — /root/reference lib/llm/src/
protocols/openai/nvext.rs) for framework-specific options (ignore_eos,
annotations). Delta aggregation for non-streaming responses mirrors the
reference's aggregator (protocols/openai/aggregator.rs).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Literal, Optional, Union

from pydantic import BaseModel, Field


class Ext(BaseModel):
    """Framework extensions (the reference's nvext)."""

    ignore_eos: Optional[bool] = None
    annotations: Optional[dict[str, Any]] = None
    #: greedy-route this request to a specific worker instance
    instance_id: Optional[str] = None
    #: suppress eos/stop-token finishes until this many output tokens
    #: (the reference's common-protocol min_tokens)
    min_tokens: Optional[int] = None
    #: skip chat-template rendering; tokenize the message contents
    #: verbatim (reference nvext.rs use_raw_prompt)
    use_raw_prompt: Optional[bool] = None
    #: force argmax decoding regardless of temperature (nvext.rs
    #: greed_sampling)
    greed_sampling: Optional[bool] = None
    #: multiplicative repetition penalty over GENERATED tokens, in the
    #: reference's (0, 2.0] range (1 = off; nvext.rs repetition_penalty —
    #: also accepted at top level, where any > 0 value is an accepted
    #: extension). Unlike HF's processor it deliberately skips prompt
    #: tokens — docs/migrating.md "Sampling semantics".
    repetition_penalty: Optional[float] = None


class ChatMessage(BaseModel):
    role: Literal["system", "user", "assistant", "tool"] = "user"
    content: Union[str, list[dict[str, Any]], None] = None
    name: Optional[str] = None


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    model: str
    messages: list[ChatMessage]
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # extension accepted at top level too
    n: Optional[int] = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    stop: Union[str, list[str], None] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None  # extension, like top_k
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None  # 0-20 alternatives when logprobs=true
    #: OpenAI logit_bias: token id (JSON string or int) -> bias in
    #: [-100, 100], applied in the sampler
    logit_bias: Optional[dict[Union[int, str], float]] = None
    #: OpenAI function-calling tool definitions. Rendered into the chat
    #: template (HF templates accept `tools`) so tool-trained models see
    #: them; the engine does not parse tool_call outputs (pass-through,
    #: like the reference forwarding requests to its engines).
    tools: Optional[list[dict]] = None
    ext: Optional[Ext] = None
    nvext: Optional[Ext] = None  # accepted alias for drop-in compatibility

    @property
    def extension(self) -> Ext:
        return self.ext or self.nvext or Ext()

    @property
    def effective_max_tokens(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens


class CompletionRequest(BaseModel):
    model: str
    prompt: Union[str, list[str], list[int]]
    max_tokens: Optional[int] = 16
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: Optional[int] = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    stop: Union[str, list[str], None] = None
    seed: Optional[int] = None
    echo: Optional[bool] = False
    logprobs: Optional[int] = None  # legacy: N => chosen + top-N per token
    logit_bias: Optional[dict[Union[int, str], float]] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None  # extension, like top_k
    ext: Optional[Ext] = None
    nvext: Optional[Ext] = None

    @property
    def extension(self) -> Ext:
        return self.ext or self.nvext or Ext()


class EmbeddingRequest(BaseModel):
    model: str
    input: Union[str, list[str], list[int], list[list[int]]]
    encoding_format: Optional[str] = "float"


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    #: OpenAI detail block; carries {"cached_tokens": n} when the prompt
    #: hit the prefix cache
    prompt_tokens_details: Optional[dict[str, int]] = None


def combine_usages(usages: list["Usage"]) -> Optional["Usage"]:
    """Fold per-choice usage blocks (`n` > 1) into one: the shared prompt
    counts once, completion tokens sum."""
    if not usages:
        return None
    u = Usage(
        prompt_tokens=usages[0].prompt_tokens,
        completion_tokens=sum(x.completion_tokens for x in usages),
        # deterministic across n>1 sibling completion order: the MAX of
        # the siblings' cached counts (a fresh prefill plus cache-hitting
        # siblings must not flip between absent and ~full-prompt per run)
        prompt_tokens_details=max(
            (x.prompt_tokens_details for x in usages
             if x.prompt_tokens_details),
            key=lambda d: d.get("cached_tokens", 0),
            default=None,
        ),
    )
    u.total_tokens = u.prompt_tokens + u.completion_tokens
    return u


class EmbeddingData(BaseModel):
    object: str = "embedding"
    index: int = 0
    #: list of floats, or a base64 string when encoding_format="base64"
    embedding: Union[list[float], str] = Field(default_factory=list)


class EmbeddingResponse(BaseModel):
    object: str = "list"
    model: str = ""
    data: list[EmbeddingData] = Field(default_factory=list)
    usage: Usage = Field(default_factory=Usage)


class TopLogprob(BaseModel):
    token: str = ""
    logprob: float = 0.0
    bytes: Optional[list[int]] = None


class TokenLogprob(BaseModel):
    token: str = ""
    logprob: float = 0.0
    bytes: Optional[list[int]] = None
    top_logprobs: list[TopLogprob] = Field(default_factory=list)


class ChoiceLogprobs(BaseModel):
    """Chat-API logprobs block: one entry per emitted token."""

    content: list[TokenLogprob] = Field(default_factory=list)


class CompletionLogprobs(BaseModel):
    """Legacy completions-API logprobs block (parallel arrays)."""

    tokens: list[str] = Field(default_factory=list)
    token_logprobs: list[float] = Field(default_factory=list)
    top_logprobs: list[dict[str, float]] = Field(default_factory=list)
    text_offset: list[int] = Field(default_factory=list)


class ChatChoiceDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None


class ChatStreamChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta = Field(default_factory=ChatChoiceDelta)
    logprobs: Optional[ChoiceLogprobs] = None
    finish_reason: Optional[str] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: str = "chat.completion.chunk"
    created: int = 0
    model: str = ""
    choices: list[ChatStreamChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage = Field(default_factory=lambda: ChatMessage(role="assistant", content=""))
    logprobs: Optional[ChoiceLogprobs] = None
    finish_reason: Optional[str] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: str = "chat.completion"
    created: int = 0
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    logprobs: Optional[CompletionLogprobs] = None
    finish_reason: Optional[str] = None


class CompletionResponse(BaseModel):
    id: str
    object: str = "text_completion"
    created: int = 0
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


# -- Responses API (the reference serves /v1/responses alongside chat:
# lib/llm/src/protocols/openai/responses.rs + http route openai.rs) -------


class ResponsesRequest(BaseModel):
    model: str
    #: a plain string, or a list of {role, content} input messages
    input: Union[str, list[dict[str, Any]]]
    instructions: Optional[str] = None
    max_output_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    stream: bool = False
    ext: Optional[Ext] = None
    nvext: Optional[Ext] = None

    @property
    def extension(self) -> Ext:
        return self.ext or self.nvext or Ext()

    def as_chat_messages(self) -> list["ChatMessage"]:
        msgs: list[ChatMessage] = []
        if self.instructions:
            msgs.append(ChatMessage(role="system", content=self.instructions))
        if isinstance(self.input, str):
            msgs.append(ChatMessage(role="user", content=self.input))
        else:
            for m in self.input:
                msgs.append(ChatMessage.model_validate(m))
        return msgs


class ResponseOutputText(BaseModel):
    type: str = "output_text"
    text: str = ""
    annotations: list = Field(default_factory=list)


class ResponseOutputMessage(BaseModel):
    type: str = "message"
    id: str = ""
    status: str = "completed"
    role: str = "assistant"
    content: list[ResponseOutputText] = Field(default_factory=list)


class ResponsesUsage(BaseModel):
    input_tokens: int = 0
    output_tokens: int = 0
    total_tokens: int = 0


class ResponsesResponse(BaseModel):
    id: str
    object: str = "response"
    created_at: int = 0
    status: str = "completed"
    model: str = ""
    output: list[ResponseOutputMessage] = Field(default_factory=list)
    usage: Optional[ResponsesUsage] = None

    @property
    def output_text(self) -> str:
        return "".join(
            part.text for msg in self.output for part in msg.content
        )


class ModelInfo(BaseModel):
    id: str
    object: str = "model"
    created: int = 0
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: str = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "cmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now() -> int:
    return int(time.time())


# -- SSE ---------------------------------------------------------------------


def sse_event(data: BaseModel | dict) -> bytes:
    if isinstance(data, BaseModel):
        body = data.model_dump_json(exclude_none=True)
    else:
        body = json.dumps(data)
    return f"data: {body}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"


def aggregate_chat_stream(
    chunks: list[ChatCompletionChunk], model: str, request_id: str
) -> ChatCompletionResponse:
    """Fold a chunk stream into a non-streaming response. Chunks may
    interleave multiple choice indices (`n` > 1); each folds into its own
    choice, and usage sums completion tokens across choices (prompt
    counted once)."""
    text: dict[int, list[str]] = {}
    finish: dict[int, Optional[str]] = {}
    lp_entries: dict[int, list[TokenLogprob]] = {}
    usages: list[Usage] = []
    for ch in chunks:
        for choice in ch.choices:
            i = choice.index
            if choice.delta.content:
                text.setdefault(i, []).append(choice.delta.content)
            if choice.logprobs is not None:
                lp_entries.setdefault(i, []).extend(choice.logprobs.content)
            if choice.finish_reason:
                finish[i] = choice.finish_reason
        if ch.usage is not None:
            usages.append(ch.usage)
    usage = combine_usages(usages)
    indices = sorted(set(text) | set(finish) | set(lp_entries)) or [0]
    return ChatCompletionResponse(
        id=request_id,
        created=now(),
        model=model,
        choices=[
            ChatChoice(
                index=i,
                message=ChatMessage(
                    role="assistant", content="".join(text.get(i, []))
                ),
                logprobs=(
                    ChoiceLogprobs(content=lp_entries[i])
                    if i in lp_entries
                    else None
                ),
                finish_reason=finish.get(i),
            )
            for i in indices
        ],
        usage=usage,
    )

"""Cost-based worker selection with softmax-temperature sampling.

For each candidate worker the selector computes the work the request would
cost there — blocks still to prefill plus the load the worker would carry —
and samples from a softmax over the negated costs. Temperature 0 is argmin
(deterministic best); higher temperatures spread load across near-ties so a
single hot prefix doesn't concentrate every request on one worker.

Capability parity with the reference's KvScheduler / DefaultWorkerSelector
(/root/reference lib/llm/src/kv_router/scheduler.rs — schedule :204,
select_worker :360, logit = overlap_weight·prefill_blocks + potential_blocks
:391, softmax_sample :276; KvRouterConfig — kv_router.rs:55).
"""

from __future__ import annotations

import logging
import math
import random
from dataclasses import dataclass, field
from typing import Optional, Protocol, Sequence

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class KvRouterConfig:
    #: weight on blocks-to-prefill relative to total resulting load
    overlap_score_weight: float = 1.0
    #: softmax temperature; 0 ⇒ deterministic argmin cost
    temperature: float = 0.0
    #: ignore workers whose KV pool is fuller than this fraction
    max_kv_usage: float = 0.98
    #: rng seed for reproducible sampling in tests (None ⇒ nondeterministic)
    seed: Optional[int] = None


@dataclass
class WorkerSnapshot:
    """One worker's load as seen by the router: the published metrics merged
    with router-local in-flight bookkeeping (ActiveSequences)."""

    instance_id: str
    kv_active_blocks: float = 0.0
    kv_total_blocks: float = 0.0
    num_waiting: int = 0
    num_running: int = 0

    @property
    def kv_usage(self) -> float:
        if self.kv_total_blocks <= 0:
            return 0.0
        return self.kv_active_blocks / self.kv_total_blocks


class WorkerSelector(Protocol):
    def select(
        self,
        workers: Sequence[WorkerSnapshot],
        overlaps: dict[str, int],
        total_blocks: int,
    ) -> Optional[str]: ...


def softmax_sample(
    neg_costs: Sequence[float], temperature: float, rng: random.Random
) -> int:
    """Sample an index ∝ softmax(neg_costs / temperature); argmax at T=0."""
    if temperature <= 0:
        return max(range(len(neg_costs)), key=lambda i: neg_costs[i])
    m = max(neg_costs)
    weights = [math.exp((c - m) / temperature) for c in neg_costs]
    return rng.choices(range(len(neg_costs)), weights=weights, k=1)[0]


@dataclass
class DefaultWorkerSelector:
    config: KvRouterConfig = field(default_factory=KvRouterConfig)

    def __post_init__(self):
        self._rng = random.Random(self.config.seed)

    def select(
        self,
        workers: Sequence[WorkerSnapshot],
        overlaps: dict[str, int],
        total_blocks: int,
    ) -> Optional[str]:
        if not workers:
            return None
        eligible = [
            w for w in workers if w.kv_usage < self.config.max_kv_usage
        ] or list(workers)
        neg_costs = []
        for w in eligible:
            prefill_blocks = total_blocks - overlaps.get(w.instance_id, 0)
            potential_blocks = w.kv_active_blocks + prefill_blocks
            cost = (
                self.config.overlap_score_weight * prefill_blocks
                + potential_blocks
            )
            neg_costs.append(-cost)
        idx = softmax_sample(neg_costs, self.config.temperature, self._rng)
        chosen = eligible[idx]
        logger.debug(
            "kv select %s: overlap=%d/%d cost=%.1f",
            chosen.instance_id,
            overlaps.get(chosen.instance_id, 0),
            total_blocks,
            -neg_costs[idx],
        )
        return chosen.instance_id

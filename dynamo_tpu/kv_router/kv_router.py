"""KvRouter: the routing brain gluing indexer + metrics + active sequences
to a worker choice.

`choose` hashes the request's prompt into chained blocks, asks the index
who holds how much of that prefix, merges published load with router-local
in-flight bookkeeping, and lets the selector pick. The router also prunes
departed workers out of every sub-structure from the endpoint's instance
watch, and emits a `kv-hit-rate` event per decision for observability.

Capability parity with the reference's KvRouter (/root/reference
lib/llm/src/kv_router/kv_router.rs — find_best_match :163, block split
with salt :171, event subscription :131-152, per-token/active bookkeeping
:204-210; KV hit-rate event subject — scheduler.rs:37).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional, Sequence

from dynamo_tpu import telemetry
from dynamo_tpu.kv_router.indexer import KvIndexer
from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
    WorkerSnapshot,
)
from dynamo_tpu.kv_router.sequence import ActiveSequences
from dynamo_tpu.subjects import KV_HIT_RATE_SUBJECT
from dynamo_tpu.tokens import hash_token_blocks

logger = logging.getLogger(__name__)


class KvRouter:
    def __init__(
        self,
        fabric,
        component: str,
        instance_source,
        block_size: int,
        salt: str,
        config: Optional[KvRouterConfig] = None,
        selector=None,
        indexer_shards: int = 1,
        economy=None,
    ):
        self.fabric = fabric
        self.component = component
        self.source = instance_source
        self.block_size = block_size
        self.salt = salt
        self.config = config or KvRouterConfig()
        self.selector = selector or DefaultWorkerSelector(self.config)
        self.metrics = MetricsAggregator(fabric, component)
        # self-healing index (docs/operations.md "KV index consistency"):
        # snapshots come from the workers' `kv.snapshot` ingress op,
        # digests from the same metrics frames this router already
        # aggregates — sequence gaps and digest drift mark a subtree
        # stale (scored cold) and trigger a targeted resync
        if indexer_shards > 1:
            from dynamo_tpu.kv_router.indexer import KvIndexerSharded

            self.indexer = KvIndexerSharded(
                fabric,
                num_shards=indexer_shards,
                snapshot_fn=self._fetch_snapshot,
                digest_source=self._worker_digests,
            )
        else:
            self.indexer = KvIndexer(
                fabric,
                snapshot_fn=self._fetch_snapshot,
                digest_source=self._worker_digests,
            )
        self.active = ActiveSequences(block_size)
        #: KV economy (kv_economy.EconomyPolicy, docs/operations.md "The
        #: KV economy"): when set, find_best_match extends warmth scores
        #: through lower tiers and, when a remote worker's deeper prefix
        #: beats the chosen worker's by more than the transfer cost,
        #: pulls the hot chain to the choice instead of cold-prefilling.
        #: None (the default) keeps the decision path bit-identical to
        #: the pre-economy router.
        self.economy = economy
        #: distinguishes this router's kv_index.status frames from other
        #: routers serving the same component (the metrics service keys
        #: and sums per (component, router) — two frontends must not
        #: overwrite each other's counters into a sawtooth)
        import uuid

        self.router_id = uuid.uuid4().hex[:12]
        self._prune_task: Optional[asyncio.Task] = None
        self._bootstrap_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await self.indexer.start()
        await self.metrics.start()
        self._prune_task = asyncio.get_running_loop().create_task(
            self._prune_loop()
        )
        # cold-start bootstrap: load live workers' snapshots instead of
        # waiting for event repopulation (a restarted router scores warm
        # prefixes within one round trip per worker)
        self._bootstrap_task = asyncio.get_running_loop().create_task(
            self._bootstrap()
        )

    async def _bootstrap(self) -> None:
        try:
            instances = self.source.list()
            if not instances:
                instances = await self.source.wait_for_instances(timeout=2.0)
            n = await self.indexer.bootstrap(
                [i.instance_id for i in instances]
            )
            if n:
                logger.info(
                    "kv index bootstrapped from %d worker snapshot(s)", n
                )
        except Exception:
            logger.warning("kv index bootstrap failed", exc_info=True)

    async def _fetch_snapshot(self, worker_id: str) -> Optional[dict]:
        """`kv.snapshot` fetch for the indexer's resync path."""
        inst = next(
            (
                i
                for i in self.source.list()
                if i.instance_id == worker_id
            ),
            None,
        )
        if inst is None:
            return None
        from dynamo_tpu.handover import call_ingress

        return await call_ingress(
            inst.host, inst.port, "kv.snapshot", {}, timeout=5.0
        )

    def _worker_digests(self) -> dict:
        """Latest worker-advertised digests for the anti-entropy sweep."""
        out = {}
        for iid, m in self.metrics.snapshot().items():
            d = m.get("kv_digest")
            if isinstance(d, dict):
                out[iid] = d
        return out

    async def _prune_loop(self, interval: float = 1.0) -> None:
        """Drop state for workers whose registration disappeared. "Known"
        workers are whatever the index/metrics/bookkeeping have actually
        heard from — not a polled history — so a worker that lives and dies
        between two ticks is still cleaned up."""
        from dynamo_tpu.subjects import KV_INDEX_SUBJECT

        while True:
            await asyncio.sleep(interval)
            live = {i.instance_id for i in self.source.list()}
            known = (
                self.indexer.workers()
                | set(self.metrics.snapshot())
                | self.active.workers()
            )
            if (
                self.economy is not None
                and self.economy.tier_map is not None
            ):
                self.economy.tier_map.retain_workers(list(live))
            for gone in known - live:
                n = self.indexer.remove_worker(gone)
                self.active.remove_worker(gone)
                self.metrics.remove(gone)
                if n:
                    logger.info(
                        "pruned %d indexed blocks of departed worker %s",
                        n, gone,
                    )
            # index-health heartbeat: the metrics service folds this into
            # dynamo_tpu_router_kv_index_*{component,router} and
            # /v1/fleet's `kv_index` section (doctor's kv-index-drift
            # rule reads it)
            try:
                await self.fabric.publish(
                    KV_INDEX_SUBJECT,
                    {
                        "component": self.component,
                        "router": self.router_id,
                        **self.indexer.stats(),
                    },
                )
            except Exception:
                logger.debug("kv_index status publish failed", exc_info=True)

    # -- the decision ------------------------------------------------------

    def _snapshots(self, instance_ids: Sequence[str]) -> list[WorkerSnapshot]:
        published = self.metrics.snapshot()
        out = []
        for iid in instance_ids:
            m = published.get(iid, {})
            # Published active pages lag; router-local bookkeeping covers the
            # gap. Take the max so neither signal is double counted.
            local = self.active.active_blocks(iid)
            out.append(
                WorkerSnapshot(
                    instance_id=iid,
                    kv_active_blocks=max(
                        float(m.get("kv_active_pages", 0)), float(local)
                    ),
                    kv_total_blocks=float(m.get("kv_total_pages", 0)),
                    num_waiting=int(m.get("num_waiting", 0)),
                    num_running=int(m.get("num_running", 0)),
                )
            )
        return out

    async def find_best_match(
        self, token_ids: Sequence[int], request_id: Optional[str] = None
    ) -> tuple[Optional[str], int]:
        """Pick a worker for this prompt; returns (instance_id, overlap_blocks)
        and registers the in-flight footprint when request_id is given."""
        with telemetry.span(
            "kv.choose", service="router",
            attrs={"isl_tokens": len(token_ids)},
        ) as sp:
            instances = self.source.list()
            if not instances:
                instances = await self.source.wait_for_instances(timeout=2.0)
            ids = [i.instance_id for i in instances]
            hashes = hash_token_blocks(
                token_ids, block_size=self.block_size, salt=self.salt
            )
            overlaps = self.indexer.find_matches(hashes)
            scores = overlaps.scores
            if self.economy is not None:
                # warmth extended past HBM: tiered blocks count at their
                # promotion-discounted value (a COPY — off-path scoring
                # is untouched)
                scores = self.economy.scored_with_tiers(scores, ids, hashes)
            choice = self.selector.select(
                self._snapshots(ids), scores, len(hashes)
            )
            sp.set_attr("total_blocks", len(hashes))
            sp.set_attr("candidates", len(ids))
            if choice is None:
                sp.set_attr("chosen", None)
                return None, 0
            overlap = overlaps.scores.get(choice, 0)
            if self.economy is not None and hashes:
                overlap = await self._maybe_migrate(
                    instances, hashes, overlaps.scores, choice, overlap
                )
            # the routing decision, traceable per request: who won, how
            # much of the prefix they already hold, and the score field
            sp.set_attr("chosen", choice)
            sp.set_attr("matched_blocks", overlap)
            sp.set_attr(
                "overlap_score",
                overlap / len(hashes) if hashes else 0.0,
            )
            if request_id is not None:
                total_blocks = -(-len(token_ids) // self.block_size)
                self.active.add(choice, request_id, total_blocks - overlap)
            await self._emit_hit_rate(len(token_ids), overlap)
            return choice, overlap

    async def _maybe_migrate(
        self,
        instances,
        hashes: Sequence[int],
        scores: dict[str, int],
        choice: str,
        overlap: int,
    ) -> int:
        """The KV economy's routing decision: when the deepest REMOTE
        holder of this prefix beats the chosen worker by more blocks
        than the transfer costs (CostModel), ask the holder to push the
        missing chain to the choice through the handover offer/transfer
        plane — the request then admits warm instead of cold-prefilling.

        Every deny/failure path returns the unmodified overlap: the
        request cold-prefills exactly as the pre-economy router would
        have. Returns the (possibly migration-credited) overlap."""
        eco = self.economy
        source, source_ov = None, overlap
        for iid, sc in scores.items():
            if iid != choice and sc > source_ov:
                source, source_ov = iid, sc
        delta = source_ov - overlap
        if source is None or not eco.cost_model.should_migrate(delta):
            return overlap
        # the deepest matched block hash names the prefix for
        # single-flight/backoff purposes
        prefix_key = int(hashes[min(source_ov, len(hashes)) - 1])
        admitted, reason = eco.manager.admit(
            prefix_key, choice, eco.cost_model.bytes_moved(delta)
        )
        if not admitted:
            logger.debug(
                "migration of %x to %s suppressed (%s)",
                prefix_key, choice, reason,
            )
            return overlap
        done, moved_bytes, moved_blocks = False, 0, 0
        try:
            by_id = {i.instance_id: i for i in instances}
            src, dst = by_id.get(source), by_id.get(choice)
            if src is None or dst is None:
                return overlap
            from dynamo_tpu.handover import call_ingress

            reply = await asyncio.wait_for(
                call_ingress(
                    src.host, src.port, "migrate_prefix",
                    {
                        "hashes": [
                            int(h) for h in hashes[overlap:source_ov]
                        ],
                        "dest": {
                            "instance_id": choice,
                            "host": dst.host,
                            "port": dst.port,
                        },
                    },
                ),
                timeout=eco.migrate_timeout_s,
            )
            if reply.get("migrated"):
                done = True
                moved_blocks = int(reply.get("blocks") or 0)
                moved_bytes = int(reply.get("bytes") or 0)
        except Exception:
            logger.warning(
                "prefix migration %s -> %s failed; request cold-prefills",
                source, choice, exc_info=True,
            )
        finally:
            eco.manager.complete(
                prefix_key, choice, done, moved_bytes, moved_blocks
            )
        return source_ov if done else overlap

    async def _emit_hit_rate(self, isl: int, overlap_blocks: int) -> None:
        try:
            await self.fabric.publish(
                KV_HIT_RATE_SUBJECT,
                {
                    "isl_tokens": isl,
                    "overlap_blocks": overlap_blocks,
                    "overlap_tokens": overlap_blocks * self.block_size,
                },
            )
        except Exception:
            logger.debug("kv-hit-rate publish failed", exc_info=True)

    # -- PushRouter integration -------------------------------------------

    async def choose(self, request: dict) -> Optional[str]:
        """kv_chooser hook for PushRouter: request is a PreprocessedRequest
        wire dict."""
        choice, _ = await self.find_best_match(
            request.get("token_ids", ()), request_id=request.get("request_id")
        )
        return choice

    def on_tokens(self, request_id: str, n: int) -> None:
        self.active.on_tokens(request_id, n)

    def on_complete(self, request_id: str) -> None:
        self.active.free(request_id)

    async def stop(self) -> None:
        if self._prune_task is not None:
            self._prune_task.cancel()
        if self._bootstrap_task is not None:
            self._bootstrap_task.cancel()
        await self.indexer.stop()
        await self.metrics.stop()

"""Approximate KV index for event-less engines.

When a worker can't emit KV events, the router can still estimate locality:
every routing decision implies the chosen worker will shortly hold the
request's blocks, so record them locally with a TTL matched to the worker's
expected cache residency. Strictly an estimate — eviction on the worker is
invisible — but it captures the dominant effect (recent prompts are hot).

Capability parity with the reference's ApproxKvIndexer
(/root/reference lib/llm/src/kv_router/approx.rs:157).
"""

from __future__ import annotations

import heapq
import time
from typing import Optional, Sequence

from dynamo_tpu.kv_router.indexer import OverlapScores, make_radix_tree


class ApproxKvIndexer:
    def __init__(self, ttl_s: float = 120.0, clock=time.monotonic):
        self.ttl_s = ttl_s
        self.tree = make_radix_tree()
        self._clock = clock
        #: (expiry, worker_id, hash) min-heap; stale entries are skipped on
        #: pop when _latest shows a refresh
        self._expiries: list[tuple[float, str, int]] = []
        #: (worker_id, hash) -> newest expiry (routing decisions refresh TTL)
        self._latest: dict[tuple[str, int], float] = {}

    def process_routing_decision(
        self, worker_id: str, seq_hashes: Sequence[int]
    ) -> None:
        now = self._clock()
        self.tree.apply_event(
            worker_id, {"kind": "stored", "block_hashes": list(seq_hashes)}
        )
        expiry = now + self.ttl_s
        for h in seq_hashes:
            heapq.heappush(self._expiries, (expiry, worker_id, h))
            self._latest[(worker_id, h)] = expiry

    def _expire(self) -> None:
        now = self._clock()
        while self._expiries and self._expiries[0][0] <= now:
            expiry, worker_id, h = heapq.heappop(self._expiries)
            if self._latest.get((worker_id, h), expiry) > expiry:
                continue  # refreshed since this entry was pushed
            self._latest.pop((worker_id, h), None)
            self.tree.apply_event(
                worker_id, {"kind": "removed", "block_hashes": [h]}
            )

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        self._expire()
        return self.tree.find_matches(seq_hashes)

    def remove_worker(self, worker_id: str) -> int:
        for key in [k for k in self._latest if k[0] == worker_id]:
            del self._latest[key]
        return self.tree.remove_worker(worker_id)

"""KV event stream recorder / replayer.

Records the live `kv_events.>` stream to JSONL for offline debugging, and
replays a recording back onto a fabric (optionally time-scaled) so routing
behavior can be reproduced without the workers that generated it.

Capability parity with the reference's KvRecorder
(/root/reference lib/llm/src/kv_router/recorder.rs; python surface
_core.pyi:637-704).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from pathlib import Path
from typing import Optional

import msgpack

from dynamo_tpu.subjects import KV_EVENT_SUBJECT

logger = logging.getLogger(__name__)


class KvRecorder:
    def __init__(self, fabric, path: str, subject: str = KV_EVENT_SUBJECT):
        self.fabric = fabric
        self.path = Path(path)
        self.subject = subject
        self.event_count = 0
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._t0: Optional[float] = None

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        with self.path.open("a") as f:
            while True:
                msg = await self._sub.next()
                if msg is None:
                    return
                now = time.monotonic()
                if self._t0 is None:
                    self._t0 = now
                events = msgpack.unpackb(msg.payload, raw=False)
                for ev in events:
                    f.write(
                        json.dumps(
                            {
                                "t": now - self._t0,
                                "worker": msg.header.get("instance_id"),
                                "event": ev,
                            }
                        )
                        + "\n"
                    )
                    self.event_count += 1
                f.flush()

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()


async def replay(
    fabric,
    path: str,
    subject: str = KV_EVENT_SUBJECT,
    timed: bool = False,
    speed: float = 1.0,
) -> int:
    """Publish a recording back onto the fabric. timed=False replays as fast
    as possible; otherwise sleeps to reproduce original spacing / speed."""
    n = 0
    last_t = 0.0
    for line in Path(path).read_text().splitlines():
        rec = json.loads(line)
        if timed and rec["t"] > last_t:
            await asyncio.sleep((rec["t"] - last_t) / speed)
        last_t = rec["t"]
        await fabric.publish(
            f"{subject}.{rec['worker']}",
            {"instance_id": rec["worker"], "count": 1},
            msgpack.packb([rec["event"]], use_bin_type=True),
        )
        n += 1
    return n

"""Rolling block-set digest: the KV index's drift detector.

One number summarizes a worker's whole registered block set: the XOR of
`xxh3_64(le64(seq_hash), DIGEST_SEED)` over every registered chained
block hash, plus the set size. XOR makes the fold order-independent and
self-inverse — store toggles a block in, remove toggles it out, both
O(1) — so the WORKER maintains it incrementally on the event publish
path, ships it in its metrics frames, and serves it (with the full hash
forest) from the `kv.snapshot` ingress op; the INDEXER recomputes the
same fold from its per-worker indexed set during the anti-entropy sweep
(RadixTree.digest_for / native dyn_radix_digest). Equal (fold, count)
at equal sequence number == the index holds exactly the worker's real
block set; any mismatch is drift, and drift triggers a targeted resync
(kv_router/indexer.py).

The per-hash xxh3 wrap (rather than XOR-ing raw hashes) keeps related
chained hashes from cancelling structurally; the same seed + little-
endian byte layout is implemented natively in native/dynamo_native.cpp
dyn_radix_digest — tests assert the two agree.
"""

from __future__ import annotations

import struct

import xxhash

#: seed isolating the digest fold from every other xxh3 use in the stack
DIGEST_SEED = 0x5E0D16E57

_MASK64 = (1 << 64) - 1


def fold_one(seq_hash: int) -> int:
    """The per-block fold term: xxh3 of the hash's 8 LE bytes."""
    return xxhash.xxh3_64_intdigest(
        struct.pack("<Q", seq_hash & _MASK64), seed=DIGEST_SEED
    )


def fold_hashes(hashes) -> tuple[int, int]:
    """(fold, count) of a full hash set — the from-scratch recompute used
    after a resync subtree replace and by RadixTree.digest_for."""
    fold = 0
    n = 0
    for h in hashes:
        fold ^= fold_one(h)
        n += 1
    return fold, n


class SetDigest:
    """Incrementally-maintained (fold, count) over an exact hash set.

    The worker-side publisher keeps one of these: exact set semantics
    (duplicate stores / removes of absent hashes are no-ops) guarantee
    the digest always equals fold_hashes(current set), so the advertised
    digest is trustworthy even against a buggy or replayed event
    stream."""

    __slots__ = ("fold", "blocks")

    def __init__(self):
        self.fold = 0
        #: hash -> parent hash (the forest the kv.snapshot op serves)
        self.blocks: dict[int, int | None] = {}

    @property
    def count(self) -> int:
        return len(self.blocks)

    def store(self, seq_hash: int, parent: int | None = None) -> bool:
        if seq_hash in self.blocks:
            return False
        self.blocks[seq_hash] = parent
        self.fold ^= fold_one(seq_hash)
        return True

    def remove(self, seq_hash: int) -> bool:
        if seq_hash not in self.blocks:
            return False
        del self.blocks[seq_hash]
        self.fold ^= fold_one(seq_hash)
        return True

    def clear(self) -> None:
        self.fold = 0
        self.blocks.clear()

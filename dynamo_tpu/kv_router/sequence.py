"""Router-local in-flight bookkeeping.

Published worker metrics lag (they arrive on the publish interval), so the
router tracks what *it* has sent each worker: per-request block footprints
that grow as tokens stream back and are released on completion. Merging
this with the scraped metrics closes the feedback gap that would otherwise
let a burst of requests all land on the momentarily-idle-looking worker.

Capability parity with the reference's ActiveSequences /
ActiveSequencesMultiWorker (/root/reference lib/llm/src/kv_router/
sequence.rs:74,247; fed per token from kv_router.rs:204-210).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class _Active:
    worker_id: str
    blocks: int
    tokens_seen: int = 0


class ActiveSequences:
    def __init__(self, block_size: int):
        self.block_size = block_size
        self._by_request: dict[str, _Active] = {}
        self._blocks_by_worker: dict[str, int] = {}

    def add(self, worker_id: str, request_id: str, prompt_blocks: int) -> None:
        if request_id in self._by_request:
            self.free(request_id)
        self._by_request[request_id] = _Active(worker_id, prompt_blocks)
        self._blocks_by_worker[worker_id] = (
            self._blocks_by_worker.get(worker_id, 0) + prompt_blocks
        )

    def on_tokens(self, request_id: str, n: int) -> None:
        """Account n generated tokens; every block_size tokens grows the
        footprint by one block."""
        a = self._by_request.get(request_id)
        if a is None:
            return
        before = a.tokens_seen // self.block_size
        a.tokens_seen += n
        grown = a.tokens_seen // self.block_size - before
        if grown:
            a.blocks += grown
            self._blocks_by_worker[a.worker_id] += grown

    def free(self, request_id: str) -> str | None:
        a = self._by_request.pop(request_id, None)
        if a is None:
            return None
        left = self._blocks_by_worker.get(a.worker_id, 0) - a.blocks
        if left > 0:
            self._blocks_by_worker[a.worker_id] = left
        else:
            self._blocks_by_worker.pop(a.worker_id, None)
        return a.worker_id

    def remove_worker(self, worker_id: str) -> int:
        gone = [
            rid for rid, a in self._by_request.items() if a.worker_id == worker_id
        ]
        for rid in gone:
            del self._by_request[rid]
        self._blocks_by_worker.pop(worker_id, None)
        return len(gone)

    def workers(self) -> set[str]:
        return {a.worker_id for a in self._by_request.values()}

    def active_blocks(self, worker_id: str) -> int:
        return self._blocks_by_worker.get(worker_id, 0)

    def active_seqs(self, worker_id: str) -> int:
        return sum(
            1 for a in self._by_request.values() if a.worker_id == worker_id
        )

    def __len__(self) -> int:
        return len(self._by_request)

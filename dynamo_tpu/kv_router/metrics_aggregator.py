"""Worker load-metrics aggregation.

Workers publish EngineMetrics snapshots on `metrics.{component}.{instance}`
every interval (worker.py _publish_loop); this aggregator subscribes the
component's whole subject space and serves the latest snapshot per live
worker, pruning entries that stop refreshing.

Capability parity with the reference's EndpointCollector /
collect_endpoints_task (/root/reference lib/llm/src/kv_router/
metrics_aggregator.rs:31,124 — there a NATS service-stats scrape; here the
workers push, which removes the scrape round-trip).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from dynamo_tpu.subjects import METRICS_SUBJECT

logger = logging.getLogger(__name__)


class MetricsAggregator:
    def __init__(
        self,
        fabric,
        component: str,
        stale_after: float = 10.0,
        subject: str = METRICS_SUBJECT,
    ):
        self.fabric = fabric
        self.component = component
        self.stale_after = stale_after
        self.subject = f"{subject}.{component}.>"
        self._latest: dict[str, tuple[dict, float]] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(self.subject)
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            m = getattr(msg, "header", None)
            try:
                iid = m.get("instance_id")
            except (AttributeError, TypeError):
                # One malformed publish (non-dict header, a worker dying
                # mid-frame) must not kill the pump and freeze every
                # later snapshot at its pre-crash state.
                logger.warning("malformed metrics frame: %r", m)
                continue
            if iid:
                self._latest[str(iid)] = (m, time.monotonic())

    def snapshot(self) -> dict[str, dict]:
        """instance_id → latest metrics dict, stale entries pruned."""
        now = time.monotonic()
        dead = [
            iid
            for iid, (_, ts) in self._latest.items()
            if now - ts > self.stale_after
        ]
        for iid in dead:
            del self._latest[iid]
        return {iid: m for iid, (m, _) in self._latest.items()}

    def snapshot_with_age(self) -> dict[str, tuple[dict, float]]:
        """instance_id → (latest metrics dict, seconds since it landed);
        stale entries pruned like snapshot(). The age becomes the fleet
        snapshot's per-worker `last_seen_s` field."""
        now = time.monotonic()
        self.snapshot()  # prune
        return {
            iid: (m, now - ts) for iid, (m, ts) in self._latest.items()
        }

    def for_instance(self, instance_id: str) -> Optional[dict]:
        entry = self._latest.get(instance_id)
        return entry[0] if entry else None

    def remove(self, instance_id: str) -> None:
        self._latest.pop(instance_id, None)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()

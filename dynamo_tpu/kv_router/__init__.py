"""KV-cache-aware routing.

Global view of which worker holds which content-addressed KV blocks, kept
fresh by worker-emitted KV events, plus a cost-based scheduler that sends
each request to the worker where the most prefix KV is already resident
(capability parity with the reference's kv_router family —
/root/reference lib/llm/src/kv_router/: KvRouter kv_router.rs:163,
RadixTree indexer.rs:239, KvScheduler scheduler.rs:204, ActiveSequences
sequence.rs:74, metrics_aggregator.rs, approx.rs, recorder.rs).
"""

from dynamo_tpu.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.kv_router.kv_router import KvRouter
from dynamo_tpu.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
    WorkerSnapshot,
)
from dynamo_tpu.kv_router.sequence import ActiveSequences

__all__ = [
    "ActiveSequences",
    "DefaultWorkerSelector",
    "KvIndexer",
    "KvRouter",
    "KvRouterConfig",
    "OverlapScores",
    "RadixTree",
    "WorkerSnapshot",
]

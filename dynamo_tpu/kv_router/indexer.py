"""Global KV-block index: (worker × chained block hash) → overlap scores.

Because block identity is a *chained* sequence hash (tokens/blocks.py), the
prefix tree over blocks collapses to a flat map: a sequence hash uniquely
names its entire ancestry, so membership of hash h implies the exact prefix
chain. `find_matches` therefore walks the request's hash chain in order and
scores each worker by its **contiguous** prefix length — only contiguous
blocks are reusable by an engine's prefix cache, so that is the true number
of prefill blocks saved.

Capability parity with the reference's RadixTree indexer
(/root/reference lib/llm/src/kv_router/indexer.rs — RadixTree :239,
apply_event :283, KvIndexer :518, OverlapScores :410), re-designed around
the flat chained-hash map instead of a pointer tree.
"""

from __future__ import annotations

import asyncio
import logging
import time
import weakref
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional, Sequence

import msgpack

from dynamo_tpu.kv_router.digest import DIGEST_SEED, fold_hashes
from dynamo_tpu.subjects import KV_EVENT_SUBJECT

logger = logging.getLogger(__name__)


# -- process-global index-health counters (telemetry/debug.kv_index_lines
# exposes them as dynamo_tpu_kv_index_{gaps,resyncs,drift_blocks}_total on
# both Prometheus surfaces; docs/operations.md "KV index consistency") ----


class IndexHealthCounters:
    def __init__(self):
        self.gaps = 0
        self.resyncs = 0
        self.resync_failures = 0
        self.drift_blocks = 0
        self.digest_mismatches = 0

    def reset(self) -> None:
        self.__init__()


index_counters = IndexHealthCounters()

#: live indexers in this process (weak — a dropped router must not pin
#: its index); the stale-workers gauge sums over them
_live_indexers: "weakref.WeakSet" = weakref.WeakSet()


def process_stale_workers() -> int:
    return sum(len(idx._stale) for idx in _live_indexers)


@dataclass
class OverlapScores:
    """Per-worker contiguous-prefix overlap, in blocks."""

    scores: dict[str, int] = field(default_factory=dict)
    #: how many leading blocks of the query hit *any* worker
    matched_blocks: int = 0

    def best(self) -> tuple[Optional[str], int]:
        if not self.scores:
            return None, 0
        worker = max(self.scores, key=lambda w: (self.scores[w], w))
        return worker, self.scores[worker]


class RadixTree:
    """Worker-set per chained block hash, with per-worker reverse index for
    O(worker's blocks) removal when a lease expires."""

    def __init__(self):
        self._workers_by_hash: dict[int, set[str]] = {}
        self._hashes_by_worker: dict[str, set[int]] = {}
        self.events_applied = 0

    # -- mutation ----------------------------------------------------------

    def apply_event(self, worker_id: str, event: dict) -> None:
        """Apply one stored/removed/handed_over event (the wire dict form
        emitted by workers — worker.py _publish_loop)."""
        kind = event["kind"]
        hashes = event["block_hashes"]
        if kind == "stored":
            self._store(worker_id, hashes)
        elif kind == "removed":
            self._remove(worker_id, hashes)
        elif kind == "handed_over":
            # bulk ownership move (worker handover): every block this
            # worker held now lives on the successor — reassign in one
            # pass instead of waiting for lease expiry + stored-event
            # propagation, so prefix routing scores the successor the
            # moment the retiring worker announces
            self.move_worker(worker_id, str(event.get("successor") or ""))
        else:
            logger.warning("unknown kv event kind %r", kind)
        self.events_applied += 1

    def _store(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.setdefault(worker_id, set())
        for h in hashes:
            self._workers_by_hash.setdefault(h, set()).add(worker_id)
            mine.add(h)

    def _remove(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.get(worker_id)
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker_id: str) -> int:
        """Drop every block owned by a departed worker."""
        hashes = self._hashes_by_worker.pop(worker_id, set())
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
        return len(hashes)

    def take_worker(self, worker_id: str) -> list[int]:
        """remove_worker that RETURNS the dropped hashes — the sharded
        indexer's cross-shard move is a take on the source shard + a
        bulk store on the destination shard."""
        hashes = self._hashes_by_worker.pop(worker_id, set())
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
        return list(hashes)

    def store_bulk(self, worker_id: str, hashes: Sequence[int]) -> None:
        self._store(worker_id, hashes)

    def move_worker(self, src: str, dst: str) -> int:
        """Bulk ownership move (worker handover): reassign every block of
        `src` to `dst` in one pass. Slightly optimistic — blocks whose
        transfer actually failed are credited to `dst` too — which is
        self-healing: a mis-routed prefix costs one cold prefill, and
        the successor's own stored/removed events correct the set."""
        if not dst or dst == src:
            return self.remove_worker(src)
        hashes = self.take_worker(src)
        if hashes:
            self._store(dst, hashes)
        return len(hashes)

    def clear(self) -> None:
        self._workers_by_hash.clear()
        self._hashes_by_worker.clear()

    # -- query -------------------------------------------------------------

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        active: Optional[set[str]] = None
        for depth, h in enumerate(seq_hashes):
            holders = self._workers_by_hash.get(h)
            if not holders:
                break
            active = set(holders) if active is None else active & holders
            if not active:
                break
            out.matched_blocks = depth + 1
            for w in active:
                out.scores[w] = depth + 1
        return out

    # -- introspection -----------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return len(self._workers_by_hash)

    def num_workers(self) -> int:
        return len(self._hashes_by_worker)

    def workers(self) -> set[str]:
        return set(self._hashes_by_worker)

    def blocks_for(self, worker_id: str) -> int:
        return len(self._hashes_by_worker.get(worker_id, ()))

    def digest_for(self, worker_id: str) -> tuple[int, int]:
        """(xxh3-fold, count) of this worker's indexed block set — the
        anti-entropy comparand against the worker-advertised digest
        (kv_router/digest.py; the native tree computes the identical
        fold in dyn_radix_digest)."""
        return fold_hashes(self._hashes_by_worker.get(worker_id, ()))


class NativeRadixTree:
    """Same interface as RadixTree, backed by the C++ index
    (native/dynamo_native.cpp RadixIndex) via ctypes. Worker names are
    interned to u32 ids on the native side; this wrapper mirrors the
    id<->name mapping and the live-worker set."""

    def __init__(self):
        from dynamo_tpu import native

        self._lib = native.lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._ptr = self._lib.dyn_radix_new()
        self._ids: dict[str, int] = {}
        self._names: list[str] = []
        self._live: set[str] = set()
        #: unknown-kind events counted here so events_applied matches the
        #: Python tree (which counts every event, known or not)
        self._unknown_events = 0

    def __del__(self):
        lib, ptr = getattr(self, "_lib", None), getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.dyn_radix_free(ptr)
            self._ptr = None

    def _intern(self, worker_id: str) -> int:
        wid = self._ids.get(worker_id)
        if wid is None:
            wid = self._lib.dyn_radix_intern(self._ptr, worker_id.encode())
            self._ids[worker_id] = wid
            assert wid == len(self._names)
            self._names.append(worker_id)
        return wid

    @staticmethod
    def _hash_buf(hashes: Sequence[int]):
        import numpy as np

        try:
            arr = np.ascontiguousarray(np.asarray(hashes, dtype=np.uint64))
        except (OverflowError, ValueError, TypeError):
            arr = np.asarray([h & (1 << 64) - 1 for h in hashes], np.uint64)
        return arr, arr.ctypes.data, len(arr)

    def apply_event(self, worker_id: str, event: dict) -> None:
        kind = event["kind"]
        hashes = event["block_hashes"]  # KeyError parity with RadixTree
        if kind == "handed_over":
            dst = str(event.get("successor") or "")
            moved = self.move_worker(worker_id, dst)
            if not (dst and dst != worker_id and moved):
                # events_applied parity: a real move counted one native
                # apply (the store_bulk); an empty/removal-only move
                # counted none
                self._unknown_events += 1
            return
        if kind not in ("stored", "removed"):
            logger.warning("unknown kv event kind %r", kind)
            self._unknown_events += 1
            return
        arr, buf, n = self._hash_buf(hashes)
        self._lib.dyn_radix_apply(
            self._ptr, self._intern(worker_id), 0 if kind == "stored" else 1,
            buf, n,
        )
        if kind == "stored":
            self._live.add(worker_id)

    def remove_worker(self, worker_id: str) -> int:
        self._live.discard(worker_id)
        wid = self._ids.get(worker_id)
        if wid is None:
            return 0
        return self._lib.dyn_radix_remove_worker(self._ptr, wid)

    def take_worker(self, worker_id: str) -> list[int]:
        """remove_worker that RETURNS the dropped hashes (native
        enumeration via dyn_radix_take_worker) — full parity with the
        Python tree, so bulk-ownership moves and resync subtree swaps
        behave identically on both implementations."""
        import numpy as np

        self._live.discard(worker_id)
        wid = self._ids.get(worker_id)
        if wid is None:
            return []
        n = self._lib.dyn_radix_blocks_for(self._ptr, wid)
        out = np.empty(max(1, n), np.uint64)
        k = self._lib.dyn_radix_take_worker(self._ptr, wid, out.ctypes.data, n)
        return [int(x) for x in out[: min(k, n)]]

    def store_bulk(self, worker_id: str, hashes) -> None:
        if not hashes:
            return
        arr, buf, n = self._hash_buf(list(hashes))
        self._lib.dyn_radix_apply(self._ptr, self._intern(worker_id), 0, buf, n)
        self._live.add(worker_id)

    def move_worker(self, src: str, dst: str) -> int:
        if not dst or dst == src:
            return self.remove_worker(src)
        hashes = self.take_worker(src)
        if hashes:
            self.store_bulk(dst, hashes)
        return len(hashes)

    def clear(self) -> None:
        self._lib.dyn_radix_clear(self._ptr)
        self._live.clear()

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        import ctypes

        import numpy as np

        out = OverlapScores()
        if not seq_hashes:
            return out
        arr, buf, n = self._hash_buf(seq_hashes)
        cap = max(1, len(self._names))
        ids = np.empty(cap, np.uint32)
        scores = np.empty(cap, np.uint32)
        matched = ctypes.c_size_t(0)
        k = self._lib.dyn_radix_find(
            self._ptr, buf, n, ids.ctypes.data, scores.ctypes.data, cap,
            ctypes.byref(matched),
        )
        out.matched_blocks = int(matched.value)
        for i in range(k):
            out.scores[self._names[ids[i]]] = int(scores[i])
        return out

    # -- introspection (parity with RadixTree) ------------------------------

    @property
    def events_applied(self) -> int:
        return self._lib.dyn_radix_events_applied(self._ptr) + self._unknown_events

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._ptr)

    def num_workers(self) -> int:
        return len(self._live)

    def workers(self) -> set[str]:
        return set(self._live)

    def blocks_for(self, worker_id: str) -> int:
        wid = self._ids.get(worker_id)
        if wid is None:
            return 0
        return self._lib.dyn_radix_blocks_for(self._ptr, wid)

    def digest_for(self, worker_id: str) -> tuple[int, int]:
        import ctypes

        wid = self._ids.get(worker_id)
        if wid is None:
            return (0, 0)
        fold = ctypes.c_uint64(0)
        n = self._lib.dyn_radix_digest(
            self._ptr, wid, DIGEST_SEED, ctypes.byref(fold)
        )
        return (int(fold.value), int(n))


def make_radix_tree():
    """Native-backed tree when libdynamo_native is available, else Python."""
    from dynamo_tpu import native

    if native.lib() is not None:
        return NativeRadixTree()
    return RadixTree()


# -- convergence machinery (docs/operations.md "KV index consistency") ----
#
# The fabric's pub/sub is at-most-once per connection epoch; the replay
# ring (runtime/fabric/local.py) narrows but cannot close the loss window
# (ring trimmed, broker restarted without a WAL, worker publish failures).
# So the index defends itself end to end:
#
#   gap detection   every worker stamps its events with a monotonic `seq`
#                   (worker.py _stamp_kv_events); a skipped seq == lost
#                   events == this worker's subtree may be wrong.
#   anti-entropy    workers advertise a rolling (seq, xxh3-fold, count)
#                   digest of their registered set in their metrics
#                   frames; a periodic sweep compares it — at equal seq —
#                   against the index's own per-worker digest, catching
#                   silent drift no gap ever reveals (and a lost stream
#                   TAIL: the frame's seq keeps leading while the index's
#                   stops moving).
#   stale-as-cold   a worker flagged by either detector is scored COLD by
#                   find_matches until repaired: a false cold hit costs
#                   one prefill; a false warm hit routes a request at
#                   pages that do not exist.
#   targeted resync fetch the worker's full hash forest over the
#                   `kv.snapshot` ingress op, atomically replace its
#                   subtree (live events buffered during the swap, then
#                   replayed past the snapshot's seq), and un-stale it.
#                   Cold start bootstraps the same way instead of waiting
#                   for event repopulation.


@dataclass
class _WkState:
    """Per-worker consistency bookkeeping (event-loop confined)."""

    last_seq: int = 0
    #: a stamped event or snapshot has established the cursor
    tracked: bool = False
    stale: bool = False
    resyncing: bool = False
    #: events held back while a resync swap is in flight
    buffer: list = field(default_factory=list)
    #: consecutive sweeps the advertised seq led a non-advancing cursor
    lag_sweeps: int = 0
    prev_sweep_seq: int = -1
    #: consecutive sweeps the digest mismatched at equal seq — one
    #: mismatch can be transient skew (a sharded drain backlog between
    #: the screened cursor and the tree), so drift needs two in a row
    mismatch_sweeps: int = 0
    #: sweeps to sit out entirely: set on the SUCCESSOR of a
    #: handed_over move, whose advertised digest lags the index's
    #: optimistic credit until its adoption `stored` events publish —
    #: comparing inside that window would cold-score the very worker
    #: the handover just warmed
    sweep_grace: int = 0


class _ConsistencyBase:
    """Sequence/digest/staleness logic shared by KvIndexer and
    KvIndexerSharded; subclasses provide `_apply_events` (route one
    screened batch into the tree(s)), `_swap_subtree` (atomic remove +
    bulk store, serialized with event application) and `_digest_of`."""

    #: seconds between anti-entropy sweeps / stale-repair attempts
    anti_entropy_interval: float = 2.0

    def _init_consistency(
        self,
        snapshot_fn: Optional[Callable[[str], Awaitable[Optional[dict]]]],
        digest_source: Optional[Callable[[], dict]],
        anti_entropy_interval: float,
    ) -> None:
        self.snapshot_fn = snapshot_fn
        self.digest_source = digest_source
        self.anti_entropy_interval = anti_entropy_interval
        self._states: dict[str, _WkState] = {}
        self._stale: set[str] = set()
        self.gaps_total = 0
        self.resyncs_total = 0
        self.resync_failures_total = 0
        self.drift_blocks_total = 0
        self.digest_mismatches_total = 0
        self._consistency_task: Optional[asyncio.Task] = None
        _live_indexers.add(self)

    @property
    def resync_enabled(self) -> bool:
        return self.snapshot_fn is not None

    def stats(self) -> dict:
        """Index-health snapshot (KvRouter publishes it on
        kv_index.status; doctor's kv-index-drift rule reads the fold)."""
        return {
            "gaps_total": self.gaps_total,
            "resyncs_total": self.resyncs_total,
            "resync_failures_total": self.resync_failures_total,
            "drift_blocks_total": self.drift_blocks_total,
            "digest_mismatches_total": self.digest_mismatches_total,
            "stale_workers": len(self._stale),
            "workers_tracked": sum(
                1 for s in self._states.values() if s.tracked
            ),
            "resync_enabled": self.resync_enabled,
        }

    def stale_workers(self) -> set[str]:
        return set(self._stale)

    def _state(self, worker_id: str) -> _WkState:
        st = self._states.get(worker_id)
        if st is None:
            st = self._states[worker_id] = _WkState()
        return st

    def _mark_stale(self, worker_id: str, st: _WkState, why: str) -> None:
        if not self.resync_enabled:
            # no repair path configured: keep the legacy scoring behavior
            # (never down-score), just surface the observation
            logger.warning(
                "kv index %s for worker %s (no resync configured)",
                why, worker_id,
            )
            return
        if not st.stale:
            st.stale = True
            self._stale.add(worker_id)
            logger.warning(
                "kv index marked worker %s stale (%s); scoring it cold "
                "until resync", worker_id, why,
            )

    def _note_gap(self, worker_id: str, st: _WkState, seq: int) -> None:
        self.gaps_total += 1
        index_counters.gaps += 1
        self._mark_stale(
            worker_id, st,
            f"sequence gap (have {st.last_seq}, saw {seq})",
        )

    def _screen_events(self, worker_id: str, events: list) -> list:
        """Event-loop-side admission of one published batch: duplicates
        (transport redelivery / resume overlap) dropped, events held
        while a resync swap is in flight, sequence gaps flagged.
        Unstamped events (sequencing off / older peers) pass through
        untracked — the pre-sequencing behavior, bit for bit."""
        out = []
        st = self._states.get(worker_id)
        for ev in events:
            seq = ev.get("seq") if isinstance(ev, dict) else None
            if not isinstance(seq, int) or seq <= 0:
                out.append(ev)
                continue
            if st is None:
                st = self._state(worker_id)
            if st.resyncing:
                st.buffer.append(ev)
                continue
            if st.tracked and seq <= st.last_seq:
                continue  # duplicate
            if st.tracked and seq > st.last_seq + 1:
                self._note_gap(worker_id, st, seq)
            elif not st.tracked and seq > 1 and self.resync_enabled:
                # first contact mid-stream: everything before `seq` was
                # published before we subscribed (indexer restart) —
                # same repair as a gap
                self._note_gap(worker_id, st, seq)
            st.last_seq = seq
            st.tracked = True
            if ev.get("kind") == "handed_over":
                # the move credits the successor with blocks its OWN
                # digest won't advertise until its adoption `stored`
                # events publish — give it a comparison grace window
                succ = ev.get("successor")
                if succ and succ != worker_id:
                    self._state(str(succ)).sweep_grace = 2
            out.append(ev)
        return out

    def _filter_stale(self, out: "OverlapScores") -> "OverlapScores":
        """Stale subtrees score COLD: drop their entries so the selector
        can never route a warm hit at pages the worker may not hold."""
        if self._stale:
            dropped = False
            for w in self._stale:
                if out.scores.pop(w, None) is not None:
                    dropped = True
            if dropped:
                out.matched_blocks = max(out.scores.values(), default=0)
        return out

    def _forget_worker(self, worker_id: str) -> None:
        self._states.pop(worker_id, None)
        self._stale.discard(worker_id)

    # -- resync ------------------------------------------------------------

    async def _resync(self, worker_id: str) -> bool:
        """Snapshot fetch → atomic subtree replace → buffered-event
        replay. False (and the worker stays stale) when the snapshot is
        unavailable — a dead worker stays cold until the prune loop
        removes it; a live one is retried next sweep."""
        if not self.resync_enabled:
            return False
        st = self._state(worker_id)
        if st.resyncing:
            return False
        st.resyncing = True
        snap = None
        try:
            snap = await self.snapshot_fn(worker_id)
        except Exception:
            logger.warning(
                "kv.snapshot fetch from %s failed", worker_id,
                exc_info=True,
            )
        swapped = False
        try:
            if isinstance(snap, dict) and snap.get("sequencing"):
                # a malformed snapshot body (mixed-version peer, junk
                # hashes) must fail like an unavailable one — never
                # leave st.resyncing latched with the buffer growing
                hashes = [int(b[0]) for b in snap.get("blocks") or ()]
                seq = int(snap.get("seq") or 0)
                drift = await self._swap_subtree(worker_id, hashes)
                swapped = True
        except Exception:
            logger.warning(
                "kv.snapshot from %s unusable", worker_id, exc_info=True
            )
        if not swapped:
            self.resync_failures_total += 1
            index_counters.resync_failures += 1
            buffered, st.buffer = st.buffer, []
            st.resyncing = False
            # apply what we buffered anyway — newer truth beats nothing —
            # and keep the worker stale for the next attempt
            events = self._screen_events(worker_id, buffered)
            if events:
                await self._apply_events(worker_id, events)
            return False
        self.drift_blocks_total += drift
        index_counters.drift_blocks += drift
        st.last_seq = seq
        st.tracked = True
        st.lag_sweeps = 0
        st.mismatch_sweeps = 0
        buffered, st.buffer = st.buffer, []
        st.resyncing = False
        if st.stale:
            st.stale = False
            self._stale.discard(worker_id)
        self.resyncs_total += 1
        index_counters.resyncs += 1
        # fleet event timeline: a resync marks the moment a subtree's
        # routing went cold->warm again (GET /v1/fleet/events; Grafana
        # annotations) — joined to any traces that overlapped it
        from dynamo_tpu.telemetry import events as fleet_events

        fleet_events.record(
            "kv_resync", source=worker_id, seq=seq,
            blocks=len(hashes), drift_blocks=drift,
        )
        # events that arrived during the swap: anything at or below the
        # snapshot's seq is already IN the snapshot; the rest applies on
        # top (an in-buffer gap re-flags and re-syncs)
        events = self._screen_events(worker_id, buffered)
        if events:
            await self._apply_events(worker_id, events)
        logger.info(
            "kv index resynced worker %s: %d blocks at seq %d "
            "(%d drift corrected)", worker_id, len(hashes), seq, drift,
        )
        return True

    async def bootstrap(self, worker_ids: Sequence[str]) -> int:
        """Cold-start population from live workers' snapshots instead of
        waiting for event repopulation (indexer restart / late join).
        Returns how many workers were loaded."""
        n = 0
        for w in worker_ids:
            try:
                if await self._resync(w):
                    n += 1
            except Exception:
                logger.warning("bootstrap of %s failed", w, exc_info=True)
        return n

    # -- anti-entropy ------------------------------------------------------

    def _start_consistency(self) -> None:
        if self.snapshot_fn is not None or self.digest_source is not None:
            self._consistency_task = asyncio.get_running_loop().create_task(
                self._consistency_loop()
            )

    def _stop_consistency(self) -> None:
        if self._consistency_task is not None:
            self._consistency_task.cancel()

    async def _consistency_loop(self) -> None:
        while True:
            await asyncio.sleep(self.anti_entropy_interval)
            try:
                await self._consistency_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("kv index consistency tick failed")

    async def _consistency_tick(self) -> None:
        # 1. repair: every stale subtree gets a resync attempt
        for w in list(self._stale):
            st = self._states.get(w)
            if st is not None and not st.resyncing:
                await self._resync(w)
        # 2. anti-entropy sweep against the metrics-frame digests
        if self.digest_source is None:
            return
        try:
            digests = self.digest_source() or {}
        except Exception:
            logger.warning("digest source failed", exc_info=True)
            return
        for w, d in digests.items():
            if not isinstance(d, dict):
                continue
            try:
                seq = int(d.get("seq") or 0)
                fold = int(d.get("fold") or 0)
                count = int(d.get("count") or 0)
            except (TypeError, ValueError):
                continue
            st = self._state(w)
            if st.resyncing or st.stale:
                continue
            if st.sweep_grace > 0:
                st.sweep_grace -= 1
                continue
            if seq == st.last_seq:
                # comparable cut: the index applied exactly through the
                # digest's seq, so the sets must be identical. One
                # mismatched sweep can still be transient skew (the
                # sharded drain thread lagging the screened cursor) —
                # only two in a row is drift.
                ifold, icount = self._digest_of(w)
                if (ifold, icount) != (fold, count):
                    st.mismatch_sweeps += 1
                    if st.mismatch_sweeps >= 2:
                        st.mismatch_sweeps = 0
                        self.digest_mismatches_total += 1
                        index_counters.digest_mismatches += 1
                        self._mark_stale(
                            w, st,
                            f"digest drift at seq {seq} "
                            f"(index {icount} blocks, worker {count})",
                        )
                else:
                    st.mismatch_sweeps = 0
                st.lag_sweeps = 0
            elif seq > st.last_seq:
                # the worker is ahead. Normally the missing events are in
                # flight and the cursor catches up; a cursor that does
                # NOT move across consecutive sweeps means the stream's
                # tail was lost — the one loss shape no later event's
                # seq can ever reveal
                if st.prev_sweep_seq == st.last_seq:
                    st.lag_sweeps += 1
                else:
                    st.lag_sweeps = 1
                if st.lag_sweeps >= 2:
                    st.lag_sweeps = 0
                    self._note_gap(w, st, seq)
            else:
                st.lag_sweeps = 0
            st.prev_sweep_seq = st.last_seq

    # -- subclass hooks ----------------------------------------------------

    async def _apply_events(self, worker_id: str, events: list) -> None:
        raise NotImplementedError

    async def _swap_subtree(self, worker_id: str, hashes: list[int]) -> int:
        raise NotImplementedError

    def _digest_of(self, worker_id: str) -> tuple[int, int]:
        raise NotImplementedError


def _resolve_future(fut: asyncio.Future, value) -> None:
    if not fut.done():
        fut.set_result(value)


class _SwapOp:
    """A resync subtree replace routed THROUGH the shard queue, so it
    serializes behind every event batch already enqueued for the worker
    (the swap must land after them, before anything buffered during
    it)."""

    __slots__ = ("worker_id", "hashes", "future", "loop")

    def __init__(self, worker_id, hashes, future, loop):
        self.worker_id = worker_id
        self.hashes = hashes
        self.future = future
        self.loop = loop


class KvIndexerSharded(_ConsistencyBase):
    """Worker-sharded index: N independent trees, each owning a subset of
    workers (hash of worker id), each with its OWN event queue drained by
    its own thread — native tree calls release the GIL, so event
    application parallelizes across shards once event rates outgrow one
    pump (reference: KvIndexerSharded — indexer.rs:696).

    Queries fan out to every shard and merge: per-worker scores live in
    exactly one shard, so the merge is a dict union; matched_blocks is the
    max across shards.

    With `snapshot_fn`/`digest_source` wired (KvRouter does), the index
    is self-healing: sequence gaps and digest drift mark a worker's
    subtree stale (scored cold) and trigger a targeted resync — see
    _ConsistencyBase above."""

    def __init__(
        self,
        fabric,
        num_shards: int = 4,
        subject: str = KV_EVENT_SUBJECT,
        snapshot_fn=None,
        digest_source=None,
        anti_entropy_interval: float = 2.0,
    ):
        import queue as _queue
        import threading

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.fabric = fabric
        self.subject = subject
        self.num_shards = num_shards
        self._init_consistency(
            snapshot_fn, digest_source, anti_entropy_interval
        )
        self.trees = [make_radix_tree() for _ in range(num_shards)]
        #: one lock per shard: serializes that shard's apply (drain thread)
        #: against queries (event-loop thread) — the native tree has no
        #: internal locking, and ctypes releases the GIL during calls.
        #: Cross-shard applies still run in parallel, which is the point.
        self._locks = [threading.Lock() for _ in range(num_shards)]
        self._queues = [_queue.SimpleQueue() for _ in range(num_shards)]
        self._busy = [False] * num_shards
        self._applied = [0] * num_shards  # per-shard: no cross-thread +=
        self._threads = [
            threading.Thread(
                target=self._drain, args=(i,), daemon=True,
                name=f"kv-indexer-shard-{i}",
            )
            for i in range(num_shards)
        ]
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._on_event_hooks = []

    @property
    def events_applied(self) -> int:
        return sum(self._applied)

    def _shard_of(self, worker_id: str) -> int:
        import zlib

        return zlib.crc32(worker_id.encode()) % self.num_shards

    async def start(self) -> None:
        for t in self._threads:
            t.start()
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())
        self._start_consistency()

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                for q in self._queues:
                    q.put(None)
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                # hooks observe the raw stream (recorder/metrics taps);
                # the tree only gets what the seq screen admits
                for ev in events:
                    for hook in self._on_event_hooks:
                        hook(worker_id, ev, time.monotonic())
                events = self._screen_events(worker_id, events)
                if events:
                    self._queues[self._shard_of(worker_id)].put(
                        (worker_id, events)
                    )
            except Exception:
                logger.exception("bad kv event message on %s", msg.subject)

    def _drain(self, shard: int) -> None:
        q, tree, lock = self._queues[shard], self.trees[shard], self._locks[shard]
        while True:
            item = q.get()
            if item is None:
                return
            self._busy[shard] = True
            try:
                if isinstance(item, _SwapOp):
                    # guarded like the per-event path below, and the
                    # future ALWAYS resolves: a raise here would kill
                    # this shard's drain thread (index frozen for its
                    # workers) and wedge the awaiting _resync forever
                    drift = 0
                    try:
                        with lock:
                            old = tree.take_worker(item.worker_id)
                            if item.hashes:
                                tree.store_bulk(
                                    item.worker_id, item.hashes
                                )
                        drift = len(set(old) ^ set(item.hashes))
                    except Exception:
                        logger.exception(
                            "shard %d swap failed for %s",
                            shard, item.worker_id,
                        )
                    try:
                        item.loop.call_soon_threadsafe(
                            _resolve_future, item.future, drift
                        )
                    except RuntimeError:
                        pass  # loop closed: nobody is awaiting anymore
                    continue
                worker_id, events = item
                for ev in events:
                    try:
                        if ev.get("kind") == "handed_over":
                            # cross-shard bulk move: src and dst may hash
                            # to different shards, so the move cannot run
                            # under one shard lock — _move locks both in
                            # index order (no ABBA deadlock)
                            self._move(
                                worker_id, str(ev.get("successor") or "")
                            )
                            continue
                        with lock:
                            tree.apply_event(worker_id, ev)
                    except Exception:
                        logger.exception("shard %d apply failed", shard)
                self._applied[shard] += len(events)
            finally:
                self._busy[shard] = False

    def _move(self, src: str, dst: str) -> None:
        s_src = self._shard_of(src)
        s_dst = self._shard_of(dst) if dst else s_src
        if not dst or s_src == s_dst:
            with self._locks[s_src]:
                self.trees[s_src].move_worker(src, dst)
            return
        a, b = sorted((s_src, s_dst))
        with self._locks[a], self._locks[b]:
            hashes = self.trees[s_src].take_worker(src)
            if hashes:
                self.trees[s_dst].store_bulk(dst, hashes)

    def add_event_hook(self, hook) -> None:
        self._on_event_hooks.append(hook)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        for tree, lock in zip(self.trees, self._locks):
            with lock:
                part = tree.find_matches(seq_hashes)
            out.scores.update(part.scores)
            out.matched_blocks = max(out.matched_blocks, part.matched_blocks)
        return self._filter_stale(out)

    def workers(self) -> set:
        out: set = set()
        for tree, lock in zip(self.trees, self._locks):
            with lock:
                out |= tree.workers()
        return out

    def remove_worker(self, worker_id: str) -> int:
        self._forget_worker(worker_id)
        shard = self._shard_of(worker_id)
        with self._locks[shard]:
            return self.trees[shard].remove_worker(worker_id)

    # -- consistency hooks (_ConsistencyBase) ------------------------------

    async def _apply_events(self, worker_id: str, events: list) -> None:
        self._queues[self._shard_of(worker_id)].put((worker_id, events))

    async def _swap_subtree(self, worker_id: str, hashes: list[int]) -> int:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._queues[self._shard_of(worker_id)].put(
            _SwapOp(worker_id, hashes, fut, loop)
        )
        return await fut

    def _digest_of(self, worker_id: str) -> tuple[int, int]:
        shard = self._shard_of(worker_id)
        with self._locks[shard]:
            return self.trees[shard].digest_for(worker_id)

    def move_worker(self, src: str, dst: str) -> None:
        """Bulk ownership move (worker handover), cross-shard safe."""
        self._move(src, dst)

    async def drain_for_tests(self, timeout: float = 2.0) -> None:
        """Wait until every shard queue is empty AND no apply is mid-flight
        (a popped batch is invisible to q.empty())."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(q.empty() for q in self._queues) and not any(self._busy):
                return
            await asyncio.sleep(0.005)

    async def stop(self) -> None:
        self._stop_consistency()
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()
        for q in self._queues:
            q.put(None)


class KvIndexer(_ConsistencyBase):
    """Event-driven index: subscribes `kv_events.>` on the fabric and keeps
    a RadixTree current (reference: KvIndexer — indexer.rs:518, fed from the
    NATS kv_events subject, kv_router.rs:131-152). Gains the same
    gap-detection / anti-entropy / resync machinery as the sharded
    variant when `snapshot_fn`/`digest_source` are wired."""

    def __init__(
        self,
        fabric,
        subject: str = KV_EVENT_SUBJECT,
        snapshot_fn=None,
        digest_source=None,
        anti_entropy_interval: float = 2.0,
    ):
        self.fabric = fabric
        self.subject = subject
        self.tree = make_radix_tree()
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._on_event_hooks = []
        self._init_consistency(
            snapshot_fn, digest_source, anti_entropy_interval
        )

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())
        self._start_consistency()

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                for ev in events:
                    for hook in self._on_event_hooks:
                        hook(worker_id, ev, time.monotonic())
                for ev in self._screen_events(worker_id, events):
                    self.tree.apply_event(worker_id, ev)
            except Exception:
                logger.exception("bad kv event message on %s", msg.subject)

    def add_event_hook(self, hook) -> None:
        """hook(worker_id, event_dict, monotonic_ts) — recorder/metrics tap."""
        self._on_event_hooks.append(hook)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self._filter_stale(self.tree.find_matches(seq_hashes))

    def workers(self) -> set:
        return self.tree.workers()

    def remove_worker(self, worker_id: str) -> int:
        self._forget_worker(worker_id)
        return self.tree.remove_worker(worker_id)

    def move_worker(self, src: str, dst: str) -> int:
        """Bulk ownership move (worker handover)."""
        return self.tree.move_worker(src, dst)

    # -- consistency hooks (_ConsistencyBase) ------------------------------

    async def _apply_events(self, worker_id: str, events: list) -> None:
        for ev in events:
            try:
                self.tree.apply_event(worker_id, ev)
            except Exception:
                logger.exception("apply failed for %s", worker_id)

    async def _swap_subtree(self, worker_id: str, hashes: list[int]) -> int:
        old = self.tree.take_worker(worker_id)
        if hashes:
            self.tree.store_bulk(worker_id, hashes)
        return len(set(old) ^ set(hashes))

    def _digest_of(self, worker_id: str) -> tuple[int, int]:
        return self.tree.digest_for(worker_id)

    async def stop(self) -> None:
        self._stop_consistency()
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()

"""Global KV-block index: (worker × chained block hash) → overlap scores.

Because block identity is a *chained* sequence hash (tokens/blocks.py), the
prefix tree over blocks collapses to a flat map: a sequence hash uniquely
names its entire ancestry, so membership of hash h implies the exact prefix
chain. `find_matches` therefore walks the request's hash chain in order and
scores each worker by its **contiguous** prefix length — only contiguous
blocks are reusable by an engine's prefix cache, so that is the true number
of prefill blocks saved.

Capability parity with the reference's RadixTree indexer
(/root/reference lib/llm/src/kv_router/indexer.rs — RadixTree :239,
apply_event :283, KvIndexer :518, OverlapScores :410), re-designed around
the flat chained-hash map instead of a pointer tree.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import msgpack

from dynamo_tpu.subjects import KV_EVENT_SUBJECT

logger = logging.getLogger(__name__)


@dataclass
class OverlapScores:
    """Per-worker contiguous-prefix overlap, in blocks."""

    scores: dict[str, int] = field(default_factory=dict)
    #: how many leading blocks of the query hit *any* worker
    matched_blocks: int = 0

    def best(self) -> tuple[Optional[str], int]:
        if not self.scores:
            return None, 0
        worker = max(self.scores, key=lambda w: (self.scores[w], w))
        return worker, self.scores[worker]


class RadixTree:
    """Worker-set per chained block hash, with per-worker reverse index for
    O(worker's blocks) removal when a lease expires."""

    def __init__(self):
        self._workers_by_hash: dict[int, set[str]] = {}
        self._hashes_by_worker: dict[str, set[int]] = {}
        self.events_applied = 0

    # -- mutation ----------------------------------------------------------

    def apply_event(self, worker_id: str, event: dict) -> None:
        """Apply one stored/removed/handed_over event (the wire dict form
        emitted by workers — worker.py _publish_loop)."""
        kind = event["kind"]
        hashes = event["block_hashes"]
        if kind == "stored":
            self._store(worker_id, hashes)
        elif kind == "removed":
            self._remove(worker_id, hashes)
        elif kind == "handed_over":
            # bulk ownership move (worker handover): every block this
            # worker held now lives on the successor — reassign in one
            # pass instead of waiting for lease expiry + stored-event
            # propagation, so prefix routing scores the successor the
            # moment the retiring worker announces
            self.move_worker(worker_id, str(event.get("successor") or ""))
        else:
            logger.warning("unknown kv event kind %r", kind)
        self.events_applied += 1

    def _store(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.setdefault(worker_id, set())
        for h in hashes:
            self._workers_by_hash.setdefault(h, set()).add(worker_id)
            mine.add(h)

    def _remove(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.get(worker_id)
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker_id: str) -> int:
        """Drop every block owned by a departed worker."""
        hashes = self._hashes_by_worker.pop(worker_id, set())
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
        return len(hashes)

    def take_worker(self, worker_id: str) -> list[int]:
        """remove_worker that RETURNS the dropped hashes — the sharded
        indexer's cross-shard move is a take on the source shard + a
        bulk store on the destination shard."""
        hashes = self._hashes_by_worker.pop(worker_id, set())
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
        return list(hashes)

    def store_bulk(self, worker_id: str, hashes: Sequence[int]) -> None:
        self._store(worker_id, hashes)

    def move_worker(self, src: str, dst: str) -> int:
        """Bulk ownership move (worker handover): reassign every block of
        `src` to `dst` in one pass. Slightly optimistic — blocks whose
        transfer actually failed are credited to `dst` too — which is
        self-healing: a mis-routed prefix costs one cold prefill, and
        the successor's own stored/removed events correct the set."""
        if not dst or dst == src:
            return self.remove_worker(src)
        hashes = self.take_worker(src)
        if hashes:
            self._store(dst, hashes)
        return len(hashes)

    def clear(self) -> None:
        self._workers_by_hash.clear()
        self._hashes_by_worker.clear()

    # -- query -------------------------------------------------------------

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        active: Optional[set[str]] = None
        for depth, h in enumerate(seq_hashes):
            holders = self._workers_by_hash.get(h)
            if not holders:
                break
            active = set(holders) if active is None else active & holders
            if not active:
                break
            out.matched_blocks = depth + 1
            for w in active:
                out.scores[w] = depth + 1
        return out

    # -- introspection -----------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return len(self._workers_by_hash)

    def num_workers(self) -> int:
        return len(self._hashes_by_worker)

    def workers(self) -> set[str]:
        return set(self._hashes_by_worker)

    def blocks_for(self, worker_id: str) -> int:
        return len(self._hashes_by_worker.get(worker_id, ()))


class NativeRadixTree:
    """Same interface as RadixTree, backed by the C++ index
    (native/dynamo_native.cpp RadixIndex) via ctypes. Worker names are
    interned to u32 ids on the native side; this wrapper mirrors the
    id<->name mapping and the live-worker set."""

    def __init__(self):
        from dynamo_tpu import native

        self._lib = native.lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._ptr = self._lib.dyn_radix_new()
        self._ids: dict[str, int] = {}
        self._names: list[str] = []
        self._live: set[str] = set()
        #: unknown-kind events counted here so events_applied matches the
        #: Python tree (which counts every event, known or not)
        self._unknown_events = 0

    def __del__(self):
        lib, ptr = getattr(self, "_lib", None), getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.dyn_radix_free(ptr)
            self._ptr = None

    def _intern(self, worker_id: str) -> int:
        wid = self._ids.get(worker_id)
        if wid is None:
            wid = self._lib.dyn_radix_intern(self._ptr, worker_id.encode())
            self._ids[worker_id] = wid
            assert wid == len(self._names)
            self._names.append(worker_id)
        return wid

    @staticmethod
    def _hash_buf(hashes: Sequence[int]):
        import numpy as np

        try:
            arr = np.ascontiguousarray(np.asarray(hashes, dtype=np.uint64))
        except (OverflowError, ValueError, TypeError):
            arr = np.asarray([h & (1 << 64) - 1 for h in hashes], np.uint64)
        return arr, arr.ctypes.data, len(arr)

    def apply_event(self, worker_id: str, event: dict) -> None:
        kind = event["kind"]
        hashes = event["block_hashes"]  # KeyError parity with RadixTree
        if kind == "handed_over":
            self.move_worker(worker_id, str(event.get("successor") or ""))
            self._unknown_events += 1  # events_applied parity (native
            # move counts no apply)
            return
        if kind not in ("stored", "removed"):
            logger.warning("unknown kv event kind %r", kind)
            self._unknown_events += 1
            return
        arr, buf, n = self._hash_buf(hashes)
        self._lib.dyn_radix_apply(
            self._ptr, self._intern(worker_id), 0 if kind == "stored" else 1,
            buf, n,
        )
        if kind == "stored":
            self._live.add(worker_id)

    def remove_worker(self, worker_id: str) -> int:
        self._live.discard(worker_id)
        wid = self._ids.get(worker_id)
        if wid is None:
            return 0
        return self._lib.dyn_radix_remove_worker(self._ptr, wid)

    def take_worker(self, worker_id: str) -> list[int]:
        """The native index cannot enumerate a worker's hashes — the
        take degrades to a remove and returns nothing; the successor's
        own stored events repopulate its score within one metrics
        interval (documented honest degradation of the bulk move)."""
        self.remove_worker(worker_id)
        return []

    def store_bulk(self, worker_id: str, hashes) -> None:
        if not hashes:
            return
        arr, buf, n = self._hash_buf(list(hashes))
        self._lib.dyn_radix_apply(self._ptr, self._intern(worker_id), 0, buf, n)
        self._live.add(worker_id)

    def move_worker(self, src: str, dst: str) -> int:
        return self.remove_worker(src)

    def clear(self) -> None:
        self._lib.dyn_radix_clear(self._ptr)
        self._live.clear()

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        import ctypes

        import numpy as np

        out = OverlapScores()
        if not seq_hashes:
            return out
        arr, buf, n = self._hash_buf(seq_hashes)
        cap = max(1, len(self._names))
        ids = np.empty(cap, np.uint32)
        scores = np.empty(cap, np.uint32)
        matched = ctypes.c_size_t(0)
        k = self._lib.dyn_radix_find(
            self._ptr, buf, n, ids.ctypes.data, scores.ctypes.data, cap,
            ctypes.byref(matched),
        )
        out.matched_blocks = int(matched.value)
        for i in range(k):
            out.scores[self._names[ids[i]]] = int(scores[i])
        return out

    # -- introspection (parity with RadixTree) ------------------------------

    @property
    def events_applied(self) -> int:
        return self._lib.dyn_radix_events_applied(self._ptr) + self._unknown_events

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._ptr)

    def num_workers(self) -> int:
        return len(self._live)

    def workers(self) -> set[str]:
        return set(self._live)

    def blocks_for(self, worker_id: str) -> int:
        wid = self._ids.get(worker_id)
        if wid is None:
            return 0
        return self._lib.dyn_radix_blocks_for(self._ptr, wid)


def make_radix_tree():
    """Native-backed tree when libdynamo_native is available, else Python."""
    from dynamo_tpu import native

    if native.lib() is not None:
        return NativeRadixTree()
    return RadixTree()


class KvIndexerSharded:
    """Worker-sharded index: N independent trees, each owning a subset of
    workers (hash of worker id), each with its OWN event queue drained by
    its own thread — native tree calls release the GIL, so event
    application parallelizes across shards once event rates outgrow one
    pump (reference: KvIndexerSharded — indexer.rs:696).

    Queries fan out to every shard and merge: per-worker scores live in
    exactly one shard, so the merge is a dict union; matched_blocks is the
    max across shards."""

    def __init__(self, fabric, num_shards: int = 4, subject: str = KV_EVENT_SUBJECT):
        import queue as _queue
        import threading

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.fabric = fabric
        self.subject = subject
        self.num_shards = num_shards
        self.trees = [make_radix_tree() for _ in range(num_shards)]
        #: one lock per shard: serializes that shard's apply (drain thread)
        #: against queries (event-loop thread) — the native tree has no
        #: internal locking, and ctypes releases the GIL during calls.
        #: Cross-shard applies still run in parallel, which is the point.
        self._locks = [threading.Lock() for _ in range(num_shards)]
        self._queues = [_queue.SimpleQueue() for _ in range(num_shards)]
        self._busy = [False] * num_shards
        self._applied = [0] * num_shards  # per-shard: no cross-thread +=
        self._threads = [
            threading.Thread(
                target=self._drain, args=(i,), daemon=True,
                name=f"kv-indexer-shard-{i}",
            )
            for i in range(num_shards)
        ]
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._on_event_hooks = []

    @property
    def events_applied(self) -> int:
        return sum(self._applied)

    def _shard_of(self, worker_id: str) -> int:
        import zlib

        return zlib.crc32(worker_id.encode()) % self.num_shards

    async def start(self) -> None:
        for t in self._threads:
            t.start()
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                for q in self._queues:
                    q.put(None)
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                self._queues[self._shard_of(worker_id)].put(
                    (worker_id, events)
                )
                for ev in events:
                    for hook in self._on_event_hooks:
                        hook(worker_id, ev, time.monotonic())
            except Exception:
                logger.exception("bad kv event message on %s", msg.subject)

    def _drain(self, shard: int) -> None:
        q, tree, lock = self._queues[shard], self.trees[shard], self._locks[shard]
        while True:
            item = q.get()
            if item is None:
                return
            self._busy[shard] = True
            try:
                worker_id, events = item
                for ev in events:
                    try:
                        if ev.get("kind") == "handed_over":
                            # cross-shard bulk move: src and dst may hash
                            # to different shards, so the move cannot run
                            # under one shard lock — _move locks both in
                            # index order (no ABBA deadlock)
                            self._move(
                                worker_id, str(ev.get("successor") or "")
                            )
                            continue
                        with lock:
                            tree.apply_event(worker_id, ev)
                    except Exception:
                        logger.exception("shard %d apply failed", shard)
                self._applied[shard] += len(events)
            finally:
                self._busy[shard] = False

    def _move(self, src: str, dst: str) -> None:
        s_src = self._shard_of(src)
        s_dst = self._shard_of(dst) if dst else s_src
        if not dst or s_src == s_dst:
            with self._locks[s_src]:
                self.trees[s_src].move_worker(src, dst)
            return
        a, b = sorted((s_src, s_dst))
        with self._locks[a], self._locks[b]:
            hashes = self.trees[s_src].take_worker(src)
            if hashes:
                self.trees[s_dst].store_bulk(dst, hashes)

    def add_event_hook(self, hook) -> None:
        self._on_event_hooks.append(hook)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        for tree, lock in zip(self.trees, self._locks):
            with lock:
                part = tree.find_matches(seq_hashes)
            out.scores.update(part.scores)
            out.matched_blocks = max(out.matched_blocks, part.matched_blocks)
        return out

    def workers(self) -> set:
        out: set = set()
        for tree, lock in zip(self.trees, self._locks):
            with lock:
                out |= tree.workers()
        return out

    def remove_worker(self, worker_id: str) -> int:
        shard = self._shard_of(worker_id)
        with self._locks[shard]:
            return self.trees[shard].remove_worker(worker_id)

    def move_worker(self, src: str, dst: str) -> None:
        """Bulk ownership move (worker handover), cross-shard safe."""
        self._move(src, dst)

    async def drain_for_tests(self, timeout: float = 2.0) -> None:
        """Wait until every shard queue is empty AND no apply is mid-flight
        (a popped batch is invisible to q.empty())."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(q.empty() for q in self._queues) and not any(self._busy):
                return
            await asyncio.sleep(0.005)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()
        for q in self._queues:
            q.put(None)


class KvIndexer:
    """Event-driven index: subscribes `kv_events.>` on the fabric and keeps
    a RadixTree current (reference: KvIndexer — indexer.rs:518, fed from the
    NATS kv_events subject, kv_router.rs:131-152)."""

    def __init__(self, fabric, subject: str = KV_EVENT_SUBJECT):
        self.fabric = fabric
        self.subject = subject
        self.tree = make_radix_tree()
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._on_event_hooks = []

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                for ev in events:
                    self.tree.apply_event(worker_id, ev)
                    for hook in self._on_event_hooks:
                        hook(worker_id, ev, time.monotonic())
            except Exception:
                logger.exception("bad kv event message on %s", msg.subject)

    def add_event_hook(self, hook) -> None:
        """hook(worker_id, event_dict, monotonic_ts) — recorder/metrics tap."""
        self._on_event_hooks.append(hook)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self.tree.find_matches(seq_hashes)

    def workers(self) -> set:
        return self.tree.workers()

    def remove_worker(self, worker_id: str) -> int:
        return self.tree.remove_worker(worker_id)

    def move_worker(self, src: str, dst: str) -> int:
        """Bulk ownership move (worker handover)."""
        return self.tree.move_worker(src, dst)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()

"""Global KV-block index: (worker × chained block hash) → overlap scores.

Because block identity is a *chained* sequence hash (tokens/blocks.py), the
prefix tree over blocks collapses to a flat map: a sequence hash uniquely
names its entire ancestry, so membership of hash h implies the exact prefix
chain. `find_matches` therefore walks the request's hash chain in order and
scores each worker by its **contiguous** prefix length — only contiguous
blocks are reusable by an engine's prefix cache, so that is the true number
of prefill blocks saved.

Capability parity with the reference's RadixTree indexer
(/root/reference lib/llm/src/kv_router/indexer.rs — RadixTree :239,
apply_event :283, KvIndexer :518, OverlapScores :410), re-designed around
the flat chained-hash map instead of a pointer tree.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import msgpack

from dynamo_tpu.subjects import KV_EVENT_SUBJECT

logger = logging.getLogger(__name__)


@dataclass
class OverlapScores:
    """Per-worker contiguous-prefix overlap, in blocks."""

    scores: dict[str, int] = field(default_factory=dict)
    #: how many leading blocks of the query hit *any* worker
    matched_blocks: int = 0

    def best(self) -> tuple[Optional[str], int]:
        if not self.scores:
            return None, 0
        worker = max(self.scores, key=lambda w: (self.scores[w], w))
        return worker, self.scores[worker]


class RadixTree:
    """Worker-set per chained block hash, with per-worker reverse index for
    O(worker's blocks) removal when a lease expires."""

    def __init__(self):
        self._workers_by_hash: dict[int, set[str]] = {}
        self._hashes_by_worker: dict[str, set[int]] = {}
        self.events_applied = 0

    # -- mutation ----------------------------------------------------------

    def apply_event(self, worker_id: str, event: dict) -> None:
        """Apply one stored/removed event (the wire dict form emitted by
        workers — worker.py _publish_loop)."""
        kind = event["kind"]
        hashes = event["block_hashes"]
        if kind == "stored":
            self._store(worker_id, hashes)
        elif kind == "removed":
            self._remove(worker_id, hashes)
        else:
            logger.warning("unknown kv event kind %r", kind)
        self.events_applied += 1

    def _store(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.setdefault(worker_id, set())
        for h in hashes:
            self._workers_by_hash.setdefault(h, set()).add(worker_id)
            mine.add(h)

    def _remove(self, worker_id: str, hashes: Sequence[int]) -> None:
        mine = self._hashes_by_worker.get(worker_id)
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker_id: str) -> int:
        """Drop every block owned by a departed worker."""
        hashes = self._hashes_by_worker.pop(worker_id, set())
        for h in hashes:
            workers = self._workers_by_hash.get(h)
            if workers is not None:
                workers.discard(worker_id)
                if not workers:
                    del self._workers_by_hash[h]
        return len(hashes)

    def clear(self) -> None:
        self._workers_by_hash.clear()
        self._hashes_by_worker.clear()

    # -- query -------------------------------------------------------------

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        active: Optional[set[str]] = None
        for depth, h in enumerate(seq_hashes):
            holders = self._workers_by_hash.get(h)
            if not holders:
                break
            active = set(holders) if active is None else active & holders
            if not active:
                break
            out.matched_blocks = depth + 1
            for w in active:
                out.scores[w] = depth + 1
        return out

    # -- introspection -----------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return len(self._workers_by_hash)

    def num_workers(self) -> int:
        return len(self._hashes_by_worker)

    def workers(self) -> set[str]:
        return set(self._hashes_by_worker)

    def blocks_for(self, worker_id: str) -> int:
        return len(self._hashes_by_worker.get(worker_id, ()))


class KvIndexer:
    """Event-driven index: subscribes `kv_events.>` on the fabric and keeps
    a RadixTree current (reference: KvIndexer — indexer.rs:518, fed from the
    NATS kv_events subject, kv_router.rs:131-152)."""

    def __init__(self, fabric, subject: str = KV_EVENT_SUBJECT):
        self.fabric = fabric
        self.subject = subject
        self.tree = RadixTree()
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self._on_event_hooks = []

    async def start(self) -> None:
        self._sub = await self.fabric.subscribe(self.subject + ".>")
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            msg = await self._sub.next()
            if msg is None:
                return
            try:
                worker_id = msg.header["instance_id"]
                events = msgpack.unpackb(msg.payload, raw=False)
                for ev in events:
                    self.tree.apply_event(worker_id, ev)
                    for hook in self._on_event_hooks:
                        hook(worker_id, ev, time.monotonic())
            except Exception:
                logger.exception("bad kv event message on %s", msg.subject)

    def add_event_hook(self, hook) -> None:
        """hook(worker_id, event_dict, monotonic_ts) — recorder/metrics tap."""
        self._on_event_hooks.append(hook)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self.tree.find_matches(seq_hashes)

    def remove_worker(self, worker_id: str) -> int:
        return self.tree.remove_worker(worker_id)

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._task is not None:
            self._task.cancel()

"""Standalone KV-router service: routing-as-a-service over the fabric.

Reference parity: components/router (main.rs:36-40 —
`Ingress::for_engine(KvRouter)`): a dedicated process that maintains the
global KV prefix index + worker load state and answers placement queries,
so many thin frontends can share one router's view instead of each
building its own.

Endpoints served (namespace/router/...):
  choose   {token_ids, request_id?} -> {instance_id, matched_blocks}
  feedback {request_id, tokens?|complete} — in-flight bookkeeping
  state    {} -> router state snapshot (workers, load, index size)
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.kv_router.kv_router import KvRouter, KvRouterConfig
from dynamo_tpu.runtime import DistributedRuntime, IngressServer

logger = logging.getLogger(__name__)


class RouterService:
    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str = "dynamo",
        component: str = "backend",
        endpoint: str = "generate",
        block_size: int = 64,
        salt: str = "",
        config: Optional[KvRouterConfig] = None,
        advertise_host: str = "127.0.0.1",
        indexer_shards: int = 1,
    ):
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.block_size = block_size
        self.salt = salt
        self.config = config
        self.indexer_shards = indexer_shards
        self.advertise_host = advertise_host
        self.router: Optional[KvRouter] = None
        bind = (
            "127.0.0.1"
            if advertise_host in ("127.0.0.1", "localhost")
            else "0.0.0.0"
        )
        self.ingress = IngressServer(host=bind)
        self.registration = None
        self.instance_id = ""

    async def start(self) -> None:
        ep = (
            self.runtime.namespace(self.namespace)
            .component(self.component)
            .endpoint(self.endpoint)
        )
        src = await ep.instance_source()
        self.router = KvRouter(
            self.runtime.fabric,
            self.component,
            src,
            block_size=self.block_size,
            salt=self.salt,
            config=self.config,
            indexer_shards=self.indexer_shards,
        )
        await self.router.start()
        self.ingress.add_handler("choose", self._choose)
        self.ingress.add_handler("feedback", self._feedback)
        self.ingress.add_handler("state", self._state)
        await self.ingress.start()
        reg_ep = (
            self.runtime.namespace(self.namespace)
            .component("router")
            .endpoint("choose")
        )
        self.registration = await reg_ep.register(
            self.advertise_host, self.ingress.port,
            metadata={"routes": self.component},
        )
        self.instance_id = self.registration.instance.instance_id
        logger.info(
            "router service %s up for %s/%s on :%d",
            self.instance_id, self.namespace, self.component,
            self.ingress.port,
        )

    async def stop(self) -> None:
        await self.ingress.stop()
        if self.router is not None:
            await self.router.stop()

    # -- handlers ----------------------------------------------------------

    async def _choose(self, ctx, request: dict):
        choice, matched = await self.router.find_best_match(
            request.get("token_ids", ()),
            request_id=request.get("request_id"),
        )
        yield {"instance_id": choice, "matched_blocks": matched}

    async def _feedback(self, ctx, request: dict):
        rid = request.get("request_id", "")
        if request.get("complete"):
            self.router.on_complete(rid)
        else:
            self.router.on_tokens(rid, int(request.get("tokens", 0)))
        yield {"ok": True}

    async def _state(self, ctx, request):
        active = self.router.active
        yield {
            "workers": [i.instance_id for i in self.router.source.list()],
            "load": self.router.metrics.snapshot(),
            "active_blocks": {
                w: active.active_blocks(w) for w in active.workers()
            },
        }


async def run_router(args) -> None:
    if not args.salt:
        # The salt MUST match the workers' content-addressing salt (the
        # model name — engine hashes with salt=config.model). A mismatch
        # doesn't error; it silently zeroes every prefix match.
        raise SystemExit(
            "router: --salt is required and must be the served model name "
            "(workers hash KV blocks with salt=<model>)"
        )
    rt = await DistributedRuntime.create(args.fabric)
    svc = RouterService(
        rt,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint,
        block_size=args.block_size,
        salt=args.salt,
        advertise_host=args.host,
        indexer_shards=getattr(args, "shards", 1),
    )
    await svc.start()
    print(f"router {svc.instance_id} up", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await svc.stop()
        await rt.close()

"""Subprocess engine lifecycle: spawn, handshake, heartbeat, restarts.

The supervisor owns exactly one child process at a time and the policy
around it (the role circus/the arbiter plays for the reference's local
serving, sdk cli/serving.py, plus the per-engine drain handlers in its
subprocess shims):

- spawn + `hello`/`ready` handshake with a timeout — a child that never
  says hello (or says it in the wrong protocol version) is killed and
  counted as a failed start;
- heartbeat: pings on an interval; a child that goes silent past the
  timeout is killed (the restart path takes it from there);
- restart with exponential backoff and a max-consecutive-failures
  circuit breaker — a crash-looping engine ends in state "broken"
  instead of burning CPU forever. A child that stays ready for
  `stable_after` seconds resets the failure streak, so a once-a-day
  crash never trips the breaker;
- graceful drain on stop(): `shutdown` frame, a grace period, then
  SIGTERM/SIGKILL;
- stderr capture: every child stderr line lands in this process's
  logging plane (JSONL-ready via logging_config) under the child's
  name, so foreign-engine tracebacks are never lost to the void.

The supervisor knows frames only as (header, payload) — routing them to
request streams is the client's job (client.py) via `on_frame`; process
death is reported via `on_down` so in-flight requests get error
finishes instead of dropped streams.
"""

from __future__ import annotations

import asyncio
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from dynamo_tpu.external import protocol

logger = logging.getLogger(__name__)


@dataclass
class SupervisorConfig:
    #: seconds the child has to complete the hello/ready handshake
    ready_timeout: float = 30.0
    #: ping cadence; 0 disables heartbeating
    heartbeat_interval: float = 2.0
    #: no frame of ANY kind for this long after readiness => kill+restart
    heartbeat_timeout: float = 15.0
    backoff_initial: float = 0.2
    backoff_max: float = 5.0
    backoff_factor: float = 2.0
    #: consecutive failed starts/crashes before the circuit opens
    max_restarts: int = 5
    #: a child ready this long resets the consecutive-failure streak
    stable_after: float = 10.0
    #: graceful-stop grace period after the shutdown frame
    drain_timeout: float = 5.0
    #: "stdio" (frames on the child's stdin/stdout) or "uds" (frames on a
    #: unix socket named in $DYNAMO_EXT_UDS; the child's stdout joins
    #: stderr in the log plane)
    transport: str = "stdio"
    env: dict = field(default_factory=dict)


class EngineSupervisor:
    """One supervised subprocess speaking external/protocol.py."""

    def __init__(
        self,
        cmd: list[str],
        name: str = "ext",
        config: Optional[SupervisorConfig] = None,
        on_frame: Optional[Callable[[Any, bytes], None]] = None,
        on_down: Optional[Callable[[str], None]] = None,
    ):
        if not cmd:
            raise ValueError("empty external engine command")
        self.cmd = list(cmd)
        self.name = name
        self.config = config or SupervisorConfig()
        if self.config.transport not in ("stdio", "uds"):
            raise ValueError(f"unknown transport {self.config.transport!r}")
        #: (header, payload) for every post-handshake child frame
        self.on_frame = on_frame
        #: called with a reason string each time the child dies/restarts
        self.on_down = on_down
        self.hello: Optional[dict] = None
        self.state = "idle"  # starting | ready | backoff | broken | stopped
        self.spawns_total = 0
        self.restarts_total = 0
        self.consecutive_failures = 0
        self.last_exit: Optional[int] = None
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._ready = asyncio.Event()
        self._broken = asyncio.Event()  # terminal: circuit open / version skew
        self._stopping = False
        self._run_task: Optional[asyncio.Task] = None
        self._side_tasks: list[asyncio.Task] = []
        self._log_tasks: list[asyncio.Task] = []
        self._send_lock = asyncio.Lock()
        self._last_rx = 0.0
        self._uds_dir: Optional[tempfile.TemporaryDirectory] = None
        self._uds_server: Optional[asyncio.AbstractServer] = None
        self._uds_accepted: Optional[asyncio.Future] = None

    # -- public api --------------------------------------------------------

    async def start(self) -> None:
        self._stopping = False
        self._run_task = asyncio.get_running_loop().create_task(
            self._run(), name=f"supervise-{self.name}"
        )

    async def wait_ready(self, timeout: float) -> bool:
        """True once ready; False on timeout OR as soon as the engine is
        permanently down (circuit open / version mismatch) — waiters must
        not sit out the full timeout for an engine that will never come."""
        r = asyncio.ensure_future(self._ready.wait())
        b = asyncio.ensure_future(self._broken.wait())
        try:
            await asyncio.wait(
                {r, b}, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED,
            )
        finally:
            r.cancel()
            b.cancel()
        return self._ready.is_set()

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    async def send(self, header: Any, payload: bytes = b"") -> None:
        """Write one frame to the child. Raises ConnectionError if the
        child is not up — callers decide whether that's retryable."""
        writer = self._writer
        if writer is None or writer.is_closing():
            raise ConnectionError(f"engine {self.name!r} is not connected")
        async with self._send_lock:
            writer.write(protocol.encode_frame(header, payload))
            await writer.drain()

    async def stop(self) -> None:
        """Graceful drain: shutdown frame, grace period, then escalate."""
        self._stopping = True
        self.state = "stopped"
        proc = self.proc
        if proc is not None and proc.returncode is None:
            try:
                await self.send({"type": "shutdown"})
            except Exception:
                pass
            try:
                await asyncio.wait_for(proc.wait(), self.config.drain_timeout)
            except asyncio.TimeoutError:
                logger.warning(
                    "engine %s did not drain in %.1fs; terminating",
                    self.name, self.config.drain_timeout,
                )
                self._terminate(proc)
                try:
                    await asyncio.wait_for(proc.wait(), 3.0)
                except asyncio.TimeoutError:
                    proc.kill()
                    await proc.wait()
        if self._run_task is not None:
            self._run_task.cancel()
            try:
                await self._run_task
            except (asyncio.CancelledError, Exception):
                pass
        self._cancel_side_tasks()
        if self._log_tasks:
            await asyncio.gather(*self._log_tasks, return_exceptions=True)
        self._close_uds()
        self._ready.clear()

    def kill(self) -> None:
        """Hard-kill the current child (tests / heartbeat): the run loop
        observes the death and applies restart policy."""
        proc = self.proc
        if proc is not None and proc.returncode is None:
            proc.kill()

    def metrics(self) -> dict:
        return {
            "ext_spawns_total": self.spawns_total,
            "ext_restarts_total": self.restarts_total,
            "ext_consecutive_failures": self.consecutive_failures,
            "ext_ready": int(self.ready),
            "ext_broken": int(self.state == "broken"),
        }

    # -- run loop ----------------------------------------------------------

    async def _run(self) -> None:
        cfg = self.config
        loop = asyncio.get_running_loop()
        while not self._stopping:
            if self.consecutive_failures > cfg.max_restarts:
                self.state = "broken"
                self._broken.set()
                logger.error(
                    "engine %s circuit open after %d consecutive failures",
                    self.name, self.consecutive_failures - 1,
                )
                if self.on_down:
                    self.on_down("circuit open")
                return
            if self.consecutive_failures:
                self.state = "backoff"
                delay = min(
                    cfg.backoff_initial
                    * cfg.backoff_factor ** (self.consecutive_failures - 1),
                    cfg.backoff_max,
                )
                logger.info(
                    "engine %s restart %d in %.2fs",
                    self.name, self.consecutive_failures, delay,
                )
                await asyncio.sleep(delay)
            self.state = "starting"
            ready_at: Optional[float] = None
            try:
                await self._spawn()
                await self._handshake()
                ready_at = loop.time()
                self._last_rx = ready_at
                self.state = "ready"
                self._ready.set()
                if self.spawns_total > 1:
                    self.restarts_total += 1
                self._start_side_task(self._heartbeat())
                await self._pump()
                reason = "wire closed"
            except protocol.VersionMismatch as e:
                # a wrong-version engine will NEVER become right by
                # restarting — refuse permanently
                logger.error("engine %s refused at handshake: %s", self.name, e)
                await self._reap()
                self.state = "broken"
                self._broken.set()
                if self.on_down:
                    self.on_down(str(e))
                return
            except asyncio.CancelledError:
                raise
            except Exception as e:
                reason = f"{type(e).__name__}: {e}"
            finally:
                self._ready.clear()
                self._cancel_side_tasks()
            await self._reap()
            if reason == "wire closed":
                reason = f"exited with {self.last_exit}"
            if self._stopping:
                return
            stable = (
                ready_at is not None
                and loop.time() - ready_at >= cfg.stable_after
            )
            self.consecutive_failures = 1 if stable else (
                self.consecutive_failures + 1
            )
            logger.warning("engine %s down: %s", self.name, reason)
            if self.on_down:
                self.on_down(reason)

    async def _spawn(self) -> None:
        cfg = self.config
        env = dict(os.environ, **cfg.env)
        stdout = asyncio.subprocess.PIPE
        if cfg.transport == "uds":
            self._open_uds()
            env[protocol.UDS_ENV] = self._uds_path
        self.spawns_total += 1
        self.proc = await asyncio.create_subprocess_exec(
            *self.cmd,
            stdin=asyncio.subprocess.PIPE,
            stdout=stdout,
            stderr=asyncio.subprocess.PIPE,
            env=env,
        )
        self._start_log_task(self._pump_logs(self.proc.stderr, "stderr"))
        if cfg.transport == "stdio":
            self._reader = self.proc.stdout
            self._writer = self.proc.stdin
        else:
            # stdout is plain output in uds mode — log it like stderr
            self._start_log_task(self._pump_logs(self.proc.stdout, "stdout"))
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.shield(self._uds_accepted), cfg.ready_timeout
                )
            except asyncio.TimeoutError:
                raise TimeoutError(
                    f"engine {self.name!r} never connected to the unix "
                    f"socket within {cfg.ready_timeout}s"
                )
            self._reader, self._writer = reader, writer

    async def _handshake(self) -> None:
        try:
            header, _ = await asyncio.wait_for(
                protocol.read_frame(self._reader), self.config.ready_timeout
            )
        except asyncio.TimeoutError:
            raise TimeoutError(
                f"engine {self.name!r} sent no hello within "
                f"{self.config.ready_timeout}s"
            )
        except asyncio.IncompleteReadError:
            raise ConnectionError(
                f"engine {self.name!r} closed the wire before hello"
            )
        self.hello = protocol.check_hello(header)
        await self.send(protocol.ready_frame())
        logger.info(
            "engine %s ready: model=%s capabilities=%s",
            self.name, self.hello.get("model"),
            self.hello.get("capabilities"),
        )

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                header, payload = await protocol.read_frame(self._reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            except protocol.CodecError as e:
                # a corrupt frame means the stream is unrecoverable (we
                # cannot re-synchronize a length-prefixed wire) — kill and
                # let restart policy take over
                logger.error(
                    "engine %s wire corrupted (%s); killing", self.name, e
                )
                self.kill()
                return
            self._last_rx = loop.time()
            if self.on_frame is not None:
                try:
                    self.on_frame(header, payload)
                except Exception:
                    logger.exception(
                        "frame handler failed for %s frame",
                        header.get("type") if isinstance(header, dict)
                        else type(header),
                    )

    async def _heartbeat(self) -> None:
        cfg = self.config
        if cfg.heartbeat_interval <= 0:
            return
        loop = asyncio.get_running_loop()
        #: send time of the oldest PING no frame has arrived after — the
        #: liveness question is "did the child answer our ping", never
        #: "how long since the last frame": the latter misfires when the
        #: PARENT loop stalls (a blocking import/compile elsewhere in the
        #: serving process) and reads a healthy child's frames late.
        outstanding: Optional[float] = None
        n = 0
        while True:
            t0 = loop.time()
            await asyncio.sleep(cfg.heartbeat_interval)
            now = loop.time()
            if now - t0 > cfg.heartbeat_interval * 2:
                # parent stall: answered frames may still sit unread in
                # the pump's backlog — drop the outstanding ping and
                # re-probe instead of blaming the child
                outstanding = None
                continue
            if outstanding is not None and self._last_rx >= outstanding:
                outstanding = None  # answered (any frame counts)
            if (
                outstanding is not None
                and now - outstanding > cfg.heartbeat_timeout
            ):
                logger.warning(
                    "engine %s unresponsive %.1fs after ping; killing "
                    "for restart", self.name, now - outstanding,
                )
                self.kill()
                return
            if outstanding is None:
                outstanding = loop.time()
                n += 1
                try:
                    await self.send({"type": "ping", "n": n})
                except Exception:
                    return  # writer gone; the pump/run loop handles it

    async def _pump_logs(self, stream, channel: str) -> None:
        """Child stderr/stdout lines -> this process's logging plane."""
        if stream is None:
            return
        log = logging.getLogger(f"external.{self.name}")
        while True:
            try:
                line = await stream.readline()
            except (ValueError, ConnectionError):
                return  # line longer than the stream limit / pipe gone
            if not line:
                return
            log.info(
                "%s", line.decode(errors="replace").rstrip(),
                extra={"child": self.name, "channel": channel},
            )

    # -- helpers -----------------------------------------------------------

    def _start_side_task(self, coro: Awaitable) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self._side_tasks.append(t)
        t.add_done_callback(
            lambda t: self._side_tasks.remove(t)
            if t in self._side_tasks else None
        )

    def _start_log_task(self, coro: Awaitable) -> None:
        # log pumps are NOT cancelled with the side tasks: they must be
        # left to drain the child's final stderr lines (the crash
        # traceback) after death; they end on pipe EOF
        t = asyncio.get_running_loop().create_task(coro)
        self._log_tasks.append(t)
        t.add_done_callback(
            lambda t: self._log_tasks.remove(t)
            if t in self._log_tasks else None
        )

    def _cancel_side_tasks(self) -> None:
        for t in list(self._side_tasks):
            t.cancel()

    def _terminate(self, proc) -> None:
        try:
            proc.terminate()
        except ProcessLookupError:
            pass

    async def _reap(self) -> None:
        proc = self.proc
        if proc is None:
            return
        if proc.returncode is None:
            self._terminate(proc)
            try:
                await asyncio.wait_for(proc.wait(), 3.0)
            except asyncio.TimeoutError:
                proc.kill()
                await proc.wait()
        self.last_exit = proc.returncode
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None
        self._reader = None

    def _open_uds(self) -> None:
        self._close_uds()
        self._uds_dir = tempfile.TemporaryDirectory(prefix="dyn-ext-")
        self._uds_path = os.path.join(self._uds_dir.name, "engine.sock")
        self._uds_accepted = asyncio.get_running_loop().create_future()

        async def _serve():
            def on_conn(reader, writer):
                if not self._uds_accepted.done():
                    self._uds_accepted.set_result((reader, writer))
                else:
                    writer.close()

            self._uds_server = await asyncio.start_unix_server(
                on_conn, self._uds_path
            )

        self._start_side_task(_serve())

    def _close_uds(self) -> None:
        if self._uds_server is not None:
            self._uds_server.close()
            self._uds_server = None
        if self._uds_dir is not None:
            self._uds_dir.cleanup()
            self._uds_dir = None

"""Self-contained reference engine for the subprocess harness.

Torch-free and deterministic, so tier-1 CPU tests (and bench.py's wire
overhead A/B) can spawn a REAL foreign process without model weights:
the "sampler" emits the prompt tokens cyclically (EchoCore semantics,
engines.rs) while honoring max_tokens, stop ids, ignore_eos, and
cancellation, and it emits real KV stored-events (chained block hashes
over the prompt, tokens/blocks.py) so KV-aware routers prefix-route to
it exactly as to a native worker.

Run under a supervisor:

  dynamo-tpu run in=http 'out=ext:python -m dynamo_tpu.external.reference_worker'

Fault-injection knobs for the FT suite:
  --delay S        seconds per emitted token (mid-stream kill windows)
  --fail-after N   hard-exit (os._exit 13) after N tokens total
  --hello-version V  claim protocol version V in hello (handshake tests)
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from dynamo_tpu.engine.page_table import KvEvent
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.tokens.blocks import TokenBlockSequence


class ReferenceEngine:
    """Deterministic echo engine with KV stored-events and fault knobs."""

    def __init__(
        self,
        block_size: int = 16,
        salt: str = "",
        delay: float = 0.0,
        fail_after: int = 0,
    ):
        self.block_size = block_size
        self.salt = salt
        self.delay = delay
        self.fail_after = fail_after
        self.on_kv_event = None  # set by the shim / Worker
        self.requests_received = 0
        self.active = 0
        self.tokens_emitted = 0

    def metrics_dict(self) -> dict:
        return {
            "num_waiting": 0,
            "num_running": self.active,
            "requests_received": self.requests_received,
            "generated_tokens": self.tokens_emitted,
        }

    def _emit_stored(self, token_ids) -> None:
        if self.on_kv_event is None:
            return
        blocks = TokenBlockSequence(
            tuple(int(t) for t in token_ids),
            block_size=self.block_size, salt=self.salt,
        ).blocks
        if not blocks:
            return
        self.on_kv_event(
            KvEvent(
                kind="stored",
                block_hashes=tuple(b.sequence_hash for b in blocks),
                parent_hash=None,
                token_blocks=tuple(tuple(b.tokens) for b in blocks),
            )
        )

    async def generate(self, context, request: PreprocessedRequest):
        self.requests_received += 1
        self.active += 1
        try:
            prompt = list(request.token_ids) or [0]
            # stored-events go out BEFORE decoding so routers can already
            # prefix-match this worker while the stream runs
            self._emit_stored(prompt)
            stop_ids = (
                set() if request.ignore_eos else set(request.stop_token_ids)
            )
            for i in range(request.max_tokens):
                if context.cancelled:
                    return
                if self.delay:
                    await asyncio.sleep(self.delay)
                tok = prompt[i % len(prompt)]
                self.tokens_emitted += 1
                if self.fail_after and self.tokens_emitted >= self.fail_after:
                    import os

                    sys.stderr.write("reference_worker: injected crash\n")
                    sys.stderr.flush()
                    os._exit(13)
                if tok in stop_ids:
                    yield {"token_ids": [tok], "finish_reason": "stop"}
                    return
                yield {
                    "token_ids": [tok],
                    "finish_reason": (
                        "length" if i == request.max_tokens - 1 else None
                    ),
                }
        finally:
            self.active -= 1

    async def embed(self, prompts, normalize: bool = True):
        from dynamo_tpu.engine.async_engine import fake_embedding

        import numpy as np

        return np.stack([fake_embedding(p) for p in prompts])


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="ext-reference")
    p.add_argument("--block-size", type=int, default=16, dest="block_size")
    p.add_argument("--salt", default=None,
                   help="KV block hash salt (default: the model name)")
    p.add_argument("--delay", type=float, default=0.0,
                   help="seconds per emitted token")
    p.add_argument("--fail-after", type=int, default=0, dest="fail_after",
                   help="hard-exit after N tokens total (fault injection)")
    p.add_argument("--hello-version", type=int, default=None,
                   dest="hello_version",
                   help="claim this protocol version (handshake tests)")
    p.add_argument("--metrics-interval", type=float, default=0.5,
                   dest="metrics_interval")
    args = p.parse_args(argv)

    if args.hello_version is not None:
        from dynamo_tpu.external import protocol

        protocol.PROTOCOL_VERSION = args.hello_version

    from dynamo_tpu.external.shim import run_engine

    engine = ReferenceEngine(
        block_size=args.block_size,
        salt=args.salt if args.salt is not None else args.model,
        delay=args.delay,
        fail_after=args.fail_after,
    )
    run_engine(
        engine, model=args.model, metrics_interval=args.metrics_interval
    )


if __name__ == "__main__":
    main()

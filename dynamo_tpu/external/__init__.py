"""Subprocess external-engine harness.

Runs ANY engine as a supervised subprocess speaking a versioned wire
protocol and presents it to the rest of the stack as a first-class
AsyncEngine — the reference's engine-subprocess shims
(launch/dynamo-run/src/subprocess/vllm_inc.py, sglang_inc.py,
trtllm_inc.py) as a reusable subsystem:

- protocol.py  — the versioned frame vocabulary over the fabric codec
- supervisor.py — spawn / handshake / heartbeat / backoff-restart
- client.py    — SubprocessEngine: the AsyncEngine facade workers use
- shim.py      — the library a foreign engine imports to speak the wire
- reference_worker.py — torch-free reference engine for tests/CI

See docs/external_engines.md "Level 2: subprocess workers".
"""

from dynamo_tpu.external.client import SubprocessEngine
from dynamo_tpu.external.supervisor import EngineSupervisor, SupervisorConfig

__all__ = ["SubprocessEngine", "EngineSupervisor", "SupervisorConfig"]

"""Versioned wire protocol between a Worker and a subprocess engine.

Frames ride the fabric codec (`runtime/codec.py`: u32 header_len, u32
payload_len, u64 xxh3(header), u64 xxh3(payload), msgpack header, raw
payload) over the child's stdio pipes or a unix socket — same checksum
discipline as every other cross-process plane in this repo, so a
truncated or corrupted frame is a `CodecError`, never a silent
misparse. Headers are JSON-shaped documents (string keys, scalar/list
values); bulk bodies (the request dict, token items, KV event batches)
ride the payload as msgpack.

Handshake: the CHILD speaks first —

  child  -> hello  {v, model, capabilities: {embed, kv_events}, card?}
  parent -> ready  {v}            (or error + close on version mismatch)

after which either side may send, full duplex:

  parent -> generate {id} + payload msgpack(PreprocessedRequest.to_dict())
  parent -> cancel   {id}         (context.cancelled propagation)
  parent -> embed    {id} + payload msgpack({prompts})
  parent -> ping     {n}
  parent -> shutdown {}           (graceful drain request)

  child  -> token    {id} + payload msgpack(stream item dict)
  child  -> finish   {id, finish_reason?, cancelled}   (terminal)
  child  -> error    {id?, message}   (request-terminal with id;
                                       process-fatal without)
  child  -> embed_result {id} + payload msgpack({embeddings})
  child  -> kv_event {} + payload msgpack([{kind, block_hashes,
                          parent_hash, token_blocks}, ...])  — the exact
                          dict shape worker.py publishes on the bus,
                          wire-compatible with engine/page_table.KvEvent
                          and native/kv_events.cpp
  child  -> metrics  {} + payload msgpack(load snapshot dict)
  child  -> span     {} + payload msgpack([finished span dicts,
                          telemetry/trace.py Span.to_dict shape]) — the
                          child's side of a distributed trace, emitted
                          only when the generate frame carried a `trace`
                          context; the parent adopts them into its ring
  child  -> pong     {n}

Unknown frame types are ignored by both sides (forward compatibility);
a `hello` whose `v` differs from PROTOCOL_VERSION is refused at
handshake — the ONLY version gate, so a fleet can mix shim builds until
an actual frame-vocabulary break bumps the number.
"""

from __future__ import annotations

import asyncio
import os
import sys
from typing import Any, Optional

import msgpack

from dynamo_tpu.runtime.codec import CodecError, encode_frame, read_frame

__all__ = [
    "PROTOCOL_VERSION",
    "UDS_ENV",
    "CodecError",
    "ProtocolError",
    "VersionMismatch",
    "hello_frame",
    "ready_frame",
    "check_hello",
    "check_ready",
    "pack",
    "unpack",
    "read_frame",
    "encode_frame",
    "stdio_streams",
    "child_streams",
]

PROTOCOL_VERSION = 1

#: env var naming the unix socket the child should connect to instead of
#: speaking on stdio (set by the supervisor in transport="uds" mode)
UDS_ENV = "DYNAMO_EXT_UDS"


class ProtocolError(Exception):
    """Frame that violates the protocol (bad handshake, missing fields)."""


class VersionMismatch(ProtocolError):
    """Handshake refused: peer speaks a different protocol version."""


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(payload: bytes) -> Any:
    return msgpack.unpackb(payload, raw=False)


def hello_frame(
    model: str,
    capabilities: Optional[dict] = None,
    card: Optional[dict] = None,
) -> dict:
    h = {
        "type": "hello",
        "v": PROTOCOL_VERSION,
        "model": model,
        "capabilities": dict(capabilities or {}),
    }
    if card:
        h["card"] = card
    return h


def ready_frame() -> dict:
    return {"type": "ready", "v": PROTOCOL_VERSION}


def check_hello(header: Any) -> dict:
    """Validate the child's opening frame; returns it. Raises
    VersionMismatch / ProtocolError for the supervisor to refuse."""
    if not isinstance(header, dict) or header.get("type") != "hello":
        raise ProtocolError(
            f"expected hello frame, got {header!r:.200}"
        )
    v = header.get("v")
    if v != PROTOCOL_VERSION:
        raise VersionMismatch(
            f"engine speaks protocol v{v}, this runtime speaks "
            f"v{PROTOCOL_VERSION}"
        )
    return header


def check_ready(header: Any) -> dict:
    """Child-side validation of the supervisor's ready frame."""
    if not isinstance(header, dict) or header.get("type") != "ready":
        raise ProtocolError(
            f"expected ready frame, got {header!r:.200}"
        )
    v = header.get("v")
    if v != PROTOCOL_VERSION:
        raise VersionMismatch(
            f"supervisor speaks protocol v{v}, this shim speaks "
            f"v{PROTOCOL_VERSION}"
        )
    return header


# -- transports -------------------------------------------------------------


async def stdio_streams() -> tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """(reader, writer) over THIS process's stdin/stdout — the child side
    of the stdio transport. stdout becomes the wire: anything else the
    engine wants to say must go to stderr (the supervisor forwards it
    into the logging plane)."""
    loop = asyncio.get_running_loop()
    reader = asyncio.StreamReader()
    await loop.connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin.buffer
    )
    w_transport, w_protocol = await loop.connect_write_pipe(
        lambda: asyncio.streams.FlowControlMixin(),
        os.fdopen(os.dup(sys.stdout.fileno()), "wb"),
    )
    writer = asyncio.StreamWriter(w_transport, w_protocol, reader, loop)
    return reader, writer


async def child_streams() -> tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """Child-side transport resolution: unix socket if the supervisor
    exported UDS_ENV, else stdio."""
    path = os.environ.get(UDS_ENV)
    if path:
        return await asyncio.open_unix_connection(path)
    return await stdio_streams()

"""SubprocessEngine: a supervised foreign engine as a first-class
AsyncEngine.

`Worker(engine_kind="external", engine=SubprocessEngine([...]))` needs
zero changes to its registration/ingress/KV-publish paths: this class
satisfies the whole AsyncEngine surface (engine/async_engine.py) —
`generate`, optional `embed`, `metrics_dict()`, and the `on_kv_event`
sink the Worker wires for prefix routing — while the actual engine
lives in a subprocess behind external/protocol.py frames.

Failure semantics (the isolation boundary the in-process Level-1 path
cannot give):

- the child crashing mid-stream turns every in-flight request into an
  ERROR finish (never a hung stream) while the supervisor backoff-
  restarts it;
- a request arriving while the child is down waits up to
  `admission_timeout` for readiness, then raises EngineUnavailableError
  — a RetryableHandlerError, so the worker's ingress flags the error
  frame retryable and PushRouter.mark_down retry logic sends the
  request to a surviving instance.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Optional

from dynamo_tpu import telemetry
from dynamo_tpu.engine.page_table import KvEvent
from dynamo_tpu.external import protocol
from dynamo_tpu.external.supervisor import EngineSupervisor, SupervisorConfig
from dynamo_tpu.runtime.context import (
    CANCELLED,
    Context,
    queue_get_or_cancelled,
)
from dynamo_tpu.runtime.ingress import RetryableHandlerError

logger = logging.getLogger(__name__)


class EngineUnavailableError(RetryableHandlerError):
    """The subprocess engine is down/restarting/broken: another instance
    should take the request (PushRouter marks this one down)."""


class SubprocessEngine:
    """AsyncEngine over a supervised external/protocol.py subprocess."""

    def __init__(
        self,
        cmd: list[str],
        name: str = "ext",
        config: Optional[SupervisorConfig] = None,
        admission_timeout: float = 10.0,
    ):
        self.supervisor = EngineSupervisor(
            cmd, name=name, config=config,
            on_frame=self._on_frame, on_down=self._on_down,
        )
        self.name = name
        self.admission_timeout = admission_timeout
        #: set by Worker(engine_kind="external"): KvEvent sink feeding the
        #: worker's publish buffer (prefix routing for foreign engines)
        self.on_kv_event = None
        self.requests_received = 0
        self._streams: dict[str, asyncio.Queue] = {}
        self._embeds: dict[str, asyncio.Future] = {}
        self._metrics: dict = {}
        self._embed_ids = iter(range(1, 1 << 62))

    # -- lifecycle ---------------------------------------------------------

    async def start(self, wait_ready: bool = True) -> None:
        await self.supervisor.start()
        if wait_ready and not await self.supervisor.wait_ready(
            self.supervisor.config.ready_timeout
        ):
            state = self.supervisor.state
            await self.supervisor.stop()
            raise RuntimeError(
                f"external engine {self.name!r} never became ready "
                f"(state={state}); see its stderr in the logs"
            )

    async def stop(self) -> None:
        await self.supervisor.stop()
        self._fail_inflight("engine stopped")

    @property
    def hello(self) -> Optional[dict]:
        return self.supervisor.hello

    @property
    def capabilities(self) -> dict:
        return (self.supervisor.hello or {}).get("capabilities") or {}

    # -- frame routing (supervisor read loop) ------------------------------

    def _on_frame(self, header: Any, payload: bytes) -> None:
        t = header.get("type") if isinstance(header, dict) else None
        if t == "token":
            q = self._streams.get(header.get("id"))
            if q is not None:
                q.put_nowait(protocol.unpack(payload))
        elif t == "finish":
            q = self._streams.get(header.get("id"))
            if q is not None:
                q.put_nowait(None)
        elif t == "error":
            rid = header.get("id")
            if rid is None:
                logger.error(
                    "engine %s fatal: %s", self.name, header.get("message")
                )
                return
            q = self._streams.get(rid)
            if q is not None:
                q.put_nowait({"error": header.get("message") or "engine error"})
                q.put_nowait(None)
        elif t == "kv_event":
            if self.on_kv_event is None:
                return
            for e in protocol.unpack(payload):
                self.on_kv_event(
                    KvEvent(
                        kind=e["kind"],
                        block_hashes=tuple(e.get("block_hashes") or ()),
                        parent_hash=e.get("parent_hash"),
                        token_blocks=tuple(
                            tuple(b) for b in e.get("token_blocks") or ()
                        ),
                    )
                )
        elif t == "metrics":
            self._metrics = protocol.unpack(payload)
        elif t == "span":
            # the child's side of a distributed trace: adopt its finished
            # spans into this process's ring (no-op when tracing is off)
            try:
                for s in protocol.unpack(payload):
                    telemetry.record_span_dict(s)
            except Exception:
                logger.debug("malformed span frame dropped", exc_info=True)
        elif t == "embed_result":
            fut = self._embeds.pop(header.get("id"), None)
            if fut is not None and not fut.done():
                if header.get("error"):
                    fut.set_exception(RuntimeError(header["error"]))
                else:
                    fut.set_result(protocol.unpack(payload)["embeddings"])
        elif t == "pong":
            pass
        else:
            logger.debug("ignoring unknown frame type %r", t)

    def _on_down(self, reason: str) -> None:
        self._fail_inflight(f"engine subprocess died: {reason}")

    def _fail_inflight(self, message: str) -> None:
        streams, self._streams = dict(self._streams), {}
        for q in streams.values():
            q.put_nowait({"error": message, "engine_down": True})
            q.put_nowait(None)
        embeds, self._embeds = dict(self._embeds), {}
        for fut in embeds.values():
            if not fut.done():
                fut.set_exception(EngineUnavailableError(message))

    # -- AsyncEngine contract ----------------------------------------------

    async def _admit(self) -> None:
        sup = self.supervisor
        if sup.state == "broken":
            raise EngineUnavailableError(
                f"external engine {self.name!r} is circuit-broken"
            )
        if not sup.ready and not await sup.wait_ready(self.admission_timeout):
            raise EngineUnavailableError(
                f"external engine {self.name!r} is down "
                f"(state={sup.state})"
            )

    async def generate(
        self, context: Context, request
    ) -> AsyncIterator[dict]:
        await self._admit()
        self.requests_received += 1
        rid = request.request_id
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        got_data = False
        settled = False  # terminal frame seen / cancel already propagated
        with telemetry.span(
            "engine.generate", service="engine",
            attrs={"request_id": rid, "engine": self.name,
                   "input_tokens": len(request.token_ids)},
        ) as sp:
            gen_header: dict = {"type": "generate", "id": rid}
            trace_ctx = telemetry.wire_context()
            if trace_ctx:
                # the child stitches its own spans under this one and
                # ships them back as `span` frames
                gen_header["trace"] = trace_ctx
            try:
                try:
                    await self.supervisor.send(
                        gen_header, protocol.pack(request.to_dict())
                    )
                except ConnectionError as e:
                    settled = True  # never reached the child
                    raise EngineUnavailableError(str(e))
                while True:
                    if context.cancelled:
                        settled = True
                        try:
                            await self.supervisor.send(
                                {"type": "cancel", "id": rid}
                            )
                        except Exception:
                            pass  # child gone — nothing left to cancel
                        return
                    item = await queue_get_or_cancelled(context, q)
                    if item is CANCELLED:
                        continue  # loop re-checks context.cancelled
                    if item is None:
                        settled = True
                        return
                    if "error" in item:
                        settled = True
                        if item.get("engine_down") and not got_data:
                            # nothing streamed yet: the request is safely
                            # retryable on another instance
                            raise EngineUnavailableError(item["error"])
                        raise RuntimeError(item["error"])
                    if not got_data:
                        sp.add_event("first_token")
                    got_data = True
                    yield item
            finally:
                self._streams.pop(rid, None)
                if not settled:
                    # the CONSUMER abandoned the stream (client disconnect
                    # closed this generator mid-yield): tell the child, or
                    # it computes the whole request for nobody
                    try:
                        await self.supervisor.send(
                            {"type": "cancel", "id": rid}
                        )
                    except Exception:
                        pass

    async def embed(self, prompts, normalize: bool = True):
        if not self.capabilities.get("embed"):
            raise RuntimeError(
                f"external engine {self.name!r} does not serve embeddings"
            )
        await self._admit()
        eid = f"embed-{next(self._embed_ids)}"
        fut = asyncio.get_running_loop().create_future()
        self._embeds[eid] = fut
        try:
            await self.supervisor.send(
                {"type": "embed", "id": eid},
                protocol.pack({"prompts": [list(p) for p in prompts],
                               "normalize": bool(normalize)}),
            )
            return await asyncio.wait_for(fut, self.admission_timeout + 30.0)
        finally:
            self._embeds.pop(eid, None)

    def metrics_dict(self) -> dict:
        return {
            "requests_received": self.requests_received,
            **self._metrics,
            **self.supervisor.metrics(),
        }

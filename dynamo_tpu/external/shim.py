"""The library a foreign engine imports to speak the wire protocol.

A shim process wraps any object satisfying the Level-1 AsyncEngine
contract (docs/external_engines.md): `generate(context,
PreprocessedRequest) -> async iterator of {"token_ids": [...],
"finish_reason": ...}`, optional `embed`, optional `metrics_dict`,
optional assignable `on_kv_event`. `run_engine(engine, model=...)` does
the rest: transport resolution (stdio, or the unix socket named in
$DYNAMO_EXT_UDS), hello/ready handshake with version refusal,
concurrent request serving with cancel propagation, KV-event and
metrics upstreaming, ping/pong, and graceful drain on `shutdown`.

Mirrors the reference's engine-side shims
(launch/dynamo-run/src/subprocess/vllm_inc.py sglang_inc.py): ~40 lines
of engine-specific code joins the runtime; everything else is here.

IMPORTANT: in stdio mode stdout IS the wire. The shim assumes nothing
else writes to it — print() diagnostics must go to stderr (the
supervisor forwards stderr into the serving process's log plane).
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import time
from typing import Any, Optional

from dynamo_tpu.external import protocol
from dynamo_tpu.preprocessor.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.telemetry.trace import new_span_id

logger = logging.getLogger(__name__)


class EngineShim:
    def __init__(
        self,
        engine,
        model: str = "external",
        card: Optional[dict] = None,
        metrics_interval: float = 1.0,
        kv_flush_interval: float = 0.2,
    ):
        self.engine = engine
        self.model = model
        self.card = card
        self.metrics_interval = metrics_interval
        self.kv_flush_interval = kv_flush_interval
        self._writer: Optional[asyncio.StreamWriter] = None
        self._write_lock = asyncio.Lock()
        self._contexts: dict[str, Context] = {}
        self._tasks: set[asyncio.Task] = set()
        self._kv_buffer: list[dict] = []

    # -- capabilities ------------------------------------------------------

    def _capabilities(self) -> dict:
        return {
            "embed": hasattr(self.engine, "embed"),
            "kv_events": hasattr(self.engine, "on_kv_event"),
        }

    def _buffer_kv(self, event) -> None:
        """KvEvent (or an equivalent duck) -> the wire dict shape the
        worker's publish loop uses on the bus."""
        self._kv_buffer.append(
            {
                "kind": event.kind,
                "block_hashes": list(event.block_hashes),
                "parent_hash": event.parent_hash,
                "token_blocks": [list(t) for t in event.token_blocks],
            }
        )

    # -- serving -----------------------------------------------------------

    async def send(self, header: Any, payload: bytes = b"") -> None:
        async with self._write_lock:
            self._writer.write(protocol.encode_frame(header, payload))
            await self._writer.drain()

    async def serve(self) -> None:
        reader, self._writer = await protocol.child_streams()
        await self.send(
            protocol.hello_frame(
                self.model, self._capabilities(), card=self.card
            )
        )
        header, _ = await asyncio.wait_for(protocol.read_frame(reader), 30.0)
        protocol.check_ready(header)  # VersionMismatch propagates -> exit
        if hasattr(self.engine, "on_kv_event"):
            self.engine.on_kv_event = self._buffer_kv
        pumps = [
            asyncio.get_running_loop().create_task(self._metrics_loop()),
            asyncio.get_running_loop().create_task(self._kv_flush_loop()),
        ]
        try:
            while True:
                try:
                    header, payload = await protocol.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return  # supervisor gone: exit with it
                t = header.get("type")
                if t == "generate":
                    self._spawn_generate(
                        header["id"], payload, trace=header.get("trace")
                    )
                elif t == "cancel":
                    ctx = self._contexts.get(header.get("id"))
                    if ctx is not None:
                        ctx.cancel()
                elif t == "embed":
                    self._spawn_embed(header["id"], payload)
                elif t == "ping":
                    await self.send({"type": "pong", "n": header.get("n")})
                elif t == "shutdown":
                    await self._drain()
                    return
                else:
                    logger.debug("ignoring unknown frame type %r", t)
        finally:
            for p in pumps:
                p.cancel()
            await self._flush_kv()

    def _spawn_generate(
        self, rid: str, payload: bytes, trace: Optional[dict] = None
    ) -> None:
        ctx = Context(request_id=rid)
        self._contexts[rid] = ctx
        t = asyncio.get_running_loop().create_task(
            self._serve_generate(ctx, rid, payload, trace)
        )
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    def _child_span(self, rid: str, trace: Optional[dict]) -> Optional[dict]:
        """A hand-built span dict stitched under the parent's engine span
        (the `trace` context from the generate frame). Built directly —
        the child's own tracer stays off; the PARENT decides whether a
        request is traced by sending (or not sending) the context."""
        if not isinstance(trace, dict) or not trace.get("trace_id"):
            return None
        return {
            "trace_id": trace["trace_id"],
            "span_id": new_span_id(),
            "parent_id": trace.get("span_id"),
            "name": "child.generate",
            "service": "ext-child",
            "start_ts": time.time(),
            "duration_ms": None,
            "status": "ok",
            "attrs": {"request_id": rid, "child_pid": os.getpid(),
                      "model": self.model},
            "events": [],
        }

    async def _ship_span(self, span: Optional[dict], t0: float, **attrs) -> None:
        if span is None:
            return
        span["duration_ms"] = (time.perf_counter() - t0) * 1000.0
        span["attrs"].update(attrs)
        try:
            await self.send({"type": "span"}, protocol.pack([span]))
        except Exception:
            pass  # wire gone — the trace just loses the child's side

    async def _serve_generate(
        self, ctx: Context, rid: str, payload: bytes,
        trace: Optional[dict] = None,
    ) -> None:
        span = self._child_span(rid, trace)
        t0 = time.perf_counter()
        tokens = 0
        try:
            request = PreprocessedRequest.from_dict(protocol.unpack(payload))
            finish = None
            async for item in self.engine.generate(ctx, request):
                if "error" in item:
                    await self.send(
                        {"type": "error", "id": rid,
                         "message": str(item["error"])}
                    )
                    await self._ship_span(span, t0, tokens=tokens)
                    span = None
                    return
                finish = item.get("finish_reason")
                if span is not None and tokens == 0:
                    span["events"].append(
                        {"ts": time.time(), "name": "first_token",
                         "attrs": {}}
                    )
                tokens += len(item.get("token_ids", ()))
                await self.send(
                    {"type": "token", "id": rid}, protocol.pack(item)
                )
            await self.send(
                {
                    "type": "finish", "id": rid, "finish_reason": finish,
                    "cancelled": ctx.cancelled,
                }
            )
            if span is not None and ctx.cancelled:
                span["status"] = "cancelled"
            await self._ship_span(span, t0, tokens=tokens)
            span = None
        except ConnectionError:
            pass  # parent gone — nobody left to tell
        except Exception as e:  # noqa: BLE001 — stream errors to the parent
            logger.exception("generate failed for %s", rid)
            await self._send_error(rid, e)
            if span is not None:
                span["status"] = "error"
                span["attrs"]["error"] = f"{type(e).__name__}: {e}"
                await self._ship_span(span, t0, tokens=tokens)
                span = None
        finally:
            self._contexts.pop(rid, None)

    async def _send_error(self, rid: str, e: Exception) -> None:
        try:
            await self.send(
                {"type": "error", "id": rid,
                 "message": f"{type(e).__name__}: {e}"}
            )
        except Exception:
            pass

    def _spawn_embed(self, eid: str, payload: bytes) -> None:
        async def _run():
            try:
                req = protocol.unpack(payload)
                vecs = await self.engine.embed(
                    req["prompts"], normalize=req.get("normalize", True)
                )
                await self.send(
                    {"type": "embed_result", "id": eid},
                    protocol.pack(
                        {"embeddings": [[float(x) for x in v] for v in vecs]}
                    ),
                )
            except Exception as e:  # noqa: BLE001
                await self.send(
                    {"type": "embed_result", "id": eid,
                     "error": f"{type(e).__name__}: {e}"}
                )

        t = asyncio.get_running_loop().create_task(_run())
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _drain(self, timeout: float = 4.0) -> None:
        """shutdown frame: let in-flight generations finish briefly, then
        cancel what's left. Cancelled streams send no finish frame — the
        parent's stop() already error-finishes its in-flight requests, so
        the child's only job here is to stop cleanly and flush KV."""
        if self._tasks:
            done, pending = await asyncio.wait(
                set(self._tasks), timeout=timeout
            )
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        await self._flush_kv()

    # -- upstream pumps ----------------------------------------------------

    async def _metrics_loop(self) -> None:
        if not hasattr(self.engine, "metrics_dict"):
            return
        while True:
            await asyncio.sleep(self.metrics_interval)
            try:
                await self.send(
                    {"type": "metrics"},
                    protocol.pack(dict(self.engine.metrics_dict())),
                )
            except (ConnectionError, RuntimeError):
                return

    async def _kv_flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.kv_flush_interval)
            try:
                await self._flush_kv()
            except (ConnectionError, RuntimeError):
                return

    async def _flush_kv(self) -> None:
        events = self._kv_buffer[: len(self._kv_buffer)]
        del self._kv_buffer[: len(events)]
        if events:
            await self.send({"type": "kv_event"}, protocol.pack(events))


def run_engine(
    engine,
    model: str = "external",
    card: Optional[dict] = None,
    metrics_interval: float = 1.0,
) -> None:
    """Blocking entry: serve `engine` on this process's wire until the
    supervisor shuts us down. Exits 2 on a protocol-version refusal."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    shim = EngineShim(
        engine, model=model, card=card, metrics_interval=metrics_interval
    )
    try:
        asyncio.run(shim.serve())
    except protocol.VersionMismatch as e:
        print(f"refusing to serve: {e}", file=sys.stderr, flush=True)
        sys.exit(2)

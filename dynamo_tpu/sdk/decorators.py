"""@service / @endpoint / depends — the graph DSL primitives.

Reference parity: deploy/sdk core/lib.py:88-121 (@service), core/decorators/
endpoint.py:99 (@endpoint), depends() in core/lib.py — reimagined thin:
metadata lives on the class, all runtime wiring happens in sdk/serving.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Union


@dataclass(frozen=True)
class ServiceMeta:
    name: str
    namespace: str = "dynamo"
    #: default replica count (config ServiceArgs.workers overrides)
    workers: int = 1


def service(cls=None, *, name: Optional[str] = None, namespace: str = "dynamo",
            workers: int = 1):
    """Class decorator marking a service. Usable bare (@service) or with
    arguments (@service(name=..., workers=2))."""

    def wrap(c):
        c._svc_meta = ServiceMeta(
            name=name or c.__name__, namespace=namespace, workers=workers
        )
        return c

    return wrap(cls) if cls is not None else wrap


def endpoint(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Method decorator: `async def handler(self, ctx, request)` yielding
    response chunks (the runtime's streaming handler contract,
    runtime/ingress.py)."""

    def wrap(f):
        f._endpoint_name = name or f.__name__
        return f

    return wrap(fn) if fn is not None else wrap


class depends:
    """Class attribute declaring a dependency on another service. At serve
    time the attribute becomes a ServiceClient whose endpoint methods stream
    responses:

        backend = depends(Backend)
        ...
        async for chunk in self.backend.generate({...}): ...
    """

    def __init__(self, target: Union[type, str]):
        self.target = target

    def target_meta(self) -> ServiceMeta:
        if isinstance(self.target, str):
            return ServiceMeta(name=self.target)
        meta = getattr(self.target, "_svc_meta", None)
        if meta is None:
            raise TypeError(
                f"depends() target {self.target!r} is not a @service class"
            )
        return meta


def service_meta(cls) -> ServiceMeta:
    meta = getattr(cls, "_svc_meta", None)
    if meta is None:
        raise TypeError(f"{cls!r} is not a @service class")
    return meta


def service_endpoints(cls) -> dict[str, str]:
    """endpoint name -> method attribute name."""
    out = {}
    for attr in dir(cls):
        fn = getattr(cls, attr, None)
        ep = getattr(fn, "_endpoint_name", None)
        if ep is not None:
            out[ep] = attr
    return out


def service_dependencies(cls) -> dict[str, depends]:
    """attribute name -> depends declaration."""
    out = {}
    for klass in reversed(cls.__mro__):
        for attr, val in vars(klass).items():
            if isinstance(val, depends):
                out[attr] = val
    return out

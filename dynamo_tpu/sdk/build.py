"""`build` / `deploy` for service graphs.

Reference parity: the dynamo CLI's build/deploy commands (deploy/sdk
cli/cli.py:71-81) — `build` freezes a graph into a deployable manifest
(services, dependency edges, endpoints, replica counts, launch commands);
`deploy` renders Kubernetes manifests from it (the YAML-first equivalent
of the reference's DynamoGraphDeployment CRD + operator, SURVEY.md §2.9).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from dynamo_tpu.sdk.decorators import (
    service_dependencies,
    service_endpoints,
    service_meta,
)
from dynamo_tpu.sdk.graph import discover_graph


def build_manifest(
    root_spec: str, config: Optional[dict] = None, image: str = "dynamo-tpu:latest"
) -> dict:
    """Resolve `pkg.module:Class` and freeze the full graph."""
    from dynamo_tpu.sdk.config import replica_count
    from dynamo_tpu.sdk.serving import resolve_service

    root = resolve_service(root_spec)
    services = []
    for cls in discover_graph(root):
        meta = service_meta(cls)
        svc_cfg = (config or {}).get(meta.name, {})
        services.append(
            {
                "name": meta.name,
                "namespace": meta.namespace,
                "class": f"{cls.__module__}:{cls.__name__}",
                "replicas": replica_count(svc_cfg, meta.workers),
                "endpoints": sorted(service_endpoints(cls)),
                "depends": sorted(
                    service_meta(d.target).name
                    if not isinstance(d.target, str)
                    else d.target
                    for d in service_dependencies(cls).values()
                ),
                "config": svc_cfg,
            }
        )
    return {
        "kind": "DynamoTpuGraph",
        "version": 1,
        "root": root_spec,
        "image": image,
        "services": services,
    }


def write_build(manifest: dict, out_dir: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "graph.json")
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
    return path


# -- k8s rendering -----------------------------------------------------------


def _k8s_name(s: str) -> str:
    return s.lower().replace("_", "-")


def render_k8s(
    manifest: dict,
    fabric_host: str = "dynamo-fabric",
    include_fabric: bool = True,
    fabric_port: int = 4222,
) -> list[dict]:
    """One Deployment per service (replicas from the graph), plus the
    fabric control-plane Deployment + Service the workers rendezvous on.
    `include_fabric=False` points services at an EXTERNALLY-managed fabric
    at `fabric_host:fabric_port` (platform-chart mode: one persistent
    fabric shared by graphs, like the reference's shared etcd/NATS
    platform services)."""
    if not include_fabric:
        return _service_objs(manifest, fabric_host, fabric_port)
    objs = [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": fabric_host, "labels": {"app": fabric_host}},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": fabric_host}},
                "template": {
                    "metadata": {"labels": {"app": fabric_host}},
                    "spec": {
                        "containers": [
                            {
                                "name": "fabric",
                                "image": manifest["image"],
                                "command": [
                                    "python", "-m", "dynamo_tpu.cli.run",
                                    "fabric", "--port", str(fabric_port),
                                ],
                                "ports": [{"containerPort": fabric_port}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": fabric_host},
            "spec": {
                "selector": {"app": fabric_host},
                "ports": [
                    {"port": fabric_port, "targetPort": fabric_port}
                ],
            },
        },
    ]
    return objs + _service_objs(manifest, fabric_host, fabric_port)


def _service_objs(
    manifest: dict, fabric_host: str, fabric_port: int = 4222
) -> list[dict]:
    objs: list[dict] = []
    for svc in manifest["services"]:
        name = _k8s_name(svc["name"])
        container = {
            "name": name,
            "image": manifest["image"],
            "command": [
                "python", "-m", "dynamo_tpu.sdk.serving",
                svc["class"], "--fabric", f"{fabric_host}:{fabric_port}",
            ],
            "env": [
                {"name": "DYNTPU_SERVICE_CONFIG",
                 "value": json.dumps(svc["config"])}
            ],
        }
        port = svc["config"].get("port")
        if port:
            container["ports"] = [{"containerPort": int(port)}]
        # k8s scheduling passthrough (TPU nodepools/chips): the graph
        # manifest can't know cluster topology, so the CR carries it
        k8s = svc.get("k8s") or {}
        if k8s.get("resources"):
            container["resources"] = k8s["resources"]
        pod_spec: dict = {"containers": [container]}
        if k8s.get("nodeSelector"):
            pod_spec["nodeSelector"] = k8s["nodeSelector"]
        objs.append(
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": name, "labels": {"app": name}},
                "spec": {
                    "replicas": svc["replicas"],
                    "selector": {"matchLabels": {"app": name}},
                    "template": {
                        "metadata": {"labels": {"app": name}},
                        "spec": pod_spec,
                    },
                },
            }
        )
        if port:
            objs.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {"name": name},
                    "spec": {
                        "selector": {"app": name},
                        "ports": [{"port": int(port), "targetPort": int(port)}],
                    },
                }
            )
    return objs


def write_k8s(objs: list[dict], out_dir: str) -> str:
    import yaml

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "deploy.yaml")
    with open(path, "w") as f:
        yaml.safe_dump_all(objs, f, sort_keys=False)
    return path


def env_report() -> dict:
    """`env` command: the serving environment at a glance."""
    import platform as plat
    import sys

    report = {
        "python": sys.version.split()[0],
        "platform": plat.platform(),
    }
    try:
        import jax

        report["jax"] = jax.__version__
        report["jax_backend"] = jax.default_backend()
        report["devices"] = [str(d) for d in jax.devices()]
    except Exception as e:  # jax init can fail off-accelerator
        report["jax_error"] = str(e)
    for mod in ("flax", "optax", "numpy", "aiohttp", "msgpack"):
        try:
            report[mod] = __import__(mod).__version__
        except Exception:
            report[mod] = None
    from dynamo_tpu.runtime.runtime import DEFAULT_FABRIC_ADDR

    report["fabric_default"] = DEFAULT_FABRIC_ADDR
    return report

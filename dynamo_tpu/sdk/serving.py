"""Serve a service graph: in-process tasks or one OS process per replica.

In-process (`serve_graph`) is the test/dev path: every service instance
shares one event loop and one fabric. The CLI (`dynamo-tpu serve
pkg.module:Root`) is the production shape — it spawns `python -m
dynamo_tpu.sdk.serving pkg.module:Service` once per replica (the
reference's circus watcher per service, cli/serving.py:66,152), each
joining the fabric with its own lease so crash-detection and scaling work
exactly as for plain workers.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import logging
import sys
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.runtime import DistributedRuntime, IngressServer
from dynamo_tpu.sdk.config import load_config
from dynamo_tpu.sdk.decorators import (
    ServiceMeta,
    service_dependencies,
    service_endpoints,
    service_meta,
)
from dynamo_tpu.sdk.graph import discover_graph

logger = logging.getLogger(__name__)


class _EndpointCaller:
    def __init__(self, client: "ServiceClient", ep_name: str):
        self._client = client
        self._ep = ep_name

    async def __call__(self, request: Any, context=None) -> AsyncIterator[Any]:
        router = await self._client._router(self._ep)
        async for item in router.generate(request, context=context):
            yield item

    async def unary(self, request: Any) -> Any:
        """Convenience: single-result endpoints — returns the last chunk."""
        last = None
        async for item in self(request):
            last = item
        return last


class ServiceClient:
    """depends() resolution: endpoint-name attribute access returns a
    streaming caller backed by a PushRouter over the target's instances."""

    def __init__(self, runtime: DistributedRuntime, meta: ServiceMeta):
        self._runtime = runtime
        self._meta = meta
        self._routers: dict[str, Any] = {}
        self._lock = asyncio.Lock()

    async def _router(self, ep_name: str):
        async with self._lock:
            router = self._routers.get(ep_name)
            if router is None:
                ep = (
                    self._runtime.namespace(self._meta.namespace)
                    .component(self._meta.name)
                    .endpoint(ep_name)
                )
                router = await ep.router()
                self._routers[ep_name] = router
        return router

    def __getattr__(self, name: str) -> _EndpointCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _EndpointCaller(self, name)

    def close(self) -> None:
        for r in self._routers.values():
            r.close()


class ServiceHandle:
    """One running service instance (in this process)."""

    def __init__(
        self, runtime, instance, ingress, registrations, clients,
        owns_runtime: bool = True,
    ):
        self.runtime = runtime
        self.instance = instance
        self.ingress = ingress
        self.registrations = registrations
        self.clients = clients
        self.owns_runtime = owns_runtime

    async def stop(self) -> None:
        for reg in self.registrations:
            try:
                await reg.deregister()
            except Exception:
                logger.debug("deregister failed", exc_info=True)
        if self.ingress is not None:
            await self.ingress.stop()
        teardown = getattr(self.instance, "teardown", None)
        if teardown is not None:
            await teardown()
        for c in self.clients:
            c.close()
        if self.owns_runtime:
            await self.runtime.close()


async def start_service(
    cls,
    config: Optional[dict] = None,
    fabric_addr: Optional[str] = None,
    runtime: Optional[DistributedRuntime] = None,
) -> ServiceHandle:
    """Bring up ONE instance of `cls`: join the fabric, inject config and
    dependency clients, run optional `async setup()`, then register
    endpoints (ready-then-advertise: no consumer is routed here before
    setup finished). Pass `runtime` to share a caller-owned runtime (the
    handle then doesn't close it)."""
    meta = service_meta(cls)
    owns_runtime = runtime is None
    if runtime is None:
        runtime = await DistributedRuntime.create(fabric_addr)
    instance = cls()
    instance.config = dict(config or {})
    instance.runtime = runtime  # services may register workers/watchers

    clients = []
    for attr, dep in service_dependencies(cls).items():
        client = ServiceClient(runtime, dep.target_meta())
        setattr(instance, attr, client)
        clients.append(client)

    eps = service_endpoints(cls)
    ingress = None
    registrations = []
    try:
        if eps:
            ingress = IngressServer()
            for ep_name, attr in eps.items():
                ingress.add_handler(ep_name, getattr(instance, attr))
            await ingress.start()

        setup = getattr(instance, "setup", None)
        if setup is not None:
            await setup()

        advertise_host = instance.config.get("advertise_host", "127.0.0.1")
        for ep_name in eps:
            ep = (
                runtime.namespace(meta.namespace)
                .component(meta.name)
                .endpoint(ep_name)
            )
            registrations.append(
                await ep.register(advertise_host, ingress.port, metadata={})
            )
    except Exception:
        if ingress is not None:
            await ingress.stop()
        for c in clients:
            c.close()
        if owns_runtime:
            await runtime.close()
        raise
    logger.info(
        "service %s up (%d endpoints)", meta.name, len(eps)
    )
    return ServiceHandle(
        runtime, instance, ingress, registrations, clients,
        owns_runtime=owns_runtime,
    )


class GraphHandle:
    def __init__(self, handles: list[ServiceHandle], shared_fabric=None):
        self.handles = handles
        self.shared_fabric = shared_fabric

    def instance_of(self, cls) -> Any:
        for h in self.handles:
            if isinstance(h.instance, cls):
                return h.instance
        raise KeyError(cls)

    async def stop(self) -> None:
        for h in reversed(self.handles):  # consumers before providers
            await h.stop()
        if self.shared_fabric is not None:
            await self.shared_fabric.close()


async def serve_graph(
    root,
    config: Optional[dict[str, dict]] = None,
    fabric_addr: Optional[str] = None,
    static: bool = False,
) -> GraphHandle:
    """In-process serving: every service of the graph on this event loop,
    dependencies first. `static=True` runs without any fabric server — all
    services share ONE in-memory fabric (discovery stays coherent). On any
    start failure, already-started services are stopped before the error
    propagates."""
    config = config or {}
    shared_fabric = None
    runtimes: list[Optional[DistributedRuntime]] = []
    classes = discover_graph(root)
    if static:
        from dynamo_tpu.runtime.fabric.local import LocalFabric

        shared_fabric = LocalFabric()
        for _ in classes:
            # LocalFabric has a real expiry reaper but no keepalive loop
            # (that lives in RemoteFabric) — an effectively-infinite TTL
            # keeps static in-process graphs registered for their lifetime.
            lease = await shared_fabric.grant_lease(1e12)
            runtimes.append(DistributedRuntime(shared_fabric, primary_lease=lease))
    else:
        runtimes = [None] * len(classes)

    handles: list[ServiceHandle] = []
    try:
        for cls, rt in zip(classes, runtimes):
            meta = service_meta(cls)
            handles.append(
                await start_service(
                    cls, config.get(meta.name), fabric_addr, runtime=rt
                )
            )
    except Exception:
        for h in reversed(handles):
            try:
                await h.stop()
            except Exception:
                logger.debug("rollback stop failed", exc_info=True)
        if shared_fabric is not None:
            await shared_fabric.close()
        raise
    return GraphHandle(handles, shared_fabric=shared_fabric)


def resolve_service(spec: str):
    """'pkg.module:ClassName' -> class."""
    mod_name, _, cls_name = spec.partition(":")
    if not cls_name:
        raise ValueError(f"service spec {spec!r} must be module:Class")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name)


async def _amain(args) -> None:
    import json
    import os

    cls = resolve_service(args.service)
    meta = service_meta(cls)
    if args.config:
        svc_config = load_config(args.config).get(meta.name)
    else:
        # k8s containers rendered by `deploy` carry the frozen per-service
        # config in the environment (sdk/build.py render_k8s).
        env_cfg = os.environ.get("DYNTPU_SERVICE_CONFIG")
        svc_config = json.loads(env_cfg) if env_cfg else None
    handle = await start_service(cls, svc_config, args.fabric)
    print(f"service {meta.name} up", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await handle.stop()


def main(argv: Optional[list[str]] = None) -> None:
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.sdk.serving",
        description="run ONE service of a graph (spawned by `dynamo-tpu serve`)",
    )
    p.add_argument("service", help="pkg.module:ClassName")
    p.add_argument("--fabric", required=True)
    p.add_argument("-f", "--config", default=None)
    args = p.parse_args(argv)
    from dynamo_tpu.logging_config import configure_logging
    from dynamo_tpu.platform import honor_jax_platforms_env

    configure_logging()
    honor_jax_platforms_env()
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()

"""Per-service YAML config with common-configs inheritance + env interpolation.

Reference parity: the SDK's YAML service configs
(examples/llm/configs/disagg_router.yaml:15-60 `common-configs`, env
interpolation in deploy/sdk lib/config.py). Shape:

    common-configs:
      fabric: 127.0.0.1:4222
    Frontend:
      port: ${FRONTEND_PORT}
    Worker:
      model: llama3-8b
      ServiceArgs:
        workers: 2
"""

from __future__ import annotations

import os
import re
from typing import Any

import yaml

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::-([^}]*))?\}")


def _interpolate(value: Any) -> Any:
    if isinstance(value, str):

        def sub(m: re.Match) -> str:
            var, default = m.group(1), m.group(2)
            got = os.environ.get(var)
            if got is None:
                if default is not None:
                    return default
                raise KeyError(
                    f"config references undefined environment variable {var}"
                )
            return got

        return _ENV_RE.sub(sub, value)
    if isinstance(value, dict):
        return {k: _interpolate(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_interpolate(v) for v in value]
    return value


def replica_count(svc_cfg: dict, default: int = 1) -> int:
    """Replicas for a service: `ServiceArgs.workers` (the documented
    shape) with a flat `workers` key accepted too — serve, build, and
    deploy all resolve through here so one config drives every command."""
    sa = svc_cfg.get("ServiceArgs") or {}
    if "workers" in sa:
        return int(sa["workers"])
    if "workers" in svc_cfg:
        return int(svc_cfg["workers"])
    return int(default)


def load_config(path: str) -> dict[str, dict]:
    """service name -> merged config dict (common-configs under, service
    overrides on top), ${VAR} / ${VAR:-default} interpolated."""
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"{path}: top level must be a mapping")
    common = data.pop("common-configs", {}) or {}
    out = {}
    for svc, cfg in data.items():
        merged = {**common, **(cfg or {})}
        out[svc] = _interpolate(merged)
    return out

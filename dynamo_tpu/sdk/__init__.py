"""SDK: the service-graph DSL and local serving orchestrator.

Capability parity with the reference's deploy/sdk (SURVEY.md #39): declare
services with `@service`, expose streaming handlers with `@endpoint`, wire
dependencies with `depends(Other)`, and run the whole graph with
`serve_graph` (in-process) or the `dynamo-tpu serve` CLI (one OS process
per service replica, the reference's circus-arbiter shape —
deploy/sdk/src/dynamo/sdk/cli/serving.py:152).

Every service process joins the distributed runtime: endpoints register
under namespace/<service>/<endpoint> with the process lease, dependencies
resolve to PushRouter-backed clients, so SDK graphs interoperate with
plain workers/frontends on the same fabric.
"""

from dynamo_tpu.sdk.config import load_config
from dynamo_tpu.sdk.decorators import depends, endpoint, service
from dynamo_tpu.sdk.graph import discover_graph
from dynamo_tpu.sdk.serving import ServiceHandle, serve_graph

__all__ = [
    "service",
    "endpoint",
    "depends",
    "discover_graph",
    "load_config",
    "serve_graph",
    "ServiceHandle",
]

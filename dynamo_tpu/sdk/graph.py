"""Graph discovery: root service class -> dependency-ordered class list."""

from __future__ import annotations

from dynamo_tpu.sdk.decorators import service_dependencies, service_meta


def discover_graph(root) -> list[type]:
    """All services reachable from `root` via depends(), dependencies first
    (so serving brings providers up before consumers). String-named
    dependencies are external (already running on the fabric) and are not
    part of the returned graph."""
    order: list[type] = []
    visiting: set[type] = set()

    def visit(cls) -> None:
        service_meta(cls)  # raises for non-services
        if cls in order:
            return
        if cls in visiting:
            raise ValueError(
                f"dependency cycle through {cls.__name__}"
            )
        visiting.add(cls)
        for dep in service_dependencies(cls).values():
            if not isinstance(dep.target, str):
                visit(dep.target)
        visiting.discard(cls)
        order.append(cls)

    visit(root)
    return order

"""Structured logging: pretty console or JSONL (env DYNTPU_LOGGING_JSONL).

Parity with the reference's logging layer (lib/runtime/src/logging.rs:100:
pretty vs JSONL selected by env, flattened span fields) — here a JSON
formatter that merges `extra` fields into each record.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_STD_ATTRS = set(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime"}


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        for k, v in record.__dict__.items():
            if k not in _STD_ATTRS and not k.startswith("_"):
                try:
                    json.dumps(v)
                    out[k] = v
                except TypeError:
                    out[k] = repr(v)
        return json.dumps(out)


def env_is_truthy(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


def configure_logging(level: int | None = None) -> None:
    level = level if level is not None else (
        logging.DEBUG if env_is_truthy("DYNTPU_DEBUG") else logging.INFO
    )
    handler = logging.StreamHandler(sys.stderr)
    if env_is_truthy("DYNTPU_LOGGING_JSONL"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)

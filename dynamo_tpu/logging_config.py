"""Structured logging: pretty console or JSONL (env DYNTPU_LOGGING_JSONL).

Parity with the reference's logging layer (lib/runtime/src/logging.rs:100:
pretty vs JSONL selected by env, flattened span fields) — here a JSON
formatter that merges `extra` fields into each record.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_STD_ATTRS = set(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime"}


def _jsonable(v):
    """A value safe to embed in the record's JSON document. Plain
    `json.dumps(v)` only raises TypeError for foreign objects — NaN/Inf
    floats serialize into INVALID JSON (bare `NaN` tokens) and circular
    references raise ValueError, both of which would kill the final
    dumps of the whole record. allow_nan=False turns the NaN case into a
    catchable error; default=repr degrades foreign members of otherwise
    serializable containers; anything still hostile becomes repr(v)."""
    try:
        json.dumps(v, allow_nan=False)
        return v
    except (TypeError, ValueError):
        pass
    try:
        return json.loads(json.dumps(v, default=repr, allow_nan=False))
    except Exception:  # circular refs, NaN nested in containers, ...
        return repr(v)


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        for k, v in record.__dict__.items():
            if k not in _STD_ATTRS and not k.startswith("_"):
                out[k] = _jsonable(v)
        # logs join traces for free: any record emitted inside an active
        # span carries its ids (the explicit-extra ones win)
        if "trace_id" not in out:
            try:
                from dynamo_tpu import telemetry

                sp = telemetry.current_span()
                if sp is not None:
                    out["trace_id"] = sp.trace_id
                    out["span_id"] = sp.span_id
            except Exception:
                pass
        return json.dumps(out, default=repr)


def env_is_truthy(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


def configure_logging(level: int | None = None) -> None:
    level = level if level is not None else (
        logging.DEBUG if env_is_truthy("DYNTPU_DEBUG") else logging.INFO
    )
    handler = logging.StreamHandler(sys.stderr)
    if env_is_truthy("DYNTPU_LOGGING_JSONL"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)

"""Structured logging: pretty console or JSONL (env DYNTPU_LOGGING_JSONL).

Parity with the reference's logging layer (lib/runtime/src/logging.rs:100:
pretty vs JSONL selected by env, flattened span fields) — here a JSON
formatter that merges `extra` fields into each record.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_STD_ATTRS = set(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime"}


def _jsonable(v):
    """A value safe to embed in the record's JSON document. Plain
    `json.dumps(v)` only raises TypeError for foreign objects — NaN/Inf
    floats serialize into INVALID JSON (bare `NaN` tokens) and circular
    references raise ValueError, both of which would kill the final
    dumps of the whole record. allow_nan=False turns the NaN case into a
    catchable error; default=repr degrades foreign members of otherwise
    serializable containers; anything still hostile becomes repr(v)."""
    try:
        json.dumps(v, allow_nan=False)
        return v
    except (TypeError, ValueError):
        pass
    try:
        return json.loads(json.dumps(v, default=repr, allow_nan=False))
    except Exception:  # circular refs, NaN nested in containers, ...
        return repr(v)


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        for k, v in record.__dict__.items():
            if k not in _STD_ATTRS and not k.startswith("_"):
                out[k] = _jsonable(v)
        # logs join traces for free: any record emitted inside an active
        # span carries its ids (the explicit-extra ones win)
        if "trace_id" not in out:
            try:
                from dynamo_tpu import telemetry

                sp = telemetry.current_span()
                if sp is not None:
                    out["trace_id"] = sp.trace_id
                    out["span_id"] = sp.span_id
            except Exception:
                pass
        return json.dumps(out, default=repr)


def env_is_truthy(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


#: where bare log-file names land (DYNTPU_LOG_DIR overrides) — services
#: must not scatter frontend.log/metrics.log into whatever CWD they were
#: launched from (those strays used to end up at the repo root)
DEFAULT_LOG_DIR = os.path.join("artifacts", "log")


def resolve_log_file(name_or_path: str) -> str:
    """A bare file name (no directory part) lands in the log dir
    (DYNTPU_LOG_DIR, default artifacts/log — created on demand);
    an explicit path is honored as-is."""
    if os.path.dirname(name_or_path):
        return name_or_path
    log_dir = os.environ.get("DYNTPU_LOG_DIR") or DEFAULT_LOG_DIR
    os.makedirs(log_dir, exist_ok=True)
    return os.path.join(log_dir, name_or_path)


def configure_logging(
    level: int | None = None, log_file: str | None = None
) -> None:
    """Console handler (pretty or JSONL per DYNTPU_LOGGING_JSONL), plus
    an optional JSONL file handler: `log_file` argument or the
    DYNTPU_LOG_FILE env var; bare names default into artifacts/log (see
    resolve_log_file). The file plane is always JSONL — it is the sink
    the stall watchdog's structured diagnoses and the trace join are
    designed for (docs/observability.md)."""
    level = level if level is not None else (
        logging.DEBUG if env_is_truthy("DYNTPU_DEBUG") else logging.INFO
    )
    handler = logging.StreamHandler(sys.stderr)
    if env_is_truthy("DYNTPU_LOGGING_JSONL"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    handlers: list[logging.Handler] = [handler]
    log_file = log_file or os.environ.get("DYNTPU_LOG_FILE") or None
    if log_file:
        try:
            fh = logging.FileHandler(resolve_log_file(log_file))
            fh.setFormatter(JsonlFormatter())
            handlers.append(fh)
        except OSError:
            # an unwritable log dir must not stop the service booting
            logging.getLogger(__name__).warning(
                "cannot open log file %r; console only", log_file,
                exc_info=True,
            )
    root = logging.getLogger()
    root.handlers[:] = handlers
    root.setLevel(level)

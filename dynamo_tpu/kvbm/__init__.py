"""KVBM — multi-tier KV block manager.

TPU-native analogue of the reference's KV Block Manager (/root/reference
lib/llm/src/block_manager.rs:69-78): a tier hierarchy

    G1 device HBM  (the engine's page pool, models/llama.py KVPages)
    G2 host DRAM   (HostTier — bounded bytes, LRU)
    G3 local disk  (DiskTier — bounded bytes, LRU, one file per block)

Content-addressed blocks evicted from the device prefix cache are *offloaded*
down the hierarchy instead of dropped; a later prefix hit *onboards* them
back into fresh device pages (block_manager.rs:169 onboard_blocks). Effective
KV capacity becomes host-DRAM/disk-sized rather than HBM-sized — the
reference reports +40% TTFT from exactly this (SURVEY.md §6).

Where the reference moves blocks with CUDA memcpy/NIXL RDMA agents
(block/transfer.rs:83-111), the TPU build moves them through JAX device
transfers: extract = gather pages → host numpy; inject = scatter into the
device pool (engine.extract_pages / inject_pages).
"""

from dynamo_tpu.kvbm.manager import TieredPageAllocator
from dynamo_tpu.kvbm.tiers import BlockEntry, DiskTier, HostTier

__all__ = ["TieredPageAllocator", "HostTier", "DiskTier", "BlockEntry"]

"""Host-DRAM and disk KV block tiers (G2/G3).

Both tiers store whole content-addressed blocks — (seq_hash, parent_hash,
tokens, k, v) with k/v of shape [L, Hkv, S, D] — under a byte budget with
LRU eviction. A tier may be given a `demote` callback that receives entries
it evicts, chaining G2 → G3 (the reference's offload pipeline,
block_manager/offload.rs:17-45).
"""

from __future__ import annotations

import logging
import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)


@dataclass
class BlockEntry:
    seq_hash: int
    parent_hash: Optional[int]
    tokens: tuple[int, ...]
    k: np.ndarray  # [L, Hkv, S, D]
    v: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


class HostTier:
    """Bounded in-memory block store, LRU order (oldest first)."""

    def __init__(
        self,
        capacity_bytes: int,
        demote: Optional[Callable[[BlockEntry], None]] = None,
    ):
        self.capacity_bytes = capacity_bytes
        self._demote = demote
        self._entries: OrderedDict[int, BlockEntry] = OrderedDict()
        self._bytes = 0

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def put(self, entry: BlockEntry) -> bool:
        """True iff the block is preserved (here or via the demote chain)."""
        if entry.seq_hash in self._entries:
            return True
        if entry.nbytes > self.capacity_bytes:
            # Can never fit this tier — pass straight down the hierarchy.
            return bool(self._demote is not None and self._demote(entry))
        self._entries[entry.seq_hash] = entry
        self._bytes += entry.nbytes
        while self._bytes > self.capacity_bytes:
            _, victim = self._entries.popitem(last=False)
            self._bytes -= victim.nbytes
            if self._demote is not None:
                self._demote(victim)
        return True

    def get(self, seq_hash: int) -> Optional[BlockEntry]:
        """Read without removing; refreshes LRU recency."""
        e = self._entries.get(seq_hash)
        if e is not None:
            self._entries.move_to_end(seq_hash)
        return e

    def pop(self, seq_hash: int) -> Optional[BlockEntry]:
        e = self._entries.pop(seq_hash, None)
        if e is not None:
            self._bytes -= e.nbytes
        return e

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0


def _dtype_from_name(name: str) -> np.dtype:
    """Resolve numpy AND ml_dtypes names (bfloat16 is not a numpy builtin)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class DiskTier:
    """Bounded on-disk block store: one .npy file per block ([2,L,Hkv,S,D],
    k stacked over v, stored as raw uint8 bytes because np.save round-trips
    ml_dtypes.bfloat16 as an unusable void dtype), in-memory LRU index.
    Process-scoped (the index is not persisted), like the reference's G3
    pool."""

    def __init__(self, directory: str, capacity_bytes: int):
        self.directory = directory
        self.capacity_bytes = capacity_bytes
        os.makedirs(directory, exist_ok=True)
        #: seq_hash -> (parent_hash, tokens, nbytes, dtype_name, block_shape)
        self._index: OrderedDict[
            int, tuple[Optional[int], tuple[int, ...], int, str, tuple[int, ...]]
        ] = OrderedDict()
        self._bytes = 0

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.directory, f"{seq_hash & 0xFFFFFFFFFFFFFFFF:016x}.npy")

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def put(self, entry: BlockEntry) -> bool:
        if entry.seq_hash in self._index:
            return True
        if entry.nbytes > self.capacity_bytes:
            return False
        stacked = np.stack([entry.k, entry.v])
        try:
            np.save(self._path(entry.seq_hash), stacked.view(np.uint8))
        except OSError:
            logger.exception("disk tier write failed for %x", entry.seq_hash)
            return False
        self._index[entry.seq_hash] = (
            entry.parent_hash, entry.tokens, entry.nbytes,
            entry.k.dtype.name, entry.k.shape,
        )
        self._bytes += entry.nbytes
        while self._bytes > self.capacity_bytes:
            victim_hash, meta = self._index.popitem(last=False)
            self._bytes -= meta[2]
            self._unlink(victim_hash)
        return True

    def get(self, seq_hash: int) -> Optional[BlockEntry]:
        meta = self._index.get(seq_hash)
        if meta is None:
            return None
        parent_hash, tokens, _, dtype_name, shape = meta
        try:
            raw = np.load(self._path(seq_hash))
        except OSError:
            logger.exception("disk tier read failed for %x", seq_hash)
            self.pop(seq_hash)
            return None
        kv = raw.view(_dtype_from_name(dtype_name)).reshape((2, *shape))
        self._index.move_to_end(seq_hash)
        return BlockEntry(
            seq_hash=seq_hash, parent_hash=parent_hash, tokens=tokens,
            k=kv[0], v=kv[1],
        )

    def pop(self, seq_hash: int) -> None:
        meta = self._index.pop(seq_hash, None)
        if meta is not None:
            self._bytes -= meta[2]
            self._unlink(seq_hash)

    def _unlink(self, seq_hash: int) -> None:
        try:
            os.unlink(self._path(seq_hash))
        except OSError:
            pass

    def clear(self) -> None:
        for h in list(self._index):
            self.pop(h)

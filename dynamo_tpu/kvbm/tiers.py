"""Host-DRAM and disk KV block tiers (G2/G3).

Both tiers store whole content-addressed blocks — (seq_hash, parent_hash,
tokens, k, v) with k/v of shape [L, Hkv, S, D] — under a byte budget with
LRU eviction. A tier may be given a `demote` callback that receives entries
it evicts, chaining G2 → G3 (the reference's offload pipeline,
block_manager/offload.rs:17-45).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np
import xxhash

from dynamo_tpu import native

logger = logging.getLogger(__name__)

#: process-global count of disk-tier blocks whose at-rest checksum failed
#: on read (bit-rot -> cache miss, never garbage tokens). Exposed on both
#: Prometheus surfaces as dynamo_tpu_kvbm_disk_corrupt_total
#: (telemetry/debug.integrity_lines).
_disk_corrupt_lock = threading.Lock()
disk_corrupt_total = 0


def _count_disk_corrupt() -> None:
    global disk_corrupt_total
    with _disk_corrupt_lock:
        disk_corrupt_total += 1


@dataclass
class BlockEntry:
    seq_hash: int
    parent_hash: Optional[int]
    tokens: tuple[int, ...]
    k: np.ndarray  # [L, Hkv, S, D]
    v: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


class HostTier:
    """Bounded in-memory block store, LRU order (oldest first).

    Block bytes live in C++-owned, 64-byte-aligned, mlock'd (best-effort)
    slabs when libdynamo_native is available (native/host_tier.cpp — the
    reference keeps its G2 tier in native pinned memory for the same
    reason: lib/llm/src/block_manager/storage/cuda.rs:174 PinnedStorage).
    One engine config has one block shape, so the native store activates
    lazily on the first put and serves every same-sized block from its
    slab pool; odd-sized blocks (none in practice) ride a Python dict so
    behavior stays exact. Entries returned by get() view the slab directly
    — valid until the entry is popped or evicted; callers copy/consume
    immediately (onboard does a device_put)."""

    def __init__(
        self,
        capacity_bytes: int,
        demote: Optional[Callable[[BlockEntry], None]] = None,
    ):
        self.capacity_bytes = capacity_bytes
        self._demote = demote
        self._entries: OrderedDict[int, BlockEntry] = OrderedDict()
        self._bytes = 0
        # native slab store (lazy):
        #   hash -> (parent, tokens, k_shape, v_shape, dtype)
        self._nlib = None
        self._nh = None
        self._block_bytes = 0
        self._k_bytes = 0  # k's share of a slab (MLA: k and v differ)
        self._meta: dict[
            int, tuple[Optional[int], tuple[int, ...], tuple, tuple, np.dtype]
        ] = {}

    def _try_native_init(self, entry: BlockEntry) -> None:
        if self._nh is not None or self._nlib is not None:
            return
        lib = native.lib()
        if lib is None:
            self._nlib = False  # latch: don't re-probe per put
            return
        nh = lib.dyn_host_new(self.capacity_bytes, entry.nbytes, 1)
        if nh:
            self._nlib, self._nh = lib, nh
            self._block_bytes = entry.nbytes
            self._k_bytes = int(
                np.ascontiguousarray(entry.k).view(np.uint8).size
            )
        else:
            self._nlib = False

    def __del__(self):
        if self._nh is not None and self._nlib:
            self._nlib.dyn_host_delete(self._nh)

    def __contains__(self, seq_hash: int) -> bool:
        if seq_hash in self._entries:
            return True
        return bool(
            self._nh is not None and self._nlib.dyn_host_contains(self._nh, seq_hash)
        )

    def __len__(self) -> int:
        n = len(self._entries)
        if self._nh is not None:
            n += self._nlib.dyn_host_len(self._nh)
        return n

    @property
    def used_bytes(self) -> int:
        b = self._bytes
        if self._nh is not None:
            b += self._nlib.dyn_host_used_bytes(self._nh)
        return b

    # -- native-slab entry views -------------------------------------------

    def _slab_entry(self, seq_hash: int, ptr: int) -> BlockEntry:
        # k and v carry their OWN shapes/offsets: MLA caches are
        # asymmetric (k = latent, v = rope key), so a half/half split
        # would corrupt both
        parent, tokens, k_shape, v_shape, dtype = self._meta[seq_hash]
        kb = self._k_bytes
        vb = self._block_bytes - kb
        buf = (ctypes.c_uint8 * self._block_bytes).from_address(ptr)
        k = np.frombuffer(buf, np.uint8, kb).view(dtype).reshape(k_shape)
        v = np.frombuffer(buf, np.uint8, vb, offset=kb).view(dtype).reshape(
            v_shape
        )
        return BlockEntry(
            seq_hash=seq_hash, parent_hash=parent, tokens=tokens, k=k, v=v
        )

    def _evict_native_lru(self) -> None:
        ok = ctypes.c_int(0)
        victim = self._nlib.dyn_host_peek_lru(self._nh, ctypes.byref(ok))
        if not ok.value:
            return
        if self._demote is not None:
            ptr = self._nlib.dyn_host_get(self._nh, victim)
            # demote consumes the bytes synchronously (DiskTier.put copies)
            self._demote(self._slab_entry(victim, ptr))
        self._nlib.dyn_host_pop(self._nh, victim)
        self._meta.pop(victim, None)

    # -- store interface ---------------------------------------------------

    def put(self, entry: BlockEntry) -> bool:
        """True iff the block is preserved (here or via the demote chain)."""
        if entry.seq_hash in self:
            return True
        if entry.nbytes > self.capacity_bytes:
            # Can never fit this tier — pass straight down the hierarchy.
            return bool(self._demote is not None and self._demote(entry))
        self._try_native_init(entry)
        if self._nh is not None and entry.nbytes == self._block_bytes:
            ptr = self._nlib.dyn_host_reserve(self._nh, entry.seq_hash)
            # At capacity: demote LRU victims until it fits. Bounded by the
            # entry count — reserve can also fail on host OOM
            # (aligned_alloc null), where spinning would hang the engine.
            while not ptr and self._nlib.dyn_host_len(self._nh) > 0:
                self._evict_native_lru()
                ptr = self._nlib.dyn_host_reserve(self._nh, entry.seq_hash)
            if not ptr:  # allocation failure — pass down the hierarchy
                return bool(self._demote is not None and self._demote(entry))
            kb = self._k_bytes
            buf = (ctypes.c_uint8 * self._block_bytes).from_address(ptr)
            dst = np.frombuffer(buf, np.uint8)
            dst[:kb] = np.ascontiguousarray(entry.k).view(np.uint8).reshape(-1)
            dst[kb:] = np.ascontiguousarray(entry.v).view(np.uint8).reshape(-1)
            self._meta[entry.seq_hash] = (
                entry.parent_hash, entry.tokens, entry.k.shape,
                entry.v.shape, entry.k.dtype,
            )
            return True
        self._entries[entry.seq_hash] = entry
        self._bytes += entry.nbytes
        # Combined budget: evict Python entries first (they're the odd ones
        # out), then native slabs, so the tier never sits above capacity.
        while self.used_bytes > self.capacity_bytes:
            if self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                if self._demote is not None:
                    self._demote(victim)
            elif self._nh is not None and self._nlib.dyn_host_len(self._nh) > 0:
                self._evict_native_lru()
            else:
                break
        return True

    def get(self, seq_hash: int) -> Optional[BlockEntry]:
        """Read without removing; refreshes LRU recency. Native-slab entries
        view C++ memory — valid until pop/eviction."""
        e = self._entries.get(seq_hash)
        if e is not None:
            self._entries.move_to_end(seq_hash)
            return e
        if self._nh is not None:
            ptr = self._nlib.dyn_host_get(self._nh, seq_hash)
            if ptr:
                return self._slab_entry(seq_hash, ptr)
        return None

    def pop(self, seq_hash: int) -> Optional[BlockEntry]:
        e = self._entries.pop(seq_hash, None)
        if e is not None:
            self._bytes -= e.nbytes
            return e
        if self._nh is not None:
            ptr = self._nlib.dyn_host_get(self._nh, seq_hash)
            if ptr:
                # Materialize a copy: the slab is recycled on pop.
                view = self._slab_entry(seq_hash, ptr)
                out = BlockEntry(
                    seq_hash=view.seq_hash, parent_hash=view.parent_hash,
                    tokens=view.tokens, k=view.k.copy(), v=view.v.copy(),
                )
                self._nlib.dyn_host_pop(self._nh, seq_hash)
                self._meta.pop(seq_hash, None)
                return out
        return None

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0
        if self._nh is not None:
            self._nlib.dyn_host_clear(self._nh)
            self._meta.clear()


def _dtype_from_name(name: str) -> np.dtype:
    """Resolve numpy AND ml_dtypes names (bfloat16 is not a numpy builtin)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class DiskTier:
    """Bounded on-disk block store: one .npy file per block ([2,L,Hkv,S,D],
    k stacked over v, stored as raw uint8 bytes because np.save round-trips
    ml_dtypes.bfloat16 as an unusable void dtype), in-memory LRU index.
    Process-scoped (the index is not persisted), like the reference's G3
    pool.

    At-rest integrity: every file carries an 8-byte xxh3 trailer over the
    block bytes; `get` verifies it and treats a mismatch as a miss —
    the file is unlinked, the corruption counted
    (dynamo_tpu_kvbm_disk_corrupt_total) and NEVER served. Bit-rot on
    disk costs a cache miss, not garbage tokens."""

    def __init__(self, directory: str, capacity_bytes: int):
        self.directory = directory
        self.capacity_bytes = capacity_bytes
        os.makedirs(directory, exist_ok=True)
        #: seq_hash -> (parent_hash, tokens, nbytes, dtype_name,
        #:              k_shape, v_shape) — separate shapes: MLA caches
        #:              are asymmetric
        self._index: OrderedDict[int, tuple] = OrderedDict()
        self._bytes = 0
        #: this tier's corrupt-read count (the module counter aggregates
        #: every tier in the process)
        self.corrupt_reads = 0

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.directory, f"{seq_hash & 0xFFFFFFFFFFFFFFFF:016x}.npy")

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def put(self, entry: BlockEntry) -> bool:
        if entry.seq_hash in self._index:
            return True
        if entry.nbytes > self.capacity_bytes:
            return False
        flat = np.concatenate([
            np.ascontiguousarray(entry.k).view(np.uint8).reshape(-1),
            np.ascontiguousarray(entry.v).view(np.uint8).reshape(-1),
        ])
        # xxh3 trailer over the block bytes, stored IN the same file so
        # the sum can never get separated from the data it covers
        digest = np.frombuffer(
            xxhash.xxh3_64_digest(flat.tobytes()), np.uint8
        )
        try:
            np.save(self._path(entry.seq_hash), np.concatenate([flat, digest]))
        except OSError:
            logger.exception("disk tier write failed for %x", entry.seq_hash)
            return False
        self._index[entry.seq_hash] = (
            entry.parent_hash, entry.tokens, entry.nbytes,
            entry.k.dtype.name, entry.k.shape, entry.v.shape,
        )
        self._bytes += entry.nbytes
        while self._bytes > self.capacity_bytes:
            victim_hash, meta = self._index.popitem(last=False)
            self._bytes -= meta[2]
            self._unlink(victim_hash)
        return True

    def get(self, seq_hash: int) -> Optional[BlockEntry]:
        meta = self._index.get(seq_hash)
        if meta is None:
            return None
        parent_hash, tokens, nbytes, dtype_name, k_shape, v_shape = meta
        try:
            raw = np.load(self._path(seq_hash))
        except OSError:
            logger.exception("disk tier read failed for %x", seq_hash)
            self.pop(seq_hash)
            return None
        except ValueError:
            # np.load parsed a header that disagrees with the file body
            # (truncation / partial write): corruption, same remedy as a
            # failed checksum — miss + unlink + count
            logger.warning(
                "disk tier block %x is malformed (truncated?); dropping "
                "as corrupt", seq_hash,
            )
            self.corrupt_reads += 1
            _count_disk_corrupt()
            self.pop(seq_hash)
            return None
        # verify the xxh3 trailer BEFORE handing any byte out: a
        # truncated or bit-rotted file is a MISS (unlink + counter), the
        # caller re-prefills the block — never decodes from garbage
        if (
            len(raw) != nbytes + 8
            or xxhash.xxh3_64_digest(raw[:nbytes].tobytes())
            != raw[nbytes:].tobytes()
        ):
            logger.warning(
                "disk tier block %x failed its checksum (%d bytes); "
                "dropping as corrupt", seq_hash, len(raw),
            )
            self.corrupt_reads += 1
            _count_disk_corrupt()
            self.pop(seq_hash)
            return None
        dtype = _dtype_from_name(dtype_name)
        kb = int(np.prod(k_shape)) * dtype.itemsize
        k = raw[:kb].view(dtype).reshape(k_shape)
        v = raw[kb:nbytes].view(dtype).reshape(v_shape)
        self._index.move_to_end(seq_hash)
        return BlockEntry(
            seq_hash=seq_hash, parent_hash=parent_hash, tokens=tokens,
            k=k, v=v,
        )

    def pop(self, seq_hash: int) -> None:
        meta = self._index.pop(seq_hash, None)
        if meta is not None:
            self._bytes -= meta[2]
            self._unlink(seq_hash)

    def _unlink(self, seq_hash: int) -> None:
        try:
            os.unlink(self._path(seq_hash))
        except OSError:
            pass

    def clear(self) -> None:
        for h in list(self._index):
            self.pop(h)

"""BlockDirectory: who in the fleet can serve which KV block (G4 remote).

Worker-side twin of the router's KvIndexer: subscribes the same
`kv_events.>` stream (device stored/removed) plus `kvbm_tier.>` (blocks a
peer offloaded to its host/disk tier — still servable over the transfer
plane), and answers "which live peer holds block H, and how deep a chain
can it extend?". This is the knowledge that drives cross-worker onboarding
(the reference's G4 remote tier + onboard_blocks —
/root/reference lib/llm/src/block_manager.rs:69-78,169).

Deliberately best-effort: tier events carry only stores (no removals), the
per-worker hash sets are LRU-capped, and staleness self-heals — a fetch
that misses drops the claimed hashes for that peer (`drop`), and dead
workers are pruned against the live instance set (`retain_workers`). A
stale entry costs one failed fetch, never correctness: the serving peer
re-checks its tiers at fetch time.
"""

from __future__ import annotations

import asyncio
import logging
from collections import OrderedDict
from typing import Optional, Sequence

import msgpack

from dynamo_tpu.subjects import KV_EVENT_SUBJECT, KVBM_TIER_SUBJECT

logger = logging.getLogger(__name__)

#: per-worker hash-set bound (device + tier each): memory backstop, LRU
MAX_HASHES_PER_WORKER = 200_000


class _WorkerSet:
    """LRU-capped hash set."""

    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict[int, None] = OrderedDict()

    def add(self, h: int) -> None:
        self._d[h] = None
        self._d.move_to_end(h)
        while len(self._d) > self.cap:
            self._d.popitem(last=False)

    def discard(self, h: int) -> None:
        self._d.pop(h, None)

    def __contains__(self, h: int) -> bool:
        return h in self._d

    def __len__(self) -> int:
        return len(self._d)


class BlockDirectory:
    def __init__(
        self,
        fabric,
        own_instance_id: str = "",
        cap_per_worker: int = MAX_HASHES_PER_WORKER,
    ):
        self.fabric = fabric
        self.own_instance_id = own_instance_id
        self.cap = cap_per_worker
        #: worker -> blocks on its device / in its lower tiers
        self._dev: dict[str, _WorkerSet] = {}
        self._tier: dict[str, _WorkerSet] = {}
        self._subs: list = []
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        for subject, kind in (
            (KV_EVENT_SUBJECT, "dev"),
            (KVBM_TIER_SUBJECT, "tier"),
        ):
            sub = await self.fabric.subscribe(subject + ".>")
            self._subs.append(sub)
            self._tasks.append(loop.create_task(self._pump(sub, kind)))

    async def _pump(self, sub, kind: str) -> None:
        while True:
            msg = await sub.next()
            if msg is None:
                return
            try:
                worker_id = msg.header["instance_id"]
                if worker_id == self.own_instance_id:
                    continue
                events = msgpack.unpackb(msg.payload, raw=False)
                sets = self._dev if kind == "dev" else self._tier
                ws = sets.get(worker_id)
                if ws is None:
                    ws = sets[worker_id] = _WorkerSet(self.cap)
                for ev in events:
                    if ev.get("kind") == "stored":
                        for h in ev["block_hashes"]:
                            ws.add(h)
                    elif ev.get("kind") == "removed":
                        for h in ev["block_hashes"]:
                            ws.discard(h)
            except Exception:
                logger.exception("bad block-directory event on %s", msg.subject)

    # -- queries -----------------------------------------------------------

    def has_entries(self) -> bool:
        return any(len(s) for s in self._dev.values()) or any(
            len(s) for s in self._tier.values()
        )

    def _servable(self, worker_id: str, h: int) -> bool:
        dev = self._dev.get(worker_id)
        if dev is not None and h in dev:
            return True
        tier = self._tier.get(worker_id)
        return tier is not None and h in tier

    def holders(self, h: int) -> list[str]:
        out = []
        for w in set(self._dev) | set(self._tier):
            if self._servable(w, h):
                out.append(w)
        return out

    def has_chain(self, seq_hashes: Sequence[int], min_blocks: int) -> bool:
        """Cheap pre-filter: does any single worker hold `min_blocks`
        consecutive hashes starting at ANY position? Upper-bounds every
        possible best_chain result, so callers can skip the (engine-thread)
        local-residency probe when nothing claimable exists."""
        for w in set(self._dev) | set(self._tier):
            run = 0
            for h in seq_hashes:
                if self._servable(w, h):
                    run += 1
                    if run >= min_blocks:
                        return True
                else:
                    run = 0
        return False

    def best_chain(
        self, seq_hashes: Sequence[int], start: int
    ) -> Optional[tuple[str, int]]:
        """Peer that can extend the chain furthest from position `start`:
        (worker_id, depth). None when nobody holds seq_hashes[start]."""
        best: Optional[tuple[str, int]] = None
        for w in self.holders(seq_hashes[start]):
            depth = 0
            for h in seq_hashes[start:]:
                if not self._servable(w, h):
                    break
                depth += 1
            if best is None or depth > best[1]:
                best = (w, depth)
        return best

    # -- self-healing ------------------------------------------------------

    def drop(self, worker_id: str, hashes: Sequence[int]) -> None:
        """A fetch claimed these and missed: forget them for that peer."""
        for sets in (self._dev, self._tier):
            ws = sets.get(worker_id)
            if ws is not None:
                for h in hashes:
                    ws.discard(h)

    def retain_workers(self, live: Sequence[str]) -> None:
        keep = set(live)
        for sets in (self._dev, self._tier):
            for w in list(sets):
                if w not in keep:
                    del sets[w]

    async def stop(self) -> None:
        for sub in self._subs:
            sub.close()
        for t in self._tasks:
            t.cancel()

"""TieredPageAllocator: the engine's PageAllocator with G2/G3 offload.

Drop-in subclass of engine.page_table.PageAllocator (the scheduler is
unaware of tiering):

- **Offload on eviction**: when a content-addressed page is about to be
  evicted from the device pool (its KV bytes would be lost), the block is
  extracted to the host tier first; host-tier overflow demotes to disk
  (reference: OffloadManager priority queues, block_manager/offload.rs).
- **Onboard on prefix hit**: `lookup` first matches device-resident pages
  (free reuse), then continues the chain through host/disk; found blocks are
  injected into freshly allocated device pages and registered, extending the
  effective prefix cache past HBM (block_manager.rs:169 onboard_blocks).

Accounting: `match_length` stays device-only on purpose — onboarded blocks
consume fresh device pages, so the scheduler's admission math (pages needed
= total - device-cached) remains exact whether or not onboarding succeeds.

Offload is **double-buffered**: eviction enqueues the page gather + the
device→host copy on the device stream (extract_async_fn) and returns
immediately; the bytes land in the host tier when the transfer is drained —
at the next engine step (flush_offloads), when the staging buffer fills, or
on demand when a prefix hit needs a still-in-flight block. Ordering makes
this safe: the gather is enqueued before any subsequent dispatch can
overwrite the evicted page (the reference overlaps its offload DMA the same
way — block_manager/offload.rs).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

import numpy as np

from dynamo_tpu.engine.page_table import KvEvent, PageAllocator
from dynamo_tpu.kvbm.tiers import BlockEntry, DiskTier, HostTier

logger = logging.getLogger(__name__)

#: (page_ids) -> (k, v) as [L, Hkv, n, S, D] host arrays
ExtractFn = Callable[[Sequence[int]], tuple[np.ndarray, np.ndarray]]
#: (page_ids, k, v) -> None, same shapes
InjectFn = Callable[[Sequence[int], np.ndarray, np.ndarray], None]

#: staged async-offload blocks before a forced drain (bounds the HBM the
#: staging gathers hold)
MAX_PENDING_OFFLOADS = 64


class TieredPageAllocator(PageAllocator):
    def __init__(
        self,
        num_pages: int,
        page_size: int,
        extract_fn: ExtractFn,
        inject_fn: InjectFn,
        host_bytes: int = 0,
        disk_bytes: int = 0,
        disk_dir: Optional[str] = None,
        on_event=None,
        extract_async_fn: Optional[ExtractFn] = None,
        on_tier_event: Optional[
            Callable[[int, Optional[int], str], None]
        ] = None,
    ):
        super().__init__(num_pages, page_size, on_event=on_event)
        #: (seq_hash, parent_hash, tier) -> None, fired when a block lands
        #: in a lower tier (G4 peers learn this worker can serve it, and
        #: the router's TierMap learns WHICH tier for warmth discounting;
        #: removals self-heal via failed fetches, so only stores are
        #: announced)
        self._on_tier_event = on_tier_event
        #: prefix-hit continuations served from a lower tier, by tier —
        #: the doctor's tier-pressure rule reads the disk share
        self.tier_hits: dict[str, int] = {"host": 0, "disk": 0}
        self._extract_fn = extract_fn
        self._extract_async_fn = extract_async_fn
        self._inject_fn = inject_fn
        if disk_bytes > 0 and not disk_dir:
            raise ValueError(
                "disk KV tier enabled (disk_bytes > 0) but no disk_dir given"
            )
        self.disk: Optional[DiskTier] = (
            DiskTier(disk_dir, disk_bytes) if disk_bytes > 0 else None
        )
        demote = self.disk.put if self.disk is not None else None
        self.host: Optional[HostTier] = (
            HostTier(host_bytes, demote=demote) if host_bytes > 0 else None
        )
        self._offload_enabled = self.host is not None or self.disk is not None
        #: seq_hash -> (parent_hash, tokens, k_dev, v_dev, column) — gathers
        #: in flight to host; k_dev/v_dev are shared per extract batch
        self._pending: dict[int, tuple] = {}

    # -- offload (device eviction hook) ------------------------------------

    def _offload_pages(self, pages: Sequence[int]) -> None:
        """Stage `pages` for offload in one batched device gather. With an
        async extractor the call returns before the copy lands; otherwise
        the bytes go straight down the tier hierarchy."""
        todo = []
        for page in pages:
            seq_hash, parent_hash, tokens = self._page_meta[page]
            if not self.tier_contains(seq_hash):
                todo.append((page, seq_hash, parent_hash, tokens))
        if not todo:
            return
        fn = self._extract_async_fn or self._extract_fn
        k, v = fn([p for p, _, _, _ in todo])
        for i, (_, seq_hash, parent_hash, tokens) in enumerate(todo):
            self._pending[seq_hash] = (parent_hash, tokens, k, v, i)
        if self._extract_async_fn is None or (
            len(self._pending) >= MAX_PENDING_OFFLOADS
        ):
            self.flush_offloads()

    def _store_entry(self, entry: BlockEntry) -> None:
        if self.host is not None:
            ok = self.host.put(entry)
            tier = "host"
        else:
            ok = self.disk.put(entry)
            tier = "disk"
        if ok:
            self.stats.offloaded_blocks += 1
            if self._on_tier_event is not None:
                self._on_tier_event(entry.seq_hash, entry.parent_hash, tier)

    def _complete(self, seq_hash: int) -> Optional[BlockEntry]:
        """Materialize one staged offload (np.asarray blocks only until the
        already-started device→host copy finishes)."""
        staged = self._pending.pop(seq_hash, None)
        if staged is None:
            return None
        parent_hash, tokens, k, v, i = staged
        k_host, v_host = np.asarray(k), np.asarray(v)
        if k_host is not k:
            # One extract batch backs many pending blocks: swap the
            # materialized host copies into the siblings so the device
            # transfer happens exactly once per batch.
            for h, t in list(self._pending.items()):
                if t[2] is k:
                    self._pending[h] = (t[0], t[1], k_host, v_host, t[4])
        return BlockEntry(
            seq_hash=seq_hash, parent_hash=parent_hash, tokens=tokens,
            k=np.ascontiguousarray(k_host[:, :, i]),
            v=np.ascontiguousarray(v_host[:, :, i]),
        )

    def flush_offloads(self) -> int:
        """Drain every staged offload into the tier hierarchy. The engine
        calls this once per step — transfers started at step N complete
        while step N+1 computes (the double buffer)."""
        n = 0
        for seq_hash in list(self._pending):
            entry = self._complete(seq_hash)
            if entry is not None:
                self._store_entry(entry)
                n += 1
        return n

    def allocate(self, n: int) -> Optional[list[int]]:
        """Pre-offload the eviction victims in ONE batched device read
        (instead of one sync per page inside the eviction loop); the
        per-page _pre_evict hook then sees them already in a lower tier."""
        if self._offload_enabled and n <= self.num_free:
            n_evict = n - min(self._free_slots(), n)
            if n_evict > 0:
                victims = self._peek_reclaimable(n_evict)  # LRU-first
                self._offload_pages(victims)
        return super().allocate(n)

    def _pre_evict(self, page: int) -> None:
        if self._offload_enabled:
            self._offload_pages([page])

    def demote(self, n: int) -> int:
        """Write-back demotion (kv_economy.TierPolicy): stage up to `n`
        of the coldest reclaimable pages into the tier hierarchy AHEAD
        of eviction. The device copies stay registered (still free prefix
        hits); when pool pressure later evicts them, the offload hook
        finds the bytes already tier-resident and the eviction costs
        nothing. Returns newly demoted blocks."""
        if not self._offload_enabled or n <= 0:
            return 0
        fresh: list[int] = []
        # peek past already-demoted victims so repeated ticks make
        # progress into the colder tail
        for page in self._peek_reclaimable(4 * n):
            meta = self._page_meta.get(page)
            if meta is None or self.tier_contains(meta[0]):
                continue
            fresh.append(page)
            if len(fresh) >= n:
                break
        if not fresh:
            return 0
        before = self.stats.offloaded_blocks
        self._offload_pages(fresh)
        self.flush_offloads()
        return self.stats.offloaded_blocks - before

    # -- onboard (prefix-hit continuation) ---------------------------------

    def _tier_get(self, seq_hash: int) -> Optional[BlockEntry]:
        # A block may still be in flight to the host tier: complete it on
        # demand (and keep it stored — the prefix may be hit again).
        staged = self._complete(seq_hash)
        if staged is not None:
            self._store_entry(staged)
            return staged
        if self.host is not None:
            e = self.host.get(seq_hash)
            if e is not None:
                self.tier_hits["host"] += 1
                return e
        if self.disk is not None:
            e = self.disk.get(seq_hash)
            if e is not None:
                self.tier_hits["disk"] += 1
            return e
        return None

    def tier_occupancy(self) -> dict[str, int]:
        """Blocks resident per lower tier (worker metrics frames; the
        Grafana "KV economy" row charts these)."""
        return {
            "host": len(self.host) if self.host is not None else 0,
            "disk": len(self.disk) if self.disk is not None else 0,
        }

    def tier_contains(self, seq_hash: int) -> bool:
        return (
            seq_hash in self._pending
            or (self.host is not None and seq_hash in self.host)
            or (self.disk is not None and seq_hash in self.disk)
        )

    def register_promoted(self, page, seq_hash, parent_hash, tokens) -> None:
        """Register + drop lower-tier copies (the block lives on device
        again, tier bytes track unique content) + count the onboard."""
        self.register(page, seq_hash, parent_hash, tokens)
        if self.host is not None:
            self.host.pop(seq_hash)
        if self.disk is not None:
            self.disk.pop(seq_hash)
        self.stats.onboarded_blocks += 1

    def resident_match_length(self, seq_hashes: Sequence[int]) -> int:
        """Leading blocks resident ANYWHERE locally (device or lower tier)
        — the probe remote onboarding uses to find where its need starts.
        No allocation, no LRU movement."""
        n = self.match_length(seq_hashes)
        for h in seq_hashes[n:]:
            if not self.tier_contains(h):
                break
            n += 1
        return n

    def lookup(self, seq_hashes: Sequence[int]) -> list[int]:
        pages = super().lookup(seq_hashes)
        if not self._offload_enabled or len(pages) >= len(seq_hashes):
            return pages
        # Continue the chain through the lower tiers.
        found: list[BlockEntry] = []
        for h in seq_hashes[len(pages):]:
            e = self._tier_get(h)
            if e is None:
                break
            found.append(e)
        if not found:
            return pages
        # Stack (= copy) the tier bytes BEFORE allocate(): allocate may
        # evict+offload device pages into the host tier, and a full host
        # tier then recycles LRU slabs — possibly the very slabs `found`
        # native-backed entries view (tiers.py HostTier.get).
        k = np.stack([e.k for e in found], axis=2)  # [L, Hkv, n, S, D]
        v = np.stack([e.v for e in found], axis=2)
        fresh = self.allocate(len(found))
        if fresh is None:
            return pages  # pool pressure — skip onboarding this time
        self._inject_fn(fresh, k, v)
        for page, e in zip(fresh, found):
            self.register_promoted(page, e.seq_hash, e.parent_hash, e.tokens)
        self.stats.hit_tokens += len(found) * self.page_size
        pages.extend(fresh)
        return pages

    # -- cache clearing ----------------------------------------------------

    def clear_cache(self) -> int:
        """/clear_kv_blocks semantics: drop cached content in ALL tiers,
        including offloads still in flight."""
        prev, self._offload_enabled = self._offload_enabled, False
        try:
            n = super().clear_cache()
        finally:
            self._offload_enabled = prev
        n += len(self._pending)
        self._pending.clear()
        if self.host is not None:
            n += len(self.host)
            self.host.clear()
        if self.disk is not None:
            n += len(self.disk)
            self.disk.clear()
        return n

"""Mixtral-style MoE decoder with GShard dispatch, ep-sharded experts.

The reference delegates expert parallelism to its engines (SGLang DeepEP
flags — SURVEY.md §2.9 "EP — engine-delegated"); here MoE is first-class
TPU: expert weights are stacked on a leading E axis sharded over the mesh's
"ep" axis, and routing is the capacity-based one-hot dispatch/combine
einsum formulation (GShard / Switch) — static shapes, MXU-shaped batched
matmuls, with XLA inserting the ep all-to-alls from the shardings alone.

Attention / norms / rope / the paged KV cache are shared with the Llama
module (models/llama.py); only the FFN differs:

    router: logits = x @ w_router            [N, E]
    gates:  softmax, top-k, renormalize      (Mixtral semantics)
    dispatch/combine: one-hot [N, E, C] einsums with per-expert capacity C
    experts: SwiGLU with weights [E, H, I] / [E, I, H]

Tokens over capacity are dropped (their expert contribution is zero and
the residual stream carries them) — the standard static-shape trade; set
capacity_factor high enough (tests use >= E/top_k) for exactness.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.models import llama as llama_mod
from dynamo_tpu.models.llama import (
    KVPages,
    LlamaConfig,
    _w,
    attention_block,
    land_staged_kv,
    quantize_channelwise_int8,
    rms_norm,
)

#: per-layer 2D weights int8 covers (w_router stays in the base dtype)
_QUANT_ATTN = ("wq", "wk", "wv", "wo")
_QUANT_EXPERTS = ("we_gate", "we_up", "we_down")  # [L, E, in, out]


def quantize_params_int8(params: dict) -> dict:
    """Weight-only int8 over the MoE layout: attention projections via
    llama's per-layer scheme, expert stacks per (layer, expert)."""
    out = dict(params)
    layers = dict(params["layers"])
    if any(
        layers.get(n) is not None and layers[n].dtype == jnp.int8
        for n in _QUANT_ATTN + _QUANT_EXPERTS
    ):
        raise ValueError("params are already int8-quantized")
    for name in _QUANT_ATTN:
        q, sc = jax.lax.map(quantize_channelwise_int8, layers[name])
        layers[name] = q
        layers[name + "_scale"] = sc
    for name in _QUANT_EXPERTS:
        q, sc = jax.lax.map(
            lambda we: jax.lax.map(quantize_channelwise_int8, we),
            layers[name],
        )
        layers[name] = q
        layers[name + "_scale"] = sc
    out["layers"] = layers
    return out


@dataclass(frozen=True)
class MoeConfig:
    """Mixtral shape: Llama attention + MoE FFN. The Qwen3-MoE family is
    the same block with qk_norm on the base, a separate expert MLP width,
    different HF tensor names, and the norm_topk_prob flag HF documents
    as "only diff with mixtral"."""

    base: LlamaConfig = field(default_factory=LlamaConfig)
    num_experts: int = 8
    top_k: int = 2
    #: per-expert capacity = ceil(top_k * tokens / num_experts) * factor
    capacity_factor: float = 2.0
    #: renormalize the top-k weights to sum 1 (Mixtral always does;
    #: Qwen3-MoE gates it on config.norm_topk_prob)
    norm_topk_prob: bool = True
    #: expert MLP width (None: base.intermediate_size — Mixtral)
    expert_intermediate_size: Optional[int] = None
    #: HF tensor naming: "mixtral" (block_sparse_moe.experts.N.w1/w2/w3),
    #: "qwen3_moe" (mlp.experts.N.gate/up/down_proj), or "llama4"
    #: (feed_forward.experts fused gate_up_proj + shared_expert)
    hf_naming: str = "mixtral"
    #: gate semantics: "softmax" (Mixtral/Qwen3: softmax probs, output
    #: combine) or "llama4" (sigmoid of the top-k LOGITS, scaling the
    #: expert INPUT — expert(x·s), not s·expert(x))
    gate: str = "softmax"
    #: Llama-4: a dense expert-width MLP added to every token's output
    shared_expert: bool = False
    #: GPT-OSS: the router linear carries a bias (params "b_router")
    router_bias: bool = False
    #: expert MLP: "swiglu" (silu(gate)·up) or "gpt_oss" (clamped GLU
    #: gate·σ(1.702·gate)·(up+1), with per-expert biases)
    expert_mlp: str = "swiglu"
    swiglu_limit: float = 7.0

    @property
    def expert_width(self) -> int:
        return self.expert_intermediate_size or self.base.intermediate_size

    @staticmethod
    def mixtral_8x7b() -> "MoeConfig":
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
                rope_theta=1000000.0,
            ),
            num_experts=8, top_k=2,
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MoeConfig":
        return MoeConfig(
            base=replace(LlamaConfig.tiny(vocab_size), intermediate_size=32),
            num_experts=4, top_k=2,
        )

    @staticmethod
    def qwen3_moe_30b() -> "MoeConfig":
        """Qwen3-30B-A3B: qk-norm attention + 128 experts (top-8,
        renormalized), expert width 768."""
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=151936, hidden_size=2048, intermediate_size=6144,
                num_layers=48, num_heads=32, num_kv_heads=4, head_dim=128,
                rope_theta=1000000.0, rms_norm_eps=1e-6, qk_norm=True,
            ),
            num_experts=128, top_k=8, norm_topk_prob=True,
            expert_intermediate_size=768, hf_naming="qwen3_moe",
        )

    @staticmethod
    def llama4_scout_text() -> "MoeConfig":
        """Llama-4-Scout (17B-A/16E) language model: interleaved rope
        with llama3 NTK scaling, NoPE every 4th layer with temperature
        tuning, chunked attention (8192) on rope layers, weightless L2
        q/k norm, sigmoid top-1 input-scaled routing + shared expert."""
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=202048, hidden_size=5120,
                intermediate_size=8192, num_layers=48, num_heads=40,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                rope_scaling_factor=8.0, rope_low_freq_factor=1.0,
                rope_high_freq_factor=4.0, rope_original_max_position=8192,
                rope_interleaved=True, nope_every=4, qk_l2_norm=True,
                attn_temperature_tuning=True, attention_chunk=8192,
            ),
            num_experts=16, top_k=1, norm_topk_prob=False,
            hf_naming="llama4", gate="llama4", shared_expert=True,
        )

    @staticmethod
    def llama4_tiny(vocab_size: int = 256) -> "MoeConfig":
        """Unit-test scale Llama-4 shape: 4 layers so the every-4th-NoPE
        pattern appears; chunk 4 so chunked masking bites at T=12."""
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=vocab_size, hidden_size=64,
                intermediate_size=32, num_layers=4, num_heads=4,
                num_kv_heads=2, head_dim=16, rope_theta=10000.0,
                rope_interleaved=True, nope_every=4, qk_l2_norm=True,
                attn_temperature_tuning=True, attn_floor_scale=4.0,
                attention_chunk=4, dtype=jnp.float32,
            ),
            num_experts=4, top_k=1, norm_topk_prob=False,
            hf_naming="llama4", gate="llama4", shared_expert=True,
            # test-scale: room for every token on ONE expert, so chunked
            # decode continuation is capacity-drop-free and exactly
            # reproduces full prefill
            capacity_factor=4.0,
        )

    @staticmethod
    def gpt_oss_20b() -> "MoeConfig":
        """GPT-OSS-20B: alternating sliding(128)/full attention with
        learned per-head sinks, YaRN x32 rope, biased qkv/o projections,
        32 experts top-4 (softmax-over-top-k) with biased clamped-GLU
        MLPs. Released MXFP4 checkpoints load via their HF bf16
        dequantization."""
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=201088, hidden_size=2880,
                intermediate_size=2880, num_layers=24, num_heads=64,
                num_kv_heads=8, head_dim=64, rope_theta=150000.0,
                rms_norm_eps=1e-5, attention_bias=True,
                attention_out_bias=True, attn_sinks=True,
                sliding_window=128, sliding_window_every=2,
                rope_yarn_factor=32.0, rope_yarn_beta_fast=32.0,
                rope_yarn_beta_slow=1.0, rope_yarn_truncate=False,
                rope_original_max_position=4096,
            ),
            num_experts=32, top_k=4, norm_topk_prob=True,
            hf_naming="gpt_oss", router_bias=True, expert_mlp="gpt_oss",
        )

    @staticmethod
    def gpt_oss_tiny(vocab_size: int = 256) -> "MoeConfig":
        """Unit-test scale GPT-OSS shape: 4 layers (two sliding, two
        full), sinks, yarn, biases everywhere, clamped-GLU experts."""
        return MoeConfig(
            base=LlamaConfig(
                vocab_size=vocab_size, hidden_size=64,
                intermediate_size=32, num_layers=4, num_heads=4,
                num_kv_heads=2, head_dim=16, rope_theta=10000.0,
                rms_norm_eps=1e-5, attention_bias=True,
                attention_out_bias=True, attn_sinks=True,
                sliding_window=8, sliding_window_every=2,
                rope_yarn_factor=4.0, rope_yarn_truncate=False,
                rope_original_max_position=32, dtype=jnp.float32,
            ),
            num_experts=4, top_k=2, norm_topk_prob=True,
            hf_naming="gpt_oss", router_bias=True, expert_mlp="gpt_oss",
            capacity_factor=4.0,
        )

    @staticmethod
    def from_hf_config(hf: dict) -> "MoeConfig":
        base = LlamaConfig.from_hf_config(hf)
        qwen3_moe = (
            hf.get("model_type") == "qwen3_moe"
            or "Qwen3MoeForCausalLM" in (hf.get("architectures") or [])
        )
        if qwen3_moe:
            if hf.get("mlp_only_layers") or hf.get("decoder_sparse_step", 1) != 1:
                raise ValueError(
                    "qwen3_moe dense-layer interleaving (mlp_only_layers/"
                    "decoder_sparse_step) is not implemented"
                )
            return MoeConfig(
                base=base,
                num_experts=int(hf.get("num_experts", 128)),
                top_k=int(hf.get("num_experts_per_tok", 8)),
                norm_topk_prob=bool(hf.get("norm_topk_prob", False)),
                expert_intermediate_size=int(
                    hf.get("moe_intermediate_size")
                    or hf["intermediate_size"]
                ),
                hf_naming="qwen3_moe",
            )
        gpt_oss = (
            hf.get("model_type") == "gpt_oss"
            or "GptOssForCausalLM" in (hf.get("architectures") or [])
        )
        if gpt_oss:
            return MoeConfig(
                base=base,
                num_experts=int(hf.get("num_local_experts", 32)),
                top_k=int(hf.get("num_experts_per_tok", 4)),
                norm_topk_prob=True,
                hf_naming="gpt_oss",
                router_bias=True,
                expert_mlp="gpt_oss",
                swiglu_limit=float(hf.get("swiglu_limit") or 7.0),
            )
        llama4 = (
            hf.get("model_type") == "llama4_text"
            or "Llama4ForCausalLM" in (hf.get("architectures") or [])
        )
        if llama4:
            if int(hf.get("interleave_moe_layer_step", 1)) != 1:
                raise ValueError(
                    "llama4 dense/MoE layer interleaving "
                    "(interleave_moe_layer_step > 1, Maverick) is not "
                    "implemented — Scout-style all-MoE only"
                )
            return MoeConfig(
                base=base,
                num_experts=int(hf.get("num_local_experts", 16)),
                top_k=int(hf.get("num_experts_per_tok", 1)),
                norm_topk_prob=False,
                hf_naming="llama4",
                gate="llama4",
                shared_expert=True,
            )
        return MoeConfig(
            base=base,
            num_experts=int(hf.get("num_local_experts", 8)),
            top_k=int(hf.get("num_experts_per_tok", 2)),
        )


def _capacity(cfg: MoeConfig, num_tokens: int) -> int:
    per = -(-cfg.top_k * num_tokens // cfg.num_experts)
    return max(1, int(per * cfg.capacity_factor))


def init_params(key: jax.Array, cfg: MoeConfig) -> dict:
    """Llama params with the dense FFN replaced by router + stacked experts."""
    base = llama_mod.init_params(key, cfg.base)
    h, i = cfg.base.hidden_size, cfg.expert_width
    L, E = cfg.base.num_layers, cfg.num_experts
    keys = jax.random.split(jax.random.fold_in(key, 1), 4)

    def dense(k, shape, fan_in):
        import math

        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(
            cfg.base.dtype
        )

    layers = base["layers"]
    for name in ("w_gate", "w_up", "w_down"):
        del layers[name]
    layers["w_router"] = dense(keys[0], (L, h, E), h)
    layers["we_gate"] = dense(keys[1], (L, E, h, i), h)
    layers["we_up"] = dense(keys[2], (L, E, h, i), h)
    layers["we_down"] = dense(keys[3], (L, E, i, h), i)
    if cfg.shared_expert:
        sk = jax.random.split(jax.random.fold_in(key, 2), 3)
        layers["ws_gate"] = dense(sk[0], (L, h, i), h)
        layers["ws_up"] = dense(sk[1], (L, h, i), h)
        layers["ws_down"] = dense(sk[2], (L, i, h), i)
    if cfg.router_bias:
        layers["b_router"] = jnp.zeros((L, E), cfg.base.dtype)
    if cfg.expert_mlp == "gpt_oss":
        layers["be_gate"] = jnp.zeros((L, E, i), jnp.float32)
        layers["be_up"] = jnp.zeros((L, E, i), jnp.float32)
        layers["be_down"] = jnp.zeros((L, E, h), jnp.float32)
    if cfg.base.attn_sinks:
        layers["sinks"] = jnp.zeros(
            (L, cfg.base.num_heads), cfg.base.dtype
        )
    if cfg.base.attention_out_bias:
        layers["bo"] = jnp.zeros((L, h), cfg.base.dtype)
    return base


def params_from_torch_state_dict(state_dict, cfg: MoeConfig) -> dict:
    """HF Mixtral state_dict -> our pytree (experts stacked on E)."""
    import numpy as np

    def t(name):
        return np.asarray(state_dict[name].to("cpu").float().numpy())

    L, E = cfg.base.num_layers, cfg.num_experts
    dt = cfg.base.dtype

    def stack(fmt, transpose=True):
        ws = [t(fmt.format(l)) for l in range(L)]
        ws = [w.T if transpose else w for w in ws]
        return jnp.asarray(np.stack(ws), dt)

    def stack_experts(fmt):
        # [L, E, in, out]: HF stores [out, in] per expert
        return jnp.asarray(
            np.stack(
                [
                    np.stack([t(fmt.format(l, e)).T for e in range(E)])
                    for l in range(L)
                ]
            ),
            dt,
        )

    if cfg.hf_naming == "qwen3_moe":
        moe_prefix = "model.layers.{}.mlp"
        e_gate, e_up, e_down = "gate_proj", "up_proj", "down_proj"
    elif cfg.hf_naming in ("llama4", "gpt_oss"):
        moe_prefix = (
            "model.layers.{}.feed_forward"
            if cfg.hf_naming == "llama4"
            else "model.layers.{}.mlp"
        )
        e_gate = e_up = e_down = None  # fused 3D tensors, handled below
    else:
        moe_prefix = "model.layers.{}.block_sparse_moe"
        e_gate, e_up, e_down = "w1", "w3", "w2"

    def fused_halves(name_fmt, bias=False):
        """Split a fused [E, H|1, 2I] gate_up tensor per layer into our
        (gate, up) pair, converting each big tensor ONCE. llama4 fuses as
        halves [gate | up]; gpt_oss INTERLEAVES (::2 gate, 1::2 up)."""
        gus = [t(name_fmt.format(l)) for l in range(L)]
        if cfg.hf_naming == "gpt_oss":
            gs = [g[..., 0::2] for g in gus]
            us = [g[..., 1::2] for g in gus]
        else:
            gs = [g[..., : cfg.expert_width] for g in gus]
            us = [g[..., cfg.expert_width :] for g in gus]
        cast = jnp.float32 if bias else dt
        return (
            jnp.asarray(np.stack(gs), cast),
            jnp.asarray(np.stack(us), cast),
        )

    params = {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), dt),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight", False
            ),
            **(
                {
                    "q_norm": stack(
                        "model.layers.{}.self_attn.q_norm.weight", False
                    ),
                    "k_norm": stack(
                        "model.layers.{}.self_attn.k_norm.weight", False
                    ),
                }
                if cfg.base.qk_norm
                else {}
            ),
            **(
                {
                    "bq": stack("model.layers.{}.self_attn.q_proj.bias", False),
                    "bk": stack("model.layers.{}.self_attn.k_proj.bias", False),
                    "bv": stack("model.layers.{}.self_attn.v_proj.bias", False),
                }
                if cfg.base.attention_bias
                else {}
            ),
            **(
                {
                    "bo": stack(
                        "model.layers.{}.self_attn.o_proj.bias", False
                    )
                }
                if cfg.base.attention_out_bias
                else {}
            ),
            **(
                {"sinks": stack("model.layers.{}.self_attn.sinks", False)}
                if cfg.base.attn_sinks
                else {}
            ),
            **(
                {
                    # Llama-4 / GPT-OSS: experts FUSED as [E, H, 2I]
                    # gate_up (already [in, out] orientation) + [E, I, H]
                    # down; router named "router"; per-family extras below
                    "w_router": stack(moe_prefix + ".router.weight"),
                    **dict(
                        zip(
                            ("we_gate", "we_up"),
                            fused_halves(
                                moe_prefix + ".experts.gate_up_proj"
                            ),
                        )
                    ),
                    "we_down": jnp.asarray(
                        np.stack(
                            [
                                t(
                                    moe_prefix.format(l)
                                    + ".experts.down_proj"
                                )
                                for l in range(L)
                            ]
                        ),
                        dt,
                    ),
                    **(
                        {
                            "ws_gate": stack(
                                moe_prefix
                                + ".shared_expert.gate_proj.weight"
                            ),
                            "ws_up": stack(
                                moe_prefix + ".shared_expert.up_proj.weight"
                            ),
                            "ws_down": stack(
                                moe_prefix
                                + ".shared_expert.down_proj.weight"
                            ),
                        }
                        if cfg.shared_expert
                        else {}
                    ),
                    **(
                        {
                            "b_router": stack(
                                moe_prefix + ".router.bias", False
                            ),
                            **dict(
                                zip(
                                    ("be_gate", "be_up"),
                                    fused_halves(
                                        moe_prefix
                                        + ".experts.gate_up_proj_bias",
                                        bias=True,
                                    ),
                                )
                            ),
                            "be_down": jnp.asarray(
                                np.stack(
                                    [
                                        t(
                                            moe_prefix.format(l)
                                            + ".experts.down_proj_bias"
                                        )
                                        for l in range(L)
                                    ]
                                ),
                                jnp.float32,
                            ),
                        }
                        if cfg.hf_naming == "gpt_oss"
                        else {}
                    ),
                }
                if cfg.hf_naming in ("llama4", "gpt_oss")
                else {
                    "w_router": stack(moe_prefix + ".gate.weight"),
                    "we_gate": stack_experts(
                        moe_prefix + ".experts.{}." + e_gate + ".weight"
                    ),
                    "we_up": stack_experts(
                        moe_prefix + ".experts.{}." + e_up + ".weight"
                    ),
                    "we_down": stack_experts(
                        moe_prefix + ".experts.{}." + e_down + ".weight"
                    ),
                }
            ),
        },
        "final_norm": jnp.asarray(t("model.norm.weight"), dt),
        "lm_head": jnp.asarray(t("lm_head.weight").T, dt),
    }
    return params


def top_k_gating(
    logits: jax.Array,  # [N, E] f32
    top_k: int,
    capacity: int,
    norm_topk_prob: bool = True,
    gate: str = "softmax",
) -> tuple[jax.Array, jax.Array]:
    """GShard dispatch/combine tensors, Mixtral gate semantics (Qwen3-MoE
    = the same with renormalization gated on norm_topk_prob; Llama-4 =
    sigmoid of the raw top-k LOGITS, no renormalization).

    Returns (dispatch [N, E, C] in {0,1}, combine [N, E, C] f32). Slot-major
    position assignment: every token's 1st choice is placed before any 2nd
    choice, so capacity pressure drops the weakest assignments first.
    """
    n, e = logits.shape
    if gate == "llama4":
        vals, idx = lax.top_k(logits, top_k)  # raw logits
        vals = jax.nn.sigmoid(vals)
    else:
        probs = jax.nn.softmax(logits, axis=-1)
        vals, idx = lax.top_k(probs, top_k)  # [N, k]
        if norm_topk_prob:
            vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [N, k, E]
    flat = onehot.transpose(1, 0, 2).reshape(top_k * n, e)  # slot-major
    pos_flat = jnp.cumsum(flat, axis=0) - flat
    pos = (pos_flat * flat).sum(-1).reshape(top_k, n).T  # [N, k]

    keep = pos < capacity
    weight = vals * keep
    disp = (
        onehot[..., None]
        * jax.nn.one_hot(pos, capacity, dtype=jnp.int32)[..., None, :]
    )  # [N, k, E, C]
    disp = disp * keep[..., None, None].astype(jnp.int32)
    dispatch = disp.sum(axis=1)
    combine = (disp * weight[..., None, None]).sum(axis=1)
    return dispatch, combine.astype(jnp.float32)


def moe_ffn(x: jax.Array, lp: dict, cfg: MoeConfig) -> jax.Array:
    """x: [B, T, H] post-norm -> MoE output [B, T, H]."""
    b, t, h = x.shape
    n = b * t
    xf = x.reshape(n, h)
    logits = (xf @ lp["w_router"]).astype(jnp.float32)  # [N, E]
    if cfg.router_bias:
        logits = logits + lp["b_router"].astype(jnp.float32)
    dispatch, combine = top_k_gating(
        logits, cfg.top_k, _capacity(cfg, n),
        norm_topk_prob=cfg.norm_topk_prob, gate=cfg.gate,
    )
    if cfg.gate == "llama4":
        # Llama-4 scales the expert INPUT by the sigmoid score —
        # expert(x·s), not s·expert(x) — and sums outputs unweighted
        in_w, out_w = combine.astype(x.dtype), dispatch.astype(jnp.float32)
    else:
        in_w, out_w = dispatch.astype(x.dtype), combine
    expert_in = jnp.einsum("nh,nec->ech", xf, in_w)  # [E, C, H]
    gate_raw = jnp.einsum(
        "ech,ehi->eci", expert_in, _w(lp, "we_gate", x.dtype)
    ).astype(jnp.float32)
    up = jnp.einsum(
        "ech,ehi->eci", expert_in, _w(lp, "we_up", x.dtype)
    ).astype(jnp.float32)
    if cfg.expert_mlp == "gpt_oss":
        # clamped GLU with per-expert biases: g·σ(1.702g)·(u+1); padding
        # capacity slots produce bias-driven outputs but carry combine
        # weight 0, so they vanish in the weighted sum
        lim = cfg.swiglu_limit
        g = jnp.minimum(gate_raw + lp["be_gate"][:, None, :], lim)
        u = jnp.clip(up + lp["be_up"][:, None, :], -lim, lim)
        act = (u + 1.0) * (g * jax.nn.sigmoid(1.702 * g))
        expert_out = (
            jnp.einsum(
                "eci,eih->ech", act.astype(x.dtype),
                _w(lp, "we_down", x.dtype),
            )
            + lp["be_down"][:, None, :]
        )  # [E, C, H]
    else:
        gate = jax.nn.silu(gate_raw)
        expert_out = jnp.einsum(
            "eci,eih->ech", (gate * up).astype(x.dtype),
            _w(lp, "we_down", x.dtype),
        )  # [E, C, H]
    out = jnp.einsum(
        "ech,nec->nh", expert_out.astype(jnp.float32), out_w
    )
    if cfg.shared_expert:
        sg = jax.nn.silu(
            jnp.einsum(
                "nh,hi->ni", xf, _w(lp, "ws_gate", x.dtype)
            ).astype(jnp.float32)
        )
        su = jnp.einsum(
            "nh,hi->ni", xf, _w(lp, "ws_up", x.dtype)
        ).astype(jnp.float32)
        out = out + jnp.einsum(
            "ni,ih->nh", (sg * su).astype(x.dtype),
            _w(lp, "ws_down", x.dtype),
        ).astype(jnp.float32)
    return out.reshape(b, t, h).astype(x.dtype)


def forward_hidden(
    params: dict,
    cfg: MoeConfig,
    tokens: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
    kv: KVPages,
    page_tables: jax.Array,
    mm_embeds=None,
    mm_mask=None,
    first_chunk: bool = False,
    mesh=None,
) -> tuple[jax.Array, KVPages]:
    """Same contract as llama.forward_hidden (engine-compatible)."""
    bc = cfg.base
    h = params["embed"][tokens].astype(bc.dtype)
    if mm_embeds is not None:
        h = jnp.where(mm_mask[..., None], mm_embeds.astype(bc.dtype), h)

    decode_work = llama_mod.maybe_decode_work(
        bc, tokens, positions, kv, page_tables
    )

    def layer(carry, xs):
        h, kvc = carry
        lp, li = xs
        x = rms_norm(h, lp["attn_norm"], bc.rms_norm_eps)
        b, t, _ = x.shape
        q = llama_mod._mm(x, lp, "wq", bc.dtype).reshape(
            b, t, bc.num_heads, bc.head_dim
        )
        k = llama_mod._mm(x, lp, "wk", bc.dtype).reshape(
            b, t, bc.num_kv_heads, bc.head_dim
        )
        v = llama_mod._mm(x, lp, "wv", bc.dtype).reshape(
            b, t, bc.num_kv_heads, bc.head_dim
        )
        if bc.attention_bias:  # GPT-OSS: qkv biases
            q = q + lp["bq"].reshape(bc.num_heads, bc.head_dim)
            k = k + lp["bk"].reshape(bc.num_kv_heads, bc.head_dim)
            v = v + lp["bv"].reshape(bc.num_kv_heads, bc.head_dim)
        if bc.qk_norm:  # Qwen3-MoE: per-head RMSNorm pre-rope
            q = rms_norm(q, lp["q_norm"], bc.rms_norm_eps)
            k = rms_norm(k, lp["k_norm"], bc.rms_norm_eps)
        attn, kvc, staged = attention_block(
            q, k, v, kvc, li, page_tables, positions, valid, bc,
            first_chunk=first_chunk, mesh=mesh, decode_work=decode_work,
            sinks=lp["sinks"] if bc.attn_sinks else None,
        )
        attn_out = llama_mod._mm(attn, lp, "wo", bc.dtype)
        if bc.attention_out_bias:
            attn_out = attn_out + lp["bo"]
        h = h + attn_out
        x = rms_norm(h, lp["mlp_norm"], bc.rms_norm_eps)
        h = h + moe_ffn(x, lp, cfg)
        return (h, kvc), staged

    (h, kv_new), staged = lax.scan(
        layer,
        (h, kv),
        (params["layers"], jnp.arange(bc.num_layers, dtype=jnp.int32)),
    )
    kv_new = land_staged_kv(
        kv_new, staged, page_tables, positions, valid, mesh=mesh
    )
    h = rms_norm(h, params["final_norm"], bc.rms_norm_eps)
    return h, kv_new


def forward(params, cfg: MoeConfig, tokens, positions, valid, kv, page_tables):
    h, kv = forward_hidden(params, cfg, tokens, positions, valid, kv, page_tables)
    return llama_mod.compute_logits(params, cfg.base, h), kv


def moe_logical_axes(cfg: MoeConfig, quantized: bool = False) -> dict:
    """Logical axis names (parallel/logical.py): llama's per-layer
    names minus the dense MLP, plus routed-expert weights [L, E, in,
    out] whose E dim is "expert" (EP placement) and whose intermediate
    dim is "mlp" (so experts shard ep x tp); the router and shared
    expert stay dense-style. Quantized scales ride their weight's
    output-dim name (contraction-sharded we_down keeps an expert-only
    scale)."""
    from dynamo_tpu.models.llama import llama_logical_axes
    from dynamo_tpu.parallel.logical import L

    axes = llama_logical_axes(cfg.base, quantized=quantized)
    layers = axes["layers"]
    for name in ("w_gate", "w_up", "w_down"):
        del layers[name]
        layers.pop(name + "_scale", None)
    layers["w_router"] = L("layers", None, None)
    layers["we_gate"] = L("layers", "expert", None, "mlp")
    layers["we_up"] = L("layers", "expert", None, "mlp")
    layers["we_down"] = L("layers", "expert", "mlp", None)
    if quantized:
        layers["we_gate_scale"] = L("layers", "expert", None, "mlp")
        layers["we_up_scale"] = L("layers", "expert", None, "mlp")
        layers["we_down_scale"] = L("layers", "expert", None, None)
    if cfg.shared_expert:  # Llama-4: dense MLP beside the experts
        layers["ws_gate"] = L("layers", None, "mlp")
        layers["ws_up"] = L("layers", None, "mlp")
        layers["ws_down"] = L("layers", "mlp", None)
    if cfg.router_bias:  # GPT-OSS
        layers["b_router"] = L("layers", None)
    if cfg.expert_mlp == "gpt_oss":  # per-expert biases ride their dims
        layers["be_gate"] = L("layers", "expert", "mlp")
        layers["be_up"] = L("layers", "expert", "mlp")
        layers["be_down"] = L("layers", "expert", None)
    if cfg.base.attn_sinks:  # per-head logits shard with the heads
        layers["sinks"] = L("layers", "heads")
    if cfg.base.attention_out_bias:  # o-proj output dim is unsharded
        layers["bo"] = L("layers", None)
    return axes


def moe_param_specs(cfg: MoeConfig, quantized: bool = False, rules=None):
    """PartitionSpecs for moe params: `moe_logical_axes` resolved
    through the logical-axis rule table (default table when `rules` is
    None)."""
    from dynamo_tpu.parallel.logical import resolve

    return resolve(moe_logical_axes(cfg, quantized=quantized), rules)
